"""paddle.fluid.layers — the 1.x functional surface.

Parity: python/paddle/fluid/layers/ (~300 public names across nn.py,
tensor.py, ops.py, loss.py, detection.py, control_flow.py,
sequence_lod.py, rnn.py, metric_op.py).  Three tiers:

* ops whose semantics survive eagerly are implemented: thin wrappers
  translating 1.x argument names (``input``/``dim``/``keep_dim``...) to
  the 2.0 implementations that already exist in paddle_tpu.tensor /
  nn.functional — no second implementation, just the old calling
  convention;
* parameter-creating op-builders (fc, conv2d, batch_norm, ...) raise
  ``UnimplementedError`` naming the Layer-class replacement — exactly
  the set that also could not run in the reference's dygraph mode;
* LoD-dependent sequence ops point at their dense/padded counterparts
  (SURVEY §7g: dense padding + masks replace LoD).

Every name of the reference module resolves: implemented, or an
instructive error — never a bare AttributeError on real 1.x API.
"""
from __future__ import annotations

from builtins import range as _range

import jax
import jax.numpy as jnp

import paddle_tpu as _p
from paddle_tpu import nn as _nn
from paddle_tpu.nn import functional as _F
from ...framework.errors import UnimplementedError

# -- direct re-exports: same name, compatible signature -----------------
from paddle_tpu.tensor import (  # noqa: F401
    cast, concat, assign, argmin, argmax, argsort, ones, zeros, reverse,
    isfinite, linspace, zeros_like, ones_like, diag, eye, triu,
    gather, gather_nd, scatter, scatter_nd_add, scatter_nd, slice,
    strided_slice, shape, rank, sign, where, unbind, unique,
    shard_index, stack, unstack, flatten, squeeze, unsqueeze, transpose,
    clip, log, pow, abs, exp, sqrt, rsqrt, ceil,
    floor, cos, sin, tanh, round, reciprocal, square, cumsum,
    less_than, less_equal, greater_than, greater_equal,
    equal, not_equal, logical_and, logical_or, logical_xor, logical_not,
    is_empty, mean,
)
from paddle_tpu import crop_tensor, increment  # noqa: F401
from paddle_tpu.nn.functional import (  # noqa: F401
    relu, selu, elu, relu6, swish, mish, prelu, leaky_relu, maxout,
    log_loss, dice_loss, npair_loss, mse_loss, square_error_cost,
    softmax_with_cross_entropy, label_smooth,
)
from paddle_tpu.nn.functional import (  # noqa: F401
    row_conv, gather_tree, iou_similarity, ssd_loss, prior_box,
    bipartite_match, target_assign, detection_output, box_coder,
    box_clip, multiclass_nms, sequence_mask, linear_chain_crf,
    crf_decoding, pixel_shuffle, unfold, temporal_shift,
    roi_align, roi_pool, sigmoid_focal_loss, yolo_box, yolov3_loss,
    matrix_nms, density_prior_box, anchor_generator, generate_proposals,
    box_decoder_and_assign, distribute_fpn_proposals, collect_fpn_proposals,
    psroi_pool, locality_aware_nms,
)
from paddle_tpu.nn import (  # noqa: F401
    BeamSearchDecoder, Decoder, dynamic_decode, RNNCellBase as RNNCell,
    GRUCell, LSTMCell, clip_by_norm,
)
from paddle_tpu.metric import accuracy  # noqa: F401
from ...static import Print, py_func, create_parameter, create_global_var  # noqa: F401


# -- 1.x calling-convention wrappers ------------------------------------
def _act(out, act):
    if act:
        fn = getattr(_F, act, None)
        if fn is None:
            raise UnimplementedError(f"activation {act!r} unknown")
        return fn(out)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _p.sum(input, axis=dim, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _p.mean(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _p.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _p.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _p.prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _p.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _p.any(input, axis=dim, keepdim=keep_dim)


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _F.softmax(input, axis=axis)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = x @ y
    return out if alpha == 1.0 else out * alpha


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """1.x mul op: flatten x/y to 2-D around the given split dims then
    matmul (ref: operators/mul_op.cc)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    xs = x.reshape((int(jnp.prod(jnp.asarray(x.shape[:x_num_col_dims]))), -1))
    ys = y.reshape((int(jnp.prod(jnp.asarray(y.shape[:y_num_col_dims]))), -1))
    out = xs @ ys
    return out.reshape(x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:])


def topk(input, k, name=None):
    return _p.topk(input, k)


def one_hot(input, depth, allow_out_of_range=False):
    return _F.one_hot(jnp.asarray(input).squeeze(-1)
                      if jnp.asarray(input).ndim > 1
                      and jnp.asarray(input).shape[-1] == 1 else input, depth)


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    from ...framework.dtype import convert_dtype

    return jnp.full(tuple(int(s) for s in shape), value, convert_dtype(dtype))


def create_tensor(dtype, name=None, persistable=False):
    from ...framework.dtype import convert_dtype

    return jnp.zeros((), convert_dtype(dtype))


def sums(input, out=None):
    return _p.add_n(list(input))


def range(start, end, step, dtype, name=None):
    from ...framework.dtype import convert_dtype

    return jnp.arange(_scalar(start), _scalar(end), _scalar(step),
                      convert_dtype(dtype))


def _scalar(v):
    import numpy as np

    return v if isinstance(v, (int, float)) else np.asarray(v).item()


def has_inf(x):
    return jnp.isinf(jnp.asarray(x)).any()


def has_nan(x):
    return jnp.isnan(jnp.asarray(x)).any()


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return _F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def split(input, num_or_sections, dim=-1, name=None):
    return _p.split(input, num_or_sections, axis=dim)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    return _act(_p.reshape(x, shape), act)


def expand(x, expand_times, name=None):
    return _p.tile(x, expand_times)


def expand_as(x, target_tensor, name=None):
    return _p.expand_as(x, target_tensor)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = jnp.asarray(x)
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return _act(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.add), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.subtract), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.multiply), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.divide), act)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.maximum), act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.minimum), act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.power), act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.mod), act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.floor_divide), act)


def _bcast(x, y, axis, op):
    """1.x elementwise broadcast: y's dims align to x starting at
    ``axis`` (ref: operators/elementwise/elementwise_op_function.h)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype)
    if axis != -1 and y.ndim < x.ndim:
        y = y.reshape(y.shape + (1,) * (x.ndim - axis - y.ndim))
    return op(x, y)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _F.normalize(x, p=2, axis=axis, epsilon=epsilon)


def cos_sim(X, Y):
    out = _F.cosine_similarity(X, Y, axis=-1)
    return jnp.asarray(out)[..., None]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return _F.cross_entropy(input, label, soft_label=soft_label,
                            ignore_index=ignore_index, reduction="none",
                            use_softmax=False)[..., None]


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    out = _F.binary_cross_entropy_with_logits(
        x, jnp.asarray(label, jnp.asarray(x).dtype), reduction="none")
    mask = jnp.asarray(label) != ignore_index
    out = jnp.where(mask, out, 0.0)
    if normalize:
        out = out / jnp.maximum(mask.sum(), 1)
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    return _F.kl_div(x, target, reduction=reduction)


def huber_loss(input, label, delta):
    return _F.smooth_l1_loss(input, label, reduction="none", delta=delta)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """1.x smooth_l1 op (ref: operators/smooth_l1_loss_op.cc): per-row
    summed smooth-L1 with optional elementwise weights; sigma scales the
    quadratic window."""
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype)
    s2 = (1.0 if sigma is None else float(sigma)) ** 2
    d = (x - y) * (1.0 if inside_weight is None
                   else jnp.asarray(inside_weight, x.dtype))
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if outside_weight is not None:
        loss = loss * jnp.asarray(outside_weight, x.dtype)
    return loss.reshape(loss.shape[0], -1).sum(-1, keepdims=True)


def mean_iou(input, label, num_classes):
    from paddle_tpu.metric import mean_iou as _miou

    return _miou(input, label, num_classes)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    from paddle_tpu.metric import chunk_eval as _ce

    return _ce(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types, seq_length)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from paddle_tpu.metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    v = m.accumulate()
    return jnp.asarray(v, jnp.float32), None, None


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    t, b, l, r = [int(p) for p in paddings]
    pad = ([0, 0, 0, 0, t, b, l, r] if data_format == "NCHW"
           else [0, 0, t, b, l, r, 0, 0])
    return _p.pad(input, pad, mode="replicate" if mode == "edge" else mode,
                  value=pad_value)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    pads = [(0, int(xs - ys)) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample]
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode=mode, align_corners=align_corners,
                          align_mode=align_mode, data_format=data_format)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, 1, data_format)


def grid_sampler(x, grid, name=None):
    return _F.grid_sample(x, grid)


def unique_with_counts(x, dtype="int32"):
    vals, idx, counts = _p.unique(x, return_inverse=True, return_counts=True)
    return vals, idx, counts


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decoding, dense-padded form (ref:
    fluid/layers/nn.py ctc_greedy_decoder over ctc_align_op): argmax per
    step, merge repeats, drop blanks.  input ``[B, T, C]`` (batch-first
    dense; the reference's LoD variant is replaced by ``input_length``).
    Returns (decoded ``[B, T]`` padded with ``padding_value``,
    lengths ``[B, 1]``)."""
    import numpy as np

    probs = np.asarray(input)
    if probs.ndim != 3:
        raise UnimplementedError(
            "dense ctc_greedy_decoder expects [batch, time, classes]")
    B, T, _ = probs.shape
    lens = (np.asarray(input_length).reshape(B)
            if input_length is not None else np.full(B, T))
    out = np.full((B, T), padding_value, np.int64)
    out_lens = np.zeros((B, 1), np.int64)
    for b in _range(B):
        path = probs[b, : lens[b]].argmax(-1)
        prev = -1
        k = 0
        for t in path:
            if t != prev and t != blank:
                out[b, k] = t
                k += 1
            prev = t
        out_lens[b, 0] = k
    return jnp.asarray(out), jnp.asarray(out_lens)


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss (ref: operators/warpctc_op over the warp-ctc vendor lib —
    here XLA computes the same dynamic program via F.ctc_loss).  Dense
    logits ``[T, B, C]`` (time-major, reference layout when
    input_length is given)."""
    if input_length is None or label_length is None:
        raise UnimplementedError(
            "warpctc needs input_length/label_length (dense-padding "
            "policy replaces LoD inputs — SURVEY §7g)")
    return _F.ctc_loss(input, label, input_length, label_length,
                       blank=blank, reduction="none")


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (ref: operators/edit_distance_op).
    Dense ``[B, T]`` int sequences + lengths; host computation (it's an
    eval metric, same as the reference's CPU-only kernel)."""
    import numpy as np

    a = np.asarray(input)
    b = np.asarray(label)
    if a.ndim == 1:
        a, b = a[None], b[None]
    B = a.shape[0]
    la = (np.asarray(input_length).reshape(B)
          if input_length is not None else np.full(B, a.shape[1]))
    lb = (np.asarray(label_length).reshape(B)
          if label_length is not None else np.full(B, b.shape[1]))
    ignored = set(ignored_tokens or ())
    out = np.zeros((B, 1), np.float32)
    seq_num = np.asarray([B], np.int64)
    for i in _range(B):
        s1 = [t for t in a[i, : la[i]] if t not in ignored]
        s2 = [t for t in b[i, : lb[i]] if t not in ignored]
        m, n = len(s1), len(s2)
        dp = np.arange(n + 1, dtype=np.int64)
        for r in _range(1, m + 1):
            prev = dp.copy()
            dp[0] = r
            for c in _range(1, n + 1):
                dp[c] = min(prev[c] + 1, dp[c - 1] + 1,
                            prev[c - 1] + (s1[r - 1] != s2[c - 1]))
        d = float(dp[n])
        out[i, 0] = d / n if (normalized and n) else d
    return jnp.asarray(out), jnp.asarray(seq_num)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    """1.x hard_sigmoid keeps its slope/offset knobs (ref:
    operators/activation_op.cc HardSigmoid; 2.0 hardsigmoid fixes
    slope=1/6)."""
    x = jnp.asarray(x)
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    x = jnp.asarray(x)
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """Bounded relu (ref: activation_op BRelu)."""
    return jnp.clip(jnp.asarray(x), t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    """log(1 + e^min(max(x,-t),t)) (ref: activation_op SoftRelu)."""
    x = jnp.clip(jnp.asarray(x), -threshold, threshold)
    return jnp.log1p(jnp.exp(x))


def size(input):
    """Element count as a tensor (ref: size_op)."""
    return _p.numel(input)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    return _p.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    from ...framework.dtype import convert_dtype

    out = _p.randn(list(shape))
    return (out * std + mean).astype(convert_dtype(dtype))


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Functional RNN driver over a cell (ref: fluid/layers/rnn.py rnn —
    the lax.scan loop lives in nn.RNN)."""
    return _nn.RNN(cell, is_reverse=is_reverse, time_major=time_major)(
        inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    return _nn.BiRNN(cell_fw, cell_bw, time_major=time_major)(
        inputs, initial_states, sequence_length)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize keeping aspect ratio so the SHORT side is out_short_len
    (ref: fluid/layers/nn.py image_resize_short)."""
    x = jnp.asarray(input)
    h, w = x.shape[2], x.shape[3]
    short, long_ = (h, w) if h < w else (w, h)
    ratio = out_short_len / float(short)
    out_shape = ([out_short_len, int(long_ * ratio)] if h < w
                 else [int(long_ * ratio), out_short_len])
    return image_resize(x, out_shape=out_shape, resample=resample)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="linear", align_corners=align_corners,
                          align_mode=align_mode, data_format=data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="trilinear", align_corners=align_corners,
                          align_mode=align_mode, data_format=data_format)


# -- sequence ops: dense/padded implementations (nn/functional/sequence.py)
from paddle_tpu.nn.functional import (  # noqa: F401,E402
    sequence_pool, sequence_softmax, sequence_reverse, sequence_pad,
    sequence_unpad, sequence_first_step, sequence_last_step,
    sequence_expand, sequence_expand_as, sequence_enumerate,
    sequence_concat, sequence_slice, sequence_scatter, sequence_reshape,
)


# -- static-only op-builders / LoD machinery ----------------------------
_STATIC_ONLY = {
    # param-creating builders → Layer classes
    "fc": "paddle.nn.Linear", "embedding": "paddle.nn.Embedding",
    "conv2d": "paddle.nn.Conv2D", "conv3d": "paddle.nn.Conv3D",
    "conv2d_transpose": "paddle.nn.Conv2DTranspose",
    "conv3d_transpose": "paddle.nn.Conv3DTranspose",
    "batch_norm": "paddle.nn.BatchNorm2D", "inplace_abn": "paddle.nn.BatchNorm2D",
    "instance_norm": "paddle.nn.InstanceNorm2D",
    "data_norm": "paddle.nn.BatchNorm1D",
    "layer_norm": "paddle.nn.LayerNorm", "group_norm": "paddle.nn.GroupNorm",
    "spectral_norm": "paddle.nn.SpectralNorm",
    "nce": "paddle.nn.functional.softmax_with_cross_entropy over sampled logits",
    "hsigmoid": "paddle.nn.HSigmoidLoss",
    "bilinear_tensor_product": "paddle.nn.BilinearTensorProduct",
    "pool2d": "paddle.nn.Pool2D / nn.functional.max_pool2d",
    "pool3d": "paddle.nn.functional.max_pool3d",
    "center_loss": "a Layer holding the centers buffer + mse update",
    "deformable_conv": "paddle.nn.functional.deform_conv2d (explicit weight/offset/mask tensors; the 1.x builder created the params itself)",
    # program control flow → lax / python
    "While": "jax.lax.while_loop (compiled) or Python while (eager)",
    "Switch": "jax.lax.switch", "IfElse": "jax.lax.cond",
    "cond": "jax.lax.cond (compiled) or Python if (eager)",
    "case": "jax.lax.switch", "switch_case": "jax.lax.switch",
    "while_loop": "jax.lax.while_loop",
    "DynamicRNN": "paddle.nn.RNN over padded batches",
    "StaticRNN": "paddle.nn.RNN",
    "array_write": "jax arrays are functional — collect in lax.scan",
    "array_read": "jax arrays are functional — index normally",
    "array_length": "len() of the Python list / leading dim",
    "create_array": "a Python list or a preallocated jnp array",
    "tensor_array_to_tensor": "jnp.stack / jnp.concatenate",
    "reorder_lod_tensor_by_rank": "LoD machinery replaced by dense padding",
    "Assert": "paddle_tpu.framework checks / chex assertions",
    "autoincreased_step_counter": "track the step in the train loop state",
    "random_crop": "paddle.vision.transforms.RandomCrop",
    "filter_by_instag": "boolean-mask gather (paddle.masked_select)",
    "merge_selected_rows": "SelectedRows replaced by dense grads",
    "get_tensor_from_selected_rows": "SelectedRows replaced by dense grads",
    "hash": "CTR-specific hashing; use Python/np hashing at ingest",
    "similarity_focus": "not implemented — open an issue if needed",
    "lod_reset": "LoD machinery replaced by dense padding + lengths",
    "lod_append": "LoD machinery replaced by dense padding + lengths",
    "sequence_conv": "conv1d over padded batches with sequence_mask",
    # PS / distributed-specific
    "Send": "XLA collectives (paddle.distributed)",
    "Recv": "XLA collectives (paddle.distributed)",
    # io readers
    "data": "paddle.static.data (InputSpec) + paddle.io.DataLoader",
    "read_file": "paddle.io.DataLoader", "double_buffer":
        "DataLoader device staging is double-buffered already",
    "py_reader": "paddle.io.DataLoader",
    "create_py_reader_by_data": "paddle.io.DataLoader",
    "load": "paddle.load / inference.load_inference_model",
    # rnn legacy
    "dynamic_lstm": "paddle.nn.LSTM", "dynamic_lstmp": "paddle.nn.LSTM",
    "dynamic_gru": "paddle.nn.GRU", "gru_unit": "paddle.nn.GRUCell",
    "lstm_unit": "paddle.nn.LSTMCell", "lstm": "paddle.nn.LSTM",
    "beam_search": "paddle.nn.BeamSearchDecoder + dynamic_decode",
    "beam_search_decode": "paddle.nn.functional.gather_tree",
    "DecodeHelper": "subclass paddle.nn.Decoder",
    "TrainingHelper": "teacher forcing = run the RNN over the batch",
    "GreedyEmbeddingHelper": "BeamSearchDecoder(beam_size=1)",
    "SampleEmbeddingHelper": "sample from softmax inside a Decoder.step",
    "BasicDecoder": "subclass paddle.nn.Decoder",
    # detection long tail
    "multi_box_head": "compose conv heads + prior_box",
    "retinanet_detection_output": "detection_output",
    # misc losses
    "sampled_softmax_with_cross_entropy": "sample negatives at ingest + "
                                          "softmax_with_cross_entropy",
    "teacher_student_sigmoid_loss": "distillation loss not implemented",
    "warpctc_lod": "warpctc with explicit lengths",
    "crop": "paddle.crop",
    "maxout_legacy": "paddle.nn.functional.maxout",
}


def __getattr__(name):
    hint = _STATIC_ONLY.get(name)
    if hint is not None:
        def shim(*a, **k):
            raise UnimplementedError(
                f"fluid.layers.{name} is 1.x Program/LoD API without an "
                f"eager counterpart here; use: {hint}")

        shim.__name__ = name
        shim.__doc__ = f"1.x shim; eager equivalent: {hint}"
        shim.__shim__ = True  # three-valued parity audit marker
        return shim
    # final fallback: 2.0 tensor/functional name used through fluid.layers
    for ns in (_p, _F):
        if hasattr(ns, name):
            return getattr(ns, name)
    raise AttributeError(
        f"module 'paddle_tpu.fluid.layers' has no attribute {name!r}")


# --- 1.x learning-rate decay functions (learning_rate_scheduler.py) ---------
# The 1.x functions built a decayed-lr Variable into the Program; eager
# equivalents return the matching paddle.optimizer.lr scheduler with the
# EXACT 1.x per-step formula — pass the result as ``learning_rate`` to any
# optimizer and step() it once per optimizer step (the 1.x global_step).

def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """Transformer Noam schedule (learning_rate_scheduler.py:53)."""
    from paddle_tpu.optimizer import lr as _lr

    return _lr.NoamDecay(d_model, warmup_steps, learning_rate)


def _step_lambda(decay_steps, staircase, fn):
    import math as _math

    def lam(step):
        d = step / decay_steps
        if staircase:
            d = _math.floor(d)
        return fn(d)

    return lam


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr · rate^(step/decay_steps) (learning_rate_scheduler.py:113).
    The continuous form maps onto the closed-form 2.0 scheduler (which
    also supports jit-traced ``value_at``); staircase keeps a lambda."""
    from paddle_tpu.optimizer import lr as _lr

    if not staircase:
        return _lr.ExponentialDecay(learning_rate,
                                    gamma=decay_rate ** (1.0 / decay_steps))
    return _lr.LambdaDecay(learning_rate, _step_lambda(
        decay_steps, staircase, lambda d: decay_rate ** d))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr · e^(−rate·step/decay_steps) (learning_rate_scheduler.py:174)."""
    import math as _math

    from paddle_tpu.optimizer import lr as _lr

    if not staircase:
        return _lr.NaturalExpDecay(learning_rate,
                                   gamma=decay_rate / decay_steps)
    return _lr.LambdaDecay(learning_rate, _step_lambda(
        decay_steps, staircase, lambda d: _math.exp(-decay_rate * d)))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + rate·step/decay_steps) (learning_rate_scheduler.py:235)."""
    from paddle_tpu.optimizer import lr as _lr

    if not staircase:
        return _lr.InverseTimeDecay(learning_rate,
                                    gamma=decay_rate / decay_steps)
    return _lr.LambdaDecay(learning_rate, _step_lambda(
        decay_steps, staircase, lambda d: 1.0 / (1.0 + decay_rate * d)))


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """(learning_rate_scheduler.py:296) — the 2.0 scheduler shares the
    formula exactly."""
    from paddle_tpu.optimizer import lr as _lr

    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_lr=end_learning_rate, power=power,
                               cycle=cycle)


def piecewise_decay(boundaries, values):
    """(learning_rate_scheduler.py:364)."""
    from paddle_tpu.optimizer import lr as _lr

    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr · ½(cos(epoch·π/epochs) + 1) with epoch = ⌊step/step_each_epoch⌋
    (learning_rate_scheduler.py:442)."""
    import math as _math

    from paddle_tpu.optimizer import lr as _lr

    def lam(step):
        epoch = _math.floor(step / step_each_epoch)
        return 0.5 * (_math.cos(epoch * _math.pi / epochs) + 1)

    return _lr.LambdaDecay(learning_rate, lam)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """(learning_rate_scheduler.py:488) — ``learning_rate`` may be a float
    or another scheduler, as in 1.x.  1.x evaluated the inner decay on
    the SHARED global_step counter, so a scheduler input gets a wrapper
    that keeps the inner schedule on the global step (the 2.0
    LinearWarmup starts the inner scheduler only after warmup)."""
    from paddle_tpu.optimizer import lr as _lr

    if not isinstance(learning_rate, _lr.LRScheduler):
        return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr,
                                end_lr)

    class _GlobalStepWarmup(_lr.LRScheduler):
        """1.x semantics exactly: a LINEAR ramp start_lr → end_lr during
        warmup (independent of the decay), then the inner decay evaluated
        at the shared GLOBAL step."""

        def __init__(self, inner, warmup_steps, start_lr, end_lr):
            self.inner = inner
            self.warmup_steps = warmup_steps
            self.start_lr = start_lr
            self.end_lr = end_lr
            super().__init__(inner.base_lr, -1, False)

        def _inner_at(self, step):
            # pure read of the inner schedule at an arbitrary step: the
            # caller may still hold (and step) the inner scheduler
            save = self.inner.last_epoch
            try:
                self.inner.last_epoch = step
                return self.inner.get_lr()
            finally:
                self.inner.last_epoch = save

        def get_lr(self):
            if self.last_epoch < self.warmup_steps:
                return (self.end_lr - self.start_lr) * self.last_epoch \
                    / self.warmup_steps + self.start_lr
            return self._inner_at(self.last_epoch)

        def value_at(self, step):
            import jax.numpy as _jnp

            ramp = (self.end_lr - self.start_lr) * step \
                / self.warmup_steps + self.start_lr
            try:
                decayed = self.inner.value_at(step)
            except NotImplementedError:
                raise NotImplementedError(
                    "linear_lr_warmup: the inner scheduler "
                    f"({type(self.inner).__name__}) has no closed-form "
                    "value_at, so the warmup composition cannot run "
                    "inside jit; use a continuous (non-staircase) decay")
            return _jnp.where(step < self.warmup_steps, ramp, decayed)

    return _GlobalStepWarmup(learning_rate, warmup_steps, start_lr, end_lr)

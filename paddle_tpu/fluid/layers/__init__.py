"""paddle.fluid.layers — the 1.x functional surface.

Parity: python/paddle/fluid/layers/ (~300 public names across nn.py,
tensor.py, ops.py, loss.py, detection.py, control_flow.py,
sequence_lod.py, rnn.py, metric_op.py).  Three tiers:

* ops whose semantics survive eagerly are implemented: thin wrappers
  translating 1.x argument names (``input``/``dim``/``keep_dim``...) to
  the 2.0 implementations that already exist in paddle_tpu.tensor /
  nn.functional — no second implementation, just the old calling
  convention;
* parameter-creating op-builders (fc, conv2d, batch_norm, ...) raise
  ``UnimplementedError`` naming the Layer-class replacement — exactly
  the set that also could not run in the reference's dygraph mode;
* LoD-dependent sequence ops point at their dense/padded counterparts
  (SURVEY §7g: dense padding + masks replace LoD).

Every name of the reference module resolves: implemented, or an
instructive error — never a bare AttributeError on real 1.x API.
"""
from __future__ import annotations

from builtins import range as _range

import jax
import jax.numpy as jnp

import paddle_tpu as _p
from paddle_tpu import nn as _nn
from paddle_tpu.nn import functional as _F
from ...framework.errors import UnimplementedError

# -- direct re-exports: same name, compatible signature -----------------
from paddle_tpu.tensor import (  # noqa: F401
    cast, concat, assign, argmin, argmax, argsort, ones, zeros, reverse,
    isfinite, linspace, zeros_like, ones_like, diag, eye, triu,
    gather, gather_nd, scatter, scatter_nd_add, scatter_nd, slice,
    strided_slice, shape, rank, sign, where, unbind, unique,
    shard_index, stack, unstack, flatten, squeeze, unsqueeze, transpose,
    clip, log, pow, abs, exp, sqrt, rsqrt, ceil,
    floor, cos, sin, tanh, round, reciprocal, square, cumsum,
    less_than, less_equal, greater_than, greater_equal,
    equal, not_equal, logical_and, logical_or, logical_xor, logical_not,
    is_empty, mean,
)
from paddle_tpu import crop_tensor, increment  # noqa: F401
from paddle_tpu.nn.functional import (  # noqa: F401
    relu, selu, elu, relu6, swish, mish, prelu, leaky_relu, maxout,
    log_loss, dice_loss, npair_loss, mse_loss, square_error_cost,
    softmax_with_cross_entropy, label_smooth,
)
from paddle_tpu.nn.functional import (  # noqa: F401
    row_conv, gather_tree, iou_similarity, ssd_loss, prior_box,
    bipartite_match, target_assign, detection_output, box_coder,
    box_clip, multiclass_nms, sequence_mask, linear_chain_crf,
    crf_decoding, pixel_shuffle, unfold, temporal_shift,
    roi_align, roi_pool, sigmoid_focal_loss, yolo_box, yolov3_loss,
    matrix_nms, density_prior_box, anchor_generator, generate_proposals,
    box_decoder_and_assign, distribute_fpn_proposals, collect_fpn_proposals,
    psroi_pool, locality_aware_nms,
)
from paddle_tpu.nn import (  # noqa: F401
    BeamSearchDecoder, Decoder, dynamic_decode, RNNCellBase as RNNCell,
    GRUCell, LSTMCell, clip_by_norm, DecodeHelper, TrainingHelper,
    GreedyEmbeddingHelper, SampleEmbeddingHelper, BasicDecoder,
)
from paddle_tpu.metric import accuracy  # noqa: F401
from ...static import Print, py_func, create_parameter, create_global_var  # noqa: F401


# -- 1.x calling-convention wrappers ------------------------------------
def _act(out, act):
    if act:
        fn = getattr(_F, act, None)
        if fn is None:
            raise UnimplementedError(f"activation {act!r} unknown")
        return fn(out)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _p.sum(input, axis=dim, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _p.mean(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _p.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _p.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _p.prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _p.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _p.any(input, axis=dim, keepdim=keep_dim)


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _F.softmax(input, axis=axis)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = x @ y
    return out if alpha == 1.0 else out * alpha


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """1.x mul op: flatten x/y to 2-D around the given split dims then
    matmul (ref: operators/mul_op.cc)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    xs = x.reshape((int(jnp.prod(jnp.asarray(x.shape[:x_num_col_dims]))), -1))
    ys = y.reshape((int(jnp.prod(jnp.asarray(y.shape[:y_num_col_dims]))), -1))
    out = xs @ ys
    return out.reshape(x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:])


def topk(input, k, name=None):
    return _p.topk(input, k)


def one_hot(input, depth, allow_out_of_range=False):
    return _F.one_hot(jnp.asarray(input).squeeze(-1)
                      if jnp.asarray(input).ndim > 1
                      and jnp.asarray(input).shape[-1] == 1 else input, depth)


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    from ...framework.dtype import convert_dtype

    dt = convert_dtype(dtype)
    shape = tuple(int(s) for s in shape)
    from ...static.graph import in_program_guard, record_call as _rc

    if in_program_guard():
        # under program_guard the constant is a named graph Variable —
        # 1.x While/StaticRNN loop state is initialized this way and the
        # NAME is what the loop carries
        return _rc(lambda: jnp.full(shape, value, dt),
                   prefix="fill_constant")
    return jnp.full(shape, value, dt)


def create_tensor(dtype, name=None, persistable=False):
    from ...framework.dtype import convert_dtype

    return jnp.zeros((), convert_dtype(dtype))


def sums(input, out=None):
    return _p.add_n(list(input))


def range(start, end, step, dtype, name=None):
    from ...framework.dtype import convert_dtype

    return jnp.arange(_scalar(start), _scalar(end), _scalar(step),
                      convert_dtype(dtype))


def _scalar(v):
    import numpy as np

    return v if isinstance(v, (int, float)) else np.asarray(v).item()


def has_inf(x):
    return jnp.isinf(jnp.asarray(x)).any()


def has_nan(x):
    return jnp.isnan(jnp.asarray(x)).any()


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return _F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def split(input, num_or_sections, dim=-1, name=None):
    return _p.split(input, num_or_sections, axis=dim)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    return _act(_p.reshape(x, shape), act)


def expand(x, expand_times, name=None):
    return _p.tile(x, expand_times)


def expand_as(x, target_tensor, name=None):
    return _p.expand_as(x, target_tensor)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = jnp.asarray(x)
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return _act(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.add), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.subtract), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.multiply), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.divide), act)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.maximum), act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.minimum), act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.power), act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.mod), act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _act(_bcast(x, y, axis, jnp.floor_divide), act)


def _bcast(x, y, axis, op):
    """1.x elementwise broadcast: y's dims align to x starting at
    ``axis`` (ref: operators/elementwise/elementwise_op_function.h)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype)
    if axis != -1 and y.ndim < x.ndim:
        y = y.reshape(y.shape + (1,) * (x.ndim - axis - y.ndim))
    return op(x, y)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _F.normalize(x, p=2, axis=axis, epsilon=epsilon)


def cos_sim(X, Y):
    out = _F.cosine_similarity(X, Y, axis=-1)
    return jnp.asarray(out)[..., None]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return _F.cross_entropy(input, label, soft_label=soft_label,
                            ignore_index=ignore_index, reduction="none",
                            use_softmax=False)[..., None]


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    out = _F.binary_cross_entropy_with_logits(
        x, jnp.asarray(label, jnp.asarray(x).dtype), reduction="none")
    mask = jnp.asarray(label) != ignore_index
    out = jnp.where(mask, out, 0.0)
    if normalize:
        out = out / jnp.maximum(mask.sum(), 1)
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    return _F.kl_div(x, target, reduction=reduction)


def huber_loss(input, label, delta):
    return _F.smooth_l1_loss(input, label, reduction="none", delta=delta)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """1.x smooth_l1 op (ref: operators/smooth_l1_loss_op.cc): per-row
    summed smooth-L1 with optional elementwise weights; sigma scales the
    quadratic window."""
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype)
    s2 = (1.0 if sigma is None else float(sigma)) ** 2
    d = (x - y) * (1.0 if inside_weight is None
                   else jnp.asarray(inside_weight, x.dtype))
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if outside_weight is not None:
        loss = loss * jnp.asarray(outside_weight, x.dtype)
    return loss.reshape(loss.shape[0], -1).sum(-1, keepdims=True)


def mean_iou(input, label, num_classes):
    from paddle_tpu.metric import mean_iou as _miou

    return _miou(input, label, num_classes)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    from paddle_tpu.metric import chunk_eval as _ce

    return _ce(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types, seq_length)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from paddle_tpu.metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    v = m.accumulate()
    return jnp.asarray(v, jnp.float32), None, None


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    t, b, l, r = [int(p) for p in paddings]
    pad = ([0, 0, 0, 0, t, b, l, r] if data_format == "NCHW"
           else [0, 0, t, b, l, r, 0, 0])
    return _p.pad(input, pad, mode="replicate" if mode == "edge" else mode,
                  value=pad_value)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    pads = [(0, int(xs - ys)) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample]
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode=mode, align_corners=align_corners,
                          align_mode=align_mode, data_format=data_format)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, 1, data_format)


def grid_sampler(x, grid, name=None):
    return _F.grid_sample(x, grid)


def unique_with_counts(x, dtype="int32"):
    vals, idx, counts = _p.unique(x, return_inverse=True, return_counts=True)
    return vals, idx, counts


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decoding, dense-padded form (ref:
    fluid/layers/nn.py ctc_greedy_decoder over ctc_align_op): argmax per
    step, merge repeats, drop blanks.  input ``[B, T, C]`` (batch-first
    dense; the reference's LoD variant is replaced by ``input_length``).
    Returns (decoded ``[B, T]`` padded with ``padding_value``,
    lengths ``[B, 1]``)."""
    import numpy as np

    probs = np.asarray(input)
    if probs.ndim != 3:
        raise UnimplementedError(
            "dense ctc_greedy_decoder expects [batch, time, classes]")
    B, T, _ = probs.shape
    lens = (np.asarray(input_length).reshape(B)
            if input_length is not None else np.full(B, T))
    out = np.full((B, T), padding_value, np.int64)
    out_lens = np.zeros((B, 1), np.int64)
    for b in _range(B):
        path = probs[b, : lens[b]].argmax(-1)
        prev = -1
        k = 0
        for t in path:
            if t != prev and t != blank:
                out[b, k] = t
                k += 1
            prev = t
        out_lens[b, 0] = k
    return jnp.asarray(out), jnp.asarray(out_lens)


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss (ref: operators/warpctc_op over the warp-ctc vendor lib —
    here XLA computes the same dynamic program via F.ctc_loss).  Dense
    logits ``[T, B, C]`` (time-major, reference layout when
    input_length is given)."""
    if input_length is None or label_length is None:
        raise UnimplementedError(
            "warpctc needs input_length/label_length (dense-padding "
            "policy replaces LoD inputs — SURVEY §7g)")
    return _F.ctc_loss(input, label, input_length, label_length,
                       blank=blank, reduction="none")


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (ref: operators/edit_distance_op).
    Dense ``[B, T]`` int sequences + lengths; host computation (it's an
    eval metric, same as the reference's CPU-only kernel)."""
    import numpy as np

    a = np.asarray(input)
    b = np.asarray(label)
    if a.ndim == 1:
        a, b = a[None], b[None]
    B = a.shape[0]
    la = (np.asarray(input_length).reshape(B)
          if input_length is not None else np.full(B, a.shape[1]))
    lb = (np.asarray(label_length).reshape(B)
          if label_length is not None else np.full(B, b.shape[1]))
    ignored = set(ignored_tokens or ())
    out = np.zeros((B, 1), np.float32)
    seq_num = np.asarray([B], np.int64)
    for i in _range(B):
        s1 = [t for t in a[i, : la[i]] if t not in ignored]
        s2 = [t for t in b[i, : lb[i]] if t not in ignored]
        m, n = len(s1), len(s2)
        dp = np.arange(n + 1, dtype=np.int64)
        for r in _range(1, m + 1):
            prev = dp.copy()
            dp[0] = r
            for c in _range(1, n + 1):
                dp[c] = min(prev[c] + 1, dp[c - 1] + 1,
                            prev[c - 1] + (s1[r - 1] != s2[c - 1]))
        d = float(dp[n])
        out[i, 0] = d / n if (normalized and n) else d
    return jnp.asarray(out), jnp.asarray(seq_num)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    """1.x hard_sigmoid keeps its slope/offset knobs (ref:
    operators/activation_op.cc HardSigmoid; 2.0 hardsigmoid fixes
    slope=1/6)."""
    x = jnp.asarray(x)
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    x = jnp.asarray(x)
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    """Bounded relu (ref: activation_op BRelu)."""
    return jnp.clip(jnp.asarray(x), t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    """log(1 + e^min(max(x,-t),t)) (ref: activation_op SoftRelu)."""
    x = jnp.clip(jnp.asarray(x), -threshold, threshold)
    return jnp.log1p(jnp.exp(x))


def size(input):
    """Element count as a tensor (ref: size_op)."""
    return _p.numel(input)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    return _p.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    from ...framework.dtype import convert_dtype

    out = _p.randn(list(shape))
    return (out * std + mean).astype(convert_dtype(dtype))


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Functional RNN driver over a cell (ref: fluid/layers/rnn.py rnn —
    the lax.scan loop lives in nn.RNN)."""
    return _nn.RNN(cell, is_reverse=is_reverse, time_major=time_major)(
        inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    return _nn.BiRNN(cell_fw, cell_bw, time_major=time_major)(
        inputs, initial_states, sequence_length)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize keeping aspect ratio so the SHORT side is out_short_len
    (ref: fluid/layers/nn.py image_resize_short)."""
    x = jnp.asarray(input)
    h, w = x.shape[2], x.shape[3]
    short, long_ = (h, w) if h < w else (w, h)
    ratio = out_short_len / float(short)
    out_shape = ([out_short_len, int(long_ * ratio)] if h < w
                 else [int(long_ * ratio), out_short_len])
    return image_resize(x, out_shape=out_shape, resample=resample)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="linear", align_corners=align_corners,
                          align_mode=align_mode, data_format=data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="trilinear", align_corners=align_corners,
                          align_mode=align_mode, data_format=data_format)


# -- sequence ops: dense/padded implementations (nn/functional/sequence.py)
from paddle_tpu.nn.functional import (  # noqa: F401,E402
    sequence_pool, sequence_softmax, sequence_reverse, sequence_pad,
    sequence_unpad, sequence_first_step, sequence_last_step,
    sequence_expand, sequence_expand_as, sequence_enumerate,
    sequence_concat, sequence_slice, sequence_scatter, sequence_reshape,
)


# -- static-only op-builders / LoD machinery ----------------------------
_STATIC_ONLY = {
    # param-creating builders → Layer classes
    "fc": "paddle.nn.Linear", "embedding": "paddle.nn.Embedding",
    "conv2d": "paddle.nn.Conv2D", "conv3d": "paddle.nn.Conv3D",
    "conv2d_transpose": "paddle.nn.Conv2DTranspose",
    "conv3d_transpose": "paddle.nn.Conv3DTranspose",
    "batch_norm": "paddle.nn.BatchNorm2D", "inplace_abn": "paddle.nn.BatchNorm2D",
    "instance_norm": "paddle.nn.InstanceNorm2D",
    "data_norm": "paddle.nn.BatchNorm1D",
    "layer_norm": "paddle.nn.LayerNorm", "group_norm": "paddle.nn.GroupNorm",
    "spectral_norm": "paddle.nn.SpectralNorm",
    "nce": "paddle.nn.functional.softmax_with_cross_entropy over sampled logits",
    "hsigmoid": "paddle.nn.HSigmoidLoss",
    "bilinear_tensor_product": "paddle.nn.BilinearTensorProduct",
    "pool2d": "paddle.nn.Pool2D / nn.functional.max_pool2d",
    "pool3d": "paddle.nn.functional.max_pool3d",
    "center_loss": "a Layer holding the centers buffer + mse update",
    "deformable_conv": "paddle.nn.functional.deform_conv2d (explicit weight/offset/mask tensors; the 1.x builder created the params itself)",
    # program control flow → lax / python
    "While": "jax.lax.while_loop (compiled) or Python while (eager)",
    "Switch": "jax.lax.switch", "IfElse": "jax.lax.cond",
    "cond": "jax.lax.cond (compiled) or Python if (eager)",
    "case": "jax.lax.switch", "switch_case": "jax.lax.switch",
    "while_loop": "jax.lax.while_loop",
    "DynamicRNN": "paddle.nn.RNN over padded batches",
    "StaticRNN": "paddle.nn.RNN",
    "array_write": "jax arrays are functional — collect in lax.scan",
    "array_read": "jax arrays are functional — index normally",
    "array_length": "len() of the Python list / leading dim",
    "create_array": "a Python list or a preallocated jnp array",
    "tensor_array_to_tensor": "jnp.stack / jnp.concatenate",
    "reorder_lod_tensor_by_rank": "LoD machinery replaced by dense padding",
    "Assert": "paddle_tpu.framework checks / chex assertions",
    "autoincreased_step_counter": "track the step in the train loop state",
    "random_crop": "paddle.vision.transforms.RandomCrop",
    "filter_by_instag": "boolean-mask gather (paddle.masked_select)",
    "merge_selected_rows": "SelectedRows replaced by dense grads",
    "get_tensor_from_selected_rows": "SelectedRows replaced by dense grads",
    "hash": "CTR-specific hashing; use Python/np hashing at ingest",
    "similarity_focus": "not implemented — open an issue if needed",
    "lod_reset": "LoD machinery replaced by dense padding + lengths",
    "lod_append": "LoD machinery replaced by dense padding + lengths",
    "sequence_conv": "conv1d over padded batches with sequence_mask",
    # PS / distributed-specific
    "Send": "XLA collectives (paddle.distributed)",
    "Recv": "XLA collectives (paddle.distributed)",
    # io readers
    "data": "paddle.static.data (InputSpec) + paddle.io.DataLoader",
    "read_file": "paddle.io.DataLoader", "double_buffer":
        "DataLoader device staging is double-buffered already",
    "py_reader": "paddle.io.DataLoader",
    "create_py_reader_by_data": "paddle.io.DataLoader",
    "load": "paddle.load / inference.load_inference_model",
    # rnn legacy
    "dynamic_lstm": "paddle.nn.LSTM", "dynamic_lstmp": "paddle.nn.LSTM",
    "dynamic_gru": "paddle.nn.GRU", "gru_unit": "paddle.nn.GRUCell",
    "lstm_unit": "paddle.nn.LSTMCell", "lstm": "paddle.nn.LSTM",
    "beam_search": "paddle.nn.BeamSearchDecoder + dynamic_decode",
    "beam_search_decode": "paddle.nn.functional.gather_tree",
    "DecodeHelper": "subclass paddle.nn.Decoder",
    "TrainingHelper": "teacher forcing = run the RNN over the batch",
    "GreedyEmbeddingHelper": "BeamSearchDecoder(beam_size=1)",
    "SampleEmbeddingHelper": "sample from softmax inside a Decoder.step",
    "BasicDecoder": "subclass paddle.nn.Decoder",
    # detection long tail
    "multi_box_head": "compose conv heads + prior_box",
    "retinanet_detection_output": "detection_output",
    # misc losses
    "sampled_softmax_with_cross_entropy": "sample negatives at ingest + "
                                          "softmax_with_cross_entropy",
    "teacher_student_sigmoid_loss": "distillation loss not implemented",
    "warpctc_lod": "warpctc with explicit lengths",
    "crop": "paddle.crop",
    "maxout_legacy": "paddle.nn.functional.maxout",
}


def __getattr__(name):
    hint = _STATIC_ONLY.get(name)
    if hint is not None:
        def shim(*a, **k):
            raise UnimplementedError(
                f"fluid.layers.{name} is 1.x Program/LoD API without an "
                f"eager counterpart here; use: {hint}")

        shim.__name__ = name
        shim.__doc__ = f"1.x shim; eager equivalent: {hint}"
        shim.__shim__ = True  # three-valued parity audit marker
        return shim
    # final fallback: 2.0 tensor/functional name used through fluid.layers
    for ns in (_p, _F):
        if hasattr(ns, name):
            return getattr(ns, name)
    raise AttributeError(
        f"module 'paddle_tpu.fluid.layers' has no attribute {name!r}")


# --- 1.x learning-rate decay functions (learning_rate_scheduler.py) ---------
# The 1.x functions built a decayed-lr Variable into the Program; eager
# equivalents return the matching paddle.optimizer.lr scheduler with the
# EXACT 1.x per-step formula — pass the result as ``learning_rate`` to any
# optimizer and step() it once per optimizer step (the 1.x global_step).

def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """Transformer Noam schedule (learning_rate_scheduler.py:53)."""
    from paddle_tpu.optimizer import lr as _lr

    return _lr.NoamDecay(d_model, warmup_steps, learning_rate)


def _step_lambda(decay_steps, staircase, fn):
    import math as _math

    def lam(step):
        d = step / decay_steps
        if staircase:
            d = _math.floor(d)
        return fn(d)

    return lam


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr · rate^(step/decay_steps) (learning_rate_scheduler.py:113).
    The continuous form maps onto the closed-form 2.0 scheduler (which
    also supports jit-traced ``value_at``); staircase keeps a lambda."""
    from paddle_tpu.optimizer import lr as _lr

    if not staircase:
        return _lr.ExponentialDecay(learning_rate,
                                    gamma=decay_rate ** (1.0 / decay_steps))
    return _lr.LambdaDecay(learning_rate, _step_lambda(
        decay_steps, staircase, lambda d: decay_rate ** d))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr · e^(−rate·step/decay_steps) (learning_rate_scheduler.py:174)."""
    import math as _math

    from paddle_tpu.optimizer import lr as _lr

    if not staircase:
        return _lr.NaturalExpDecay(learning_rate,
                                   gamma=decay_rate / decay_steps)
    return _lr.LambdaDecay(learning_rate, _step_lambda(
        decay_steps, staircase, lambda d: _math.exp(-decay_rate * d)))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + rate·step/decay_steps) (learning_rate_scheduler.py:235)."""
    from paddle_tpu.optimizer import lr as _lr

    if not staircase:
        return _lr.InverseTimeDecay(learning_rate,
                                    gamma=decay_rate / decay_steps)
    return _lr.LambdaDecay(learning_rate, _step_lambda(
        decay_steps, staircase, lambda d: 1.0 / (1.0 + decay_rate * d)))


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """(learning_rate_scheduler.py:296) — the 2.0 scheduler shares the
    formula exactly."""
    from paddle_tpu.optimizer import lr as _lr

    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_lr=end_learning_rate, power=power,
                               cycle=cycle)


def piecewise_decay(boundaries, values):
    """(learning_rate_scheduler.py:364)."""
    from paddle_tpu.optimizer import lr as _lr

    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr · ½(cos(epoch·π/epochs) + 1) with epoch = ⌊step/step_each_epoch⌋
    (learning_rate_scheduler.py:442)."""
    import math as _math

    from paddle_tpu.optimizer import lr as _lr

    def lam(step):
        epoch = _math.floor(step / step_each_epoch)
        return 0.5 * (_math.cos(epoch * _math.pi / epochs) + 1)

    return _lr.LambdaDecay(learning_rate, lam)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """(learning_rate_scheduler.py:488) — ``learning_rate`` may be a float
    or another scheduler, as in 1.x.  1.x evaluated the inner decay on
    the SHARED global_step counter, so a scheduler input gets a wrapper
    that keeps the inner schedule on the global step (the 2.0
    LinearWarmup starts the inner scheduler only after warmup)."""
    from paddle_tpu.optimizer import lr as _lr

    if not isinstance(learning_rate, _lr.LRScheduler):
        return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr,
                                end_lr)

    class _GlobalStepWarmup(_lr.LRScheduler):
        """1.x semantics exactly: a LINEAR ramp start_lr → end_lr during
        warmup (independent of the decay), then the inner decay evaluated
        at the shared GLOBAL step."""

        def __init__(self, inner, warmup_steps, start_lr, end_lr):
            self.inner = inner
            self.warmup_steps = warmup_steps
            self.start_lr = start_lr
            self.end_lr = end_lr
            super().__init__(inner.base_lr, -1, False)

        def _inner_at(self, step):
            # pure read of the inner schedule at an arbitrary step: the
            # caller may still hold (and step) the inner scheduler
            save = self.inner.last_epoch
            try:
                self.inner.last_epoch = step
                return self.inner.get_lr()
            finally:
                self.inner.last_epoch = save

        def get_lr(self):
            if self.last_epoch < self.warmup_steps:
                return (self.end_lr - self.start_lr) * self.last_epoch \
                    / self.warmup_steps + self.start_lr
            return self._inner_at(self.last_epoch)

        def value_at(self, step):
            import jax.numpy as _jnp

            ramp = (self.end_lr - self.start_lr) * step \
                / self.warmup_steps + self.start_lr
            try:
                decayed = self.inner.value_at(step)
            except NotImplementedError:
                raise NotImplementedError(
                    "linear_lr_warmup: the inner scheduler "
                    f"({type(self.inner).__name__}) has no closed-form "
                    "value_at, so the warmup composition cannot run "
                    "inside jit; use a continuous (non-staircase) decay")
            return _jnp.where(step < self.warmup_steps, ramp, decayed)

    return _GlobalStepWarmup(learning_rate, warmup_steps, start_lr, end_lr)


# ======================================================================
# Graph mode (static/graph.py): the 1.x build/run flow.
# ======================================================================
# Control flow — eager/traced/graph dispatch (control_flow.py in this
# package; ref control_flow.py:2298/1110/971/449/2576/2715); the imports
# SHADOW the eager-only re-exports above where the 1.x signature differs
# (increment's in_place, less_than's cond= out-param).
from .control_flow import (  # noqa: E402,F401
    cond, while_loop, case, switch_case, While, StaticRNN, increment,
    less_than, array_write, array_read, array_length, create_array,
    tensor_array_to_tensor, Assert, Switch, IfElse,
)

# Parameter-creating op-builders over the recorded graph (static/builders)
from paddle_tpu.static.builders import (  # noqa: E402,F401
    fc, embedding, conv2d, pool2d, batch_norm, layer_norm,
    conv2d_transpose, conv3d, conv3d_transpose, instance_norm, group_norm,
    spectral_norm, prelu, bilinear_tensor_product,
)

from paddle_tpu.static.graph import (  # noqa: E402
    Variable as _GraphVar, data as _graph_data, maybe_record as _maybe_record,
    record_call as _record_call,
)


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    """1.x fluid.layers.data (ref: fluid/layers/io.py:54): unlike
    fluid.data, prepends the implicit -1 batch dim unless the shape
    already leads with -1 or append_batch_size=False."""
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    return _graph_data(name, shape, dtype)


def assign(input, output=None):
    """1.x assign with the ``output=`` out-parameter: in graph mode the
    value is written to output's NAME (the in-place idiom While-loop
    bodies use); eager falls through to tensor.assign."""
    if isinstance(output, _GraphVar):
        return _record_call(lambda v: jnp.asarray(v), input,
                            out_names=[output.name], prefix="assign")
    if isinstance(input, _GraphVar):
        return _record_call(lambda v: jnp.asarray(v), input, prefix="assign")
    from paddle_tpu.tensor import assign as _assign

    return _assign(input) if output is None else _assign(input, output)


# SelectedRows ops — real now (framework/selected_rows.py)
def merge_selected_rows(x, name=None):
    """ref: operators/merge_selected_rows_op — segment-sums duplicate rows
    of a SelectedRows gradient."""
    from paddle_tpu.framework.selected_rows import SelectedRows

    if not isinstance(x, SelectedRows):
        raise UnimplementedError(
            "merge_selected_rows expects a SelectedRows gradient — they "
            "come from Embedding(sparse=True) inside a sparse-aware train "
            "step (framework/selected_rows.py)")
    return x.merged()


def get_tensor_from_selected_rows(x, name=None):
    """ref: operators/get_tensor_from_selected_rows_op — the [k, D] row
    values of a SelectedRows."""
    from paddle_tpu.framework.selected_rows import SelectedRows

    if not isinstance(x, SelectedRows):
        raise UnimplementedError(
            "get_tensor_from_selected_rows expects a SelectedRows gradient")
    return x.values


def hash(input, hash_size, num_hash=1, name=None):
    """ref: operators/hash_op (XXH64 % hash_size per row).  Same
    dimensionality-reduction capability with a splitmix64-style integer
    mix instead of xxhash (documented deviation: hashed ids differ from
    the reference's, which only matters when loading reference-trained
    embeddings over hashed slots)."""
    x = jnp.asarray(input, jnp.uint64)

    def mix(v, seed):
        v = v ^ jnp.uint64(seed * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF)
        v = (v ^ (v >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        v = (v ^ (v >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        return v ^ (v >> jnp.uint64(31))

    # combine the last-dim elements of each row into one key, then hash
    # num_hash times with different seeds
    key = x.reshape(x.shape[:-1] + (-1,))
    row = key[..., 0]
    for j in _range(1, key.shape[-1]):
        row = mix(row, 1) + key[..., j]
    outs = [(mix(row, seed + 1) % jnp.uint64(hash_size)).astype(jnp.int64)
            for seed in _range(num_hash)]
    return jnp.stack(outs, axis=-1)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """ref: operators/sample_logits_op + softmax_with_cross_entropy —
    softmax CE over the true class plus ``num_samples`` uniformly sampled
    negatives (the sampled-softmax estimator for huge softmax layers)."""
    logits = jnp.asarray(logits)
    label = jnp.asarray(label).reshape(logits.shape[0], num_true)
    n_cls = logits.shape[-1]
    # seed==0 means "draw fresh" (1.x convention) — a fixed key would
    # sample the SAME negatives every step, degenerating the estimator
    if seed:
        key = jax.random.PRNGKey(seed)
    else:
        from paddle_tpu.framework import random as _prandom

        key = _prandom.default_generator().next_key()
    neg = jax.random.randint(key, (logits.shape[0], int(num_samples)),
                             0, n_cls)
    if remove_accidental_hits:
        # resample-by-shift: an accidental true hit moves to (id+1) % n
        hit = (neg[..., None] == label[:, None, :]).any(-1)
        neg = jnp.where(hit, (neg + 1) % n_cls, neg)
    idx = jnp.concatenate([label, neg], axis=1)              # [B, T+S]
    picked = jnp.take_along_axis(logits, idx, axis=1)
    lse = jax.nn.logsumexp(picked, axis=1, keepdims=True)
    true_logit = picked[:, :num_true]
    return (lse - true_logit).reshape(label.shape[0], num_true)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """ref: operators/teacher_student_sigmoid_loss_op.cc:107 — CTR
    distillation loss: label in {-1} ∪ [0,1] ∪ (1,2] selects the
    teacher/student mixing of sigmoid CE terms; x is clipped to the soft
    bounds."""
    x = jnp.clip(jnp.asarray(input, jnp.float32).reshape(-1),
                 soft_max_lower_bound, soft_max_up_bound)
    z = jnp.asarray(label, jnp.float32).reshape(-1)
    log1pex = jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0.0)
    # reference piecewise: z == -1 → pure negative CE; 0<=z<=1 → soft
    # teacher CE with weight z; z>1 → student click CE + (z-1) scaling
    neg = log1pex - 0  # -log sigmoid(-x) = log(1+e^x) = log1pex
    soft = log1pex - x * z
    stud = (log1pex - x) * (z - 1.0) + log1pex
    loss = jnp.where(z < 0.0, neg, jnp.where(z <= 1.0, soft, stud))
    return loss.reshape(jnp.asarray(input).shape[:-1] + (1,))


def random_crop(x, shape, seed=None):
    """ref: operators/random_crop_op — an INDEPENDENT random crop offset
    per leading-dim instance (the reference draws per-instance), cropping
    the trailing ``len(shape)`` dims to ``shape``."""
    x = jnp.asarray(x)
    shape = tuple(int(s) for s in shape)
    k = len(shape)
    from paddle_tpu.framework import random as _prandom

    key = (jax.random.PRNGKey(seed) if seed else
           _prandom.default_generator().next_key())
    maxs = [x.shape[-k + i] - shape[i] for i in _range(k)]

    def crop_one(sample, skey):
        keys = jax.random.split(skey, k)
        out = sample
        for i in _range(k):
            start = jax.random.randint(keys[i], (), 0, maxs[i] + 1)
            out = jax.lax.dynamic_slice_in_dim(
                out, start, shape[i], axis=sample.ndim - k + i)
        return out

    if x.ndim == k:  # single instance
        return crop_one(x, key)
    lead = x.shape[:-k]
    flat = x.reshape((-1,) + x.shape[-k:])
    keys = jax.random.split(key, flat.shape[0])
    out = jax.vmap(crop_one)(flat, keys)
    return out.reshape(lead + shape)


# PyReader adapter — the 1.x feeding pipeline over io.DataLoader
class _PyReaderAdapter:
    """ref: fluid/layers/io.py py_reader / fluid/reader.py PyReader — a
    capacity-bounded reader the Program pulls from.  Here the adapter owns
    feed placeholder Variables; Executor.run() with no feed pulls the next
    batch from every started reader (raising fluid.core.EOFException when
    a pass ends, like the reference)."""

    def __init__(self, capacity, shapes, dtypes, names):
        self.capacity = capacity
        self._vars = [
            _graph_data(n, s, dt) for n, s, dt in zip(names, shapes, dtypes)]
        self._source = None
        self._iter = None
        from paddle_tpu.static.graph import default_main_program

        default_main_program()._readers = getattr(
            default_main_program(), "_readers", [])
        default_main_program()._readers.append(self)

    # -- decoration (all three reference spellings) ----------------------
    def decorate_sample_list_generator(self, generator, places=None):
        self._source = generator

    decorate_paddle_reader = decorate_sample_list_generator
    decorate_batch_generator = decorate_sample_list_generator

    def start(self):
        if self._source is None:
            raise UnimplementedError(
                "py_reader: call decorate_sample_list_generator/"
                "decorate_paddle_reader first")
        self._iter = iter(self._source())

    def reset(self):
        self._iter = None

    def next_feed(self):
        from paddle_tpu.fluid.core import EOFException

        if self._iter is None:
            raise UnimplementedError("py_reader: call start() first")
        try:
            batch = next(self._iter)
        except StopIteration:
            self._iter = None
            raise EOFException("pass end")
        if isinstance(batch, (list, tuple)) and batch and isinstance(
                batch[0], (list, tuple)):
            # sample-list form: list of per-sample tuples → stack fields
            import numpy as _np

            batch = [_np.stack([_np.asarray(s[i]) for s in batch])
                     for i in _range(len(batch[0]))]
        return {v.name: b for v, b in zip(self._vars, batch)}

    @property
    def variables(self):
        return list(self._vars)


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """ref: fluid/layers/io.py:415 py_reader — returns the reader object;
    read its Variables with fluid.layers.read_file(reader)."""
    from paddle_tpu.static.graph import default_main_program as _dmp

    # unique per reader even unnamed (1.x uses unique_name): two readers
    # must not collide on feed slot names
    base = name or _dmp().unique_name("py_reader")
    names = [f"{base}_{i}" for i in _range(len(shapes))]
    return _PyReaderAdapter(capacity, shapes, dtypes, names)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """ref: fluid/layers/io.py create_py_reader_by_data — py_reader whose
    slots mirror existing data Variables."""
    r = _PyReaderAdapter(capacity,
                         [list(v.shape) for v in feed_list],
                         [v.dtype for v in feed_list],
                         [f"{name or 'py_reader'}_{v.name}" for v in feed_list])
    return r


def read_file(reader):
    """ref: fluid/layers/io.py read_file — the reader's output Variables."""
    if isinstance(reader, _PyReaderAdapter):
        vs = reader.variables
        return vs[0] if len(vs) == 1 else tuple(vs)
    raise UnimplementedError(
        "read_file expects a py_reader; for files use paddle.io.DataLoader")


def double_buffer(reader, place=None, name=None):
    """ref: fluid/layers/io.py double_buffer — device prefetch staging.
    The DataLoader/Executor feed path is already double-buffered
    (io/dataloader.py staging thread), so this is the identity."""
    return reader


# names implemented above are no longer shims
for _impl in ("fc", "embedding", "conv2d", "conv3d", "conv2d_transpose",
              "conv3d_transpose", "batch_norm", "layer_norm", "pool2d",
              "instance_norm", "group_norm", "spectral_norm",
              "bilinear_tensor_product", "cond", "while_loop", "case",
              "switch_case", "While", "StaticRNN", "array_write",
              "array_read", "array_length", "create_array",
              "tensor_array_to_tensor", "Assert", "data", "py_reader",
              "create_py_reader_by_data", "read_file", "double_buffer",
              "merge_selected_rows", "get_tensor_from_selected_rows",
              "hash", "random_crop", "sampled_softmax_with_cross_entropy",
              "teacher_student_sigmoid_loss", "load"):
    _STATIC_ONLY.pop(_impl, None)

# `load` maps to the real serialization loader (fluid.io / paddle.load)
from paddle_tpu.framework.serialization import load  # noqa: E402,F401

# -- make the whole eager surface graph-capable: public functions called
# with symbolic Variables record into the current Program instead of
# executing (static/graph.py maybe_record); builders/control-flow handle
# their own dispatch and are excluded
import types as _types  # noqa: E402

_NO_WRAP = {
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "batch_norm", "layer_norm", "pool2d",
    "instance_norm", "group_norm", "spectral_norm",
    "bilinear_tensor_product", "cond", "while_loop", "case", "switch_case",
    "increment", "less_than", "assign", "data", "py_reader",
    "create_py_reader_by_data", "read_file", "double_buffer",
    "array_write", "array_read", "array_length", "create_array",
    "tensor_array_to_tensor", "Assert", "load",
}
for _n, _v in list(globals().items()):
    if (isinstance(_v, _types.FunctionType) and not _n.startswith("_")
            and _n not in _NO_WRAP):
        globals()[_n] = _maybe_record(_v)
del _n, _v


# -- round-4 shim burn-down batch 2 -------------------------------------
def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    """ref: fluid/layers/nn.py pool3d (NCDHW)."""
    x = jnp.asarray(input)
    if global_pooling:
        axes = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
        red = jnp.max if pool_type == "max" else jnp.mean
        return red(x, axis=axes, keepdims=True)
    if pool_type == "max":
        return _F.max_pool3d(x, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode,
                             data_format=data_format)
    return _F.avg_pool3d(x, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode,
                         exclusive=exclusive, data_format=data_format)


def beam_search_decode(ids, scores, beam_size=None, end_id=0, name=None):
    """ref: fluid/layers/rnn.py beam_search_decode — back-trace the stored
    per-step (ids, parents) into full sequences.  Dense form: ``ids`` and
    ``scores`` are the per-step arrays a decode loop collected (list /
    stacked [T, batch, beam]); parent pointers ride the high bits the way
    paddle.nn.functional.gather_tree expects — this is a thin adapter over
    it (the 1.x op's LoD plumbing is replaced by dense [T, B, W])."""
    ids = jnp.stack([jnp.asarray(a) for a in ids]) \
        if isinstance(ids, (list, tuple)) else jnp.asarray(ids)
    scores = jnp.stack([jnp.asarray(a) for a in scores]) \
        if isinstance(scores, (list, tuple)) else jnp.asarray(scores)
    if ids.ndim != 3:
        raise UnimplementedError(
            "beam_search_decode expects dense [T, batch, beam] step ids "
            "(collect them from the decode loop; LoD beams are replaced "
            "by dense padding here)")
    # the per-step parent beam indices must come through the scores slot
    # (integer layout) — float log-probs carry no ancestry in dense form
    # (the 1.x op recovered it from the LoD, which dense padding replaces)
    if jnp.issubdtype(scores.dtype, jnp.floating):  # incl. bfloat16
        raise UnimplementedError(
            "beam_search_decode(dense): pass the per-step PARENT indices "
            "(int) in the scores argument, or use "
            "paddle.nn.functional.gather_tree(ids, parents) / "
            "paddle.nn.BeamSearchDecoder which track ancestry explicitly")
    parents = scores.astype(jnp.int64)
    seqs = _F.gather_tree(ids, parents)
    return seqs, scores


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """ref: operators/filter_by_instag_op — keep rows of ``ins`` whose tag
    set intersects ``filter_tag``.  Dense form: ``ins_tag`` is [N] (one
    tag per row) or [N, K] padded with -1; returns (filtered rows, the
    kept row indices, loss-weight vector) like the reference's three
    outputs.  Eager-only: the output row count is data-dependent and
    cannot compile into a Program/jit."""
    if any(isinstance(a, _GraphVar) for a in (ins, ins_tag, filter_tag)):
        raise UnimplementedError(
            "filter_by_instag produces a data-dependent row count and "
            "cannot compile into a Program/jit; call it eagerly on host "
            "arrays (e.g. at feed time) and feed the filtered batch")
    ins = jnp.asarray(ins)
    tags = jnp.asarray(ins_tag)
    if tags.ndim == 1:
        tags = tags[:, None]
    want = jnp.asarray(filter_tag).reshape(-1)
    keep = (tags[..., None] == want[None, None, :]).any(axis=(1, 2))
    idx = jnp.nonzero(keep)[0]  # eager: data-dependent size is fine
    out = ins[idx]
    if out.shape[0] == 0:
        # fabricated placeholder row: loss weight 0 keeps it inert (the
        # reference op does the same for the empty-match case)
        out = jnp.full((1,) + ins.shape[1:], out_val_if_empty, ins.dtype)
        idx = jnp.asarray([0])
        loss_weight = jnp.zeros((1, 1), jnp.float32)
    else:
        loss_weight = jnp.ones((out.shape[0], 1), jnp.float32)
    return out, idx.astype(jnp.int64), loss_weight


for _impl in ("pool3d", "beam_search_decode", "filter_by_instag", "crop"):
    _STATIC_ONLY.pop(_impl, None)
# crop resolves through the 2.0 fallback (paddle.crop)

for _n in ("pool3d", "beam_search_decode"):
    globals()[_n] = _maybe_record(globals()[_n])
del _n  # filter_by_instag stays eager-only (data-dependent output size)


# -- round-4 graph-builder batch 3 (param-creating, real in graph mode) --
from paddle_tpu.static.builders import (  # noqa: E402,F401
    nce, center_loss, sequence_conv, inplace_abn, hsigmoid, lstm,
    data_norm, multi_box_head, deformable_conv, gru_unit, lstm_unit,
    dynamic_lstm, dynamic_lstmp, dynamic_gru,
)

for _impl in ("nce", "center_loss", "sequence_conv", "inplace_abn",
              "hsigmoid", "lstm", "data_norm", "multi_box_head",
              "Switch", "IfElse", "deformable_conv", "gru_unit",
              "lstm_unit", "dynamic_lstm", "dynamic_lstmp", "dynamic_gru"):
    _STATIC_ONLY.pop(_impl, None)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """ref: fluid/layers/rnn.py beam_search (operators/beam_search_op) —
    one pruning step: from each batch's beam_size x K candidate expansions
    keep the top beam_size.  Dense form: ``scores``/``ids`` are
    [batch·beam, K]; returns (selected_ids, selected_scores[, parent_idx])
    each [batch·beam, 1], parent_idx naming the source beam — feed the
    collected parents to beam_search_decode/gather_tree.  Finished beams
    (pre_ids == end_id) keep their score and re-emit end_id, as the
    reference does."""
    pre_ids = jnp.asarray(pre_ids).reshape(-1)
    pre_scores = jnp.asarray(pre_scores).reshape(-1)
    ids = jnp.asarray(ids)
    scores = jnp.asarray(scores)
    if ids.ndim != 2 or scores.ndim != 2 or ids.shape != scores.shape:
        raise UnimplementedError(
            "beam_search(dense) expects matching ids/scores "
            "[batch*beam, K]")
    BK, K = scores.shape
    if BK % int(beam_size):
        raise UnimplementedError(
            f"beam_search: leading dim {BK} is not a multiple of "
            f"beam_size {beam_size} — in graph mode declare the "
            f"batch*beam dim statically (not -1)")
    batch = BK // int(beam_size)
    if not is_accumulated:
        scores = jnp.log(jnp.clip(scores, 1e-20)) + pre_scores[:, None]
    # finished beams contribute exactly one candidate: (end_id, pre_score)
    finished = (pre_ids == end_id)[:, None]
    neg_inf = jnp.full_like(scores, -jnp.inf)
    first_col = jnp.zeros((BK, K), bool).at[:, 0].set(True)
    scores = jnp.where(finished, jnp.where(first_col, pre_scores[:, None],
                                           neg_inf), scores)
    ids = jnp.where(finished, jnp.full_like(ids, end_id), ids)
    flat_s = scores.reshape(batch, int(beam_size) * K)
    flat_i = ids.reshape(batch, int(beam_size) * K)
    top_s, top_pos = jax.lax.top_k(flat_s, int(beam_size))
    sel_ids = jnp.take_along_axis(flat_i, top_pos, axis=1)
    parent = top_pos // K  # source beam within the batch
    out_ids = sel_ids.reshape(-1, 1).astype(jnp.int64)
    out_scores = top_s.reshape(-1, 1)
    parent_idx = (parent + jnp.arange(batch)[:, None] * int(beam_size)
                  ).reshape(-1).astype(jnp.int64)
    if return_parent_idx:
        return out_ids, out_scores, parent_idx
    return out_ids, out_scores


for _impl in ("beam_search",):
    _STATIC_ONLY.pop(_impl, None)


def _beam_search_graph_dispatch(fn):
    import functools as _ft

    @_ft.wraps(fn)
    def wrapped(pre_ids, pre_scores, ids, scores, beam_size, end_id, **kw):
        from paddle_tpu.static.graph import in_graph_mode, record_call

        if in_graph_mode(pre_ids, pre_scores, ids, scores):
            # the shape probe replaces -1 dims with 1, which cannot carry
            # a batch*beam factorization — require a static leading dim
            for v in (ids, scores):
                if isinstance(v, _GraphVar) and v.shape[0] is None:
                    raise UnimplementedError(
                        "beam_search in graph mode needs a STATIC "
                        "batch*beam leading dim (declare it instead of "
                        "-1: the pruning factorizes that dim)")
            return record_call(fn, pre_ids, pre_scores, ids, scores,
                               beam_size, end_id, **kw)
        return fn(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                  **kw)

    return wrapped


globals()["beam_search"] = _beam_search_graph_dispatch(
    globals()["beam_search"])


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """ref: fluid/layers/nn.py autoincreased_step_counter — a persistable
    int64 counter advanced by ``step`` on every executor run (the global
    step).  Graph mode: a Program buffer updated in the recorded op
    (training and eval runs both advance it, like the reference)."""
    from paddle_tpu.static.graph import default_main_program, in_program_guard

    if not in_program_guard():
        raise UnimplementedError(
            "autoincreased_step_counter is Program state: use it under "
            "program_guard/enable_static, or track the step in your "
            "train-loop state eagerly")
    prog = default_main_program()
    bname = counter_name or prog.unique_name("step_counter")
    prog.register_buffer(bname, jnp.asarray(begin - step, jnp.int64))
    from paddle_tpu.static.graph import record_call as _rc

    def fn(pv, bv, *, training=False, rngs=None):
        new = bv[bname] + jnp.int64(step)
        return new, {bname: new}

    return _rc(fn, buffer_names=(bname,), writes_buffers=True,
               scoped=True, prefix="step_counter")


for _impl in ("autoincreased_step_counter",):
    _STATIC_ONLY.pop(_impl, None)


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """ref: fluid/layers/detection.py:3100 retinanet_detection_output
    (operators/detection/retinanet_detection_output_op.cc) — per FPN
    level: threshold (0.0 for the HIGHEST level, :retinanet op rule),
    take the nms_top_k best (anchor, class) pairs, decode center-size
    deltas against the level's anchors (+1 pixel convention, /im_scale,
    clipped to the rounded original image); merge levels and run
    per-class greedy NMS with eta adaptation, keep_top_k overall.

    Eager post-processor (inference time): returns a list of per-image
    [No_i, 6] arrays ``[label(1-based), score, x1, y1, x2, y2]`` — the
    dense replacement for the reference's LoD-packed output."""
    import numpy as _np

    bboxes = [_np.asarray(b, _np.float32) for b in bboxes]
    scores = [_np.asarray(s, _np.float32) for s in scores]
    anchors = [_np.asarray(a, _np.float32) for a in anchors]
    im_info = _np.asarray(im_info, _np.float32).reshape(-1, 3)
    N = bboxes[0].shape[0]
    C = scores[0].shape[-1]
    L = len(scores)

    def iou(a, b):  # +1 pixel convention, matching the op's NMS
        ix1 = max(a[0], b[0]); iy1 = max(a[1], b[1])
        ix2 = min(a[2], b[2]); iy2 = min(a[3], b[3])
        iw = max(0.0, ix2 - ix1 + 1); ih = max(0.0, iy2 - iy1 + 1)
        inter = iw * ih
        ar = (a[2]-a[0]+1) * (a[3]-a[1]+1)
        br = (b[2]-b[0]+1) * (b[3]-b[1]+1)
        return inter / max(ar + br - inter, 1e-10)

    out = []
    for n in _range(N):
        imh, imw, im_scale = im_info[n]
        imh = round(float(imh) / im_scale)
        imw = round(float(imw) / im_scale)
        preds = {c: [] for c in _range(C)}
        for l in _range(L):
            sc = scores[l][n].reshape(-1)            # [A*C]
            thr = score_threshold if l < L - 1 else 0.0
            idx = _np.nonzero(sc > thr)[0]
            if nms_top_k >= 0 and idx.size > nms_top_k:
                idx = idx[_np.argsort(-sc[idx])[:int(nms_top_k)]]
            for i in idx:
                a_i, c_i = divmod(int(i), C)
                anc = anchors[l][a_i]
                d = bboxes[l][n, a_i]
                aw = anc[2] - anc[0] + 1; ah = anc[3] - anc[1] + 1
                acx = anc[0] + aw / 2; acy = anc[1] + ah / 2
                cx = d[0] * aw + acx; cy = d[1] * ah + acy
                w = _np.exp(d[2]) * aw; h = _np.exp(d[3]) * ah
                box = _np.array([cx - w/2, cy - h/2,
                                 cx + w/2 - 1, cy + h/2 - 1]) / im_scale
                box[0::2] = _np.clip(box[0::2], 0, imw - 1)
                box[1::2] = _np.clip(box[1::2], 0, imh - 1)
                preds[c_i].append((float(sc[i]), box))
        dets = []
        for c_i, cand in preds.items():
            cand.sort(key=lambda t: -t[0])
            kept, thr_c = [], nms_threshold
            for s_v, b_v in cand:
                if all(iou(b_v, kb) <= thr_c for _, kb in kept):
                    kept.append((s_v, b_v))
                    if nms_eta < 1.0 and thr_c > 0.5:
                        thr_c *= nms_eta
            dets.extend((c_i, s_v, b_v) for s_v, b_v in kept)
        dets.sort(key=lambda t: -t[1])
        if keep_top_k >= 0:  # -1 = keep all (1.x convention)
            dets = dets[:int(keep_top_k)]
        out.append(_np.array(
            [[c_i + 1, s_v, *b_v] for c_i, s_v, b_v in dets],
            _np.float32).reshape(-1, 6))
    return out


for _impl in ("retinanet_detection_output",):
    _STATIC_ONLY.pop(_impl, None)


def similarity_focus(input, axis, indexes, name=None):
    """ref: fluid/layers/nn.py similarity_focus (operators/
    similarity_focus_op) — for each index slice along ``axis``, greedily
    mark the min(B, C) largest values whose row AND column are both
    unused, OR the masks over ``indexes``, broadcast along ``axis``.
    Pure-jax greedy (fori_loop with row/column exclusion masks) — works
    eagerly and records/compiles in graph mode."""
    x = jnp.asarray(input)
    if x.ndim != 4:
        raise UnimplementedError(
            "similarity_focus expects a 4-D tensor (ref op constraint)")
    if axis not in (1, 2, 3):
        raise UnimplementedError("similarity_focus: axis must be 1, 2 or 3")
    A_dim = x.shape[axis]
    if not len(indexes):
        raise UnimplementedError("similarity_focus: indexes must be "
                                 "non-empty")
    for idx in indexes:  # reference enforces 0 <= index < dim
        if not (0 <= int(idx) < A_dim):
            raise UnimplementedError(
                f"similarity_focus: index {idx} out of range for axis "
                f"{axis} with size {A_dim}")
    perm = [0, axis] + [d for d in _range(1, 4) if d != axis]
    xt = jnp.transpose(x, perm)                      # [N, A, B, C]
    N, A, B, Cd = xt.shape
    K = min(B, Cd)

    def one_slice(T):                                # [B, C] → mask
        def body(_, state):
            mask, used_r, used_c = state
            blocked = used_r[:, None] | used_c[None, :]
            cand = jnp.where(blocked, -jnp.inf, T.astype(jnp.float32))
            f = jnp.argmax(cand)
            r, c = f // Cd, f % Cd
            return (mask.at[r, c].set(1.0), used_r.at[r].set(True),
                    used_c.at[c].set(True))

        mask, _, _ = jax.lax.fori_loop(
            0, K, body, (jnp.zeros((B, Cd), jnp.float32),
                         jnp.zeros((B,), bool), jnp.zeros((Cd,), bool)))
        return mask

    masks = jax.vmap(  # per batch: OR of the per-index greedy masks
        lambda slices: jnp.max(jax.vmap(one_slice)(slices), axis=0))(
            xt[:, jnp.asarray([int(i) for i in indexes])])
    out = jnp.broadcast_to(masks[:, None], (N, A, B, Cd))
    inv = [perm.index(i) for i in _range(4)]
    return jnp.transpose(out, inv).astype(x.dtype)


for _impl in ("similarity_focus", "DecodeHelper", "TrainingHelper",
              "GreedyEmbeddingHelper", "SampleEmbeddingHelper",
              "BasicDecoder"):
    _STATIC_ONLY.pop(_impl, None)
globals()["similarity_focus"] = _maybe_record(globals()["similarity_focus"])

"""Control flow: cond / while_loop / case / switch_case / While / StaticRNN.

Reference: python/paddle/fluid/layers/control_flow.py — ``cond`` (:2298),
``while_loop`` (:1110), ``While`` (:971), ``StaticRNN`` (:449), ``case``
(:2576), ``switch_case`` (:2715).  The reference builds
conditional_block/while ops into the Program; here each name has the
dispatch the execution mode calls for:

* **eager** (concrete booleans): plain Python — ``cond`` is an ``if``,
  ``while_loop`` a ``while``;
* **traced** (inside jit / a tracer pred): ``lax.cond`` /
  ``lax.while_loop`` / ``lax.switch`` — the XLA control-flow primitives
  the reference's ops lower to conceptually;
* **graph mode** (symbolic Variables from fluid.program_guard):
  ``While``/``StaticRNN`` capture the ops their ``with`` blocks record
  and replay them inside ``lax.while_loop``/``lax.scan`` at Executor.run
  time, reproducing the 1.x block semantics (including the
  ``less_than(..., cond=...)`` in-place idiom) without a Program
  interpreter.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ...framework.errors import InvalidArgumentError
from ...static.graph import (Op, Variable, default_main_program, record_call,
                             run_ops)

__all__ = ["cond", "while_loop", "case", "switch_case", "While",
           "StaticRNN", "increment", "less_than", "array_write",
           "array_read", "array_length", "create_array",
           "tensor_array_to_tensor", "Assert"]


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name=None,
         return_names=None):
    """ref control_flow.py:2298 — both branches must return the same
    structure.  Concrete pred → Python if (branches may be arbitrarily
    dynamic); traced pred → lax.cond (both branches traced)."""
    if true_fn is None and false_fn is None:
        raise InvalidArgumentError("cond: need at least one branch fn")
    true_fn = true_fn or (lambda: None)
    false_fn = false_fn or (lambda: None)
    if isinstance(pred, Variable):
        raise InvalidArgumentError(
            "cond over graph Variables: run the branch computation under "
            "jit (@paddle.jit.to_static) where pred is traced, or use "
            "fluid.layers.While for Program-style loops")
    if _is_traced(pred):
        return lax.cond(pred, true_fn, false_fn)
    return true_fn() if bool(pred) else false_fn()


def while_loop(cond_fn: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None):
    """ref control_flow.py:1110 — ``loop_vars`` is a list/tuple pytree;
    body returns the same structure.  Traced state → lax.while_loop
    (shapes must be loop-invariant, the same constraint the reference's
    while op has); concrete state → Python while."""
    loop_vars = list(loop_vars)
    traced = any(_is_traced(leaf)
                 for leaf in jax.tree_util.tree_leaves(loop_vars)) or \
        _is_traced(cond_fn(*loop_vars))
    if traced:
        def _body(vs):
            # call body exactly once per trace: a tapped/effectful body
            # (sparse-tape tap) must not double-record
            out = body(*vs)
            return tuple(out) if isinstance(out, (list, tuple)) else (out,)

        out = lax.while_loop(lambda vs: cond_fn(*vs), _body,
                             tuple(loop_vars))
        return list(out)
    while bool(cond_fn(*loop_vars)):
        out = body(*loop_vars)
        loop_vars = list(out) if isinstance(out, (list, tuple)) else [out]
    return loop_vars


def case(pred_fn_pairs, default: Optional[Callable] = None, name=None):
    """ref control_flow.py:2576 — first true predicate wins.  Concrete
    preds → sequential Python; any traced pred → nested lax.cond chain."""
    if not pred_fn_pairs:
        raise InvalidArgumentError("case: pred_fn_pairs is empty")
    preds = [p for p, _ in pred_fn_pairs]
    if default is None:
        # reference behavior: the last fn doubles as the default
        preds, fns = preds[:-1], [f for _, f in pred_fn_pairs]
        default = fns[-1]
        pairs = list(zip(preds, fns[:-1]))
    else:
        pairs = list(pred_fn_pairs)
    if not any(_is_traced(p) for p, _ in pairs):
        for p, fn in pairs:
            if bool(p):
                return fn()
        return default()

    def build(i):
        if i == len(pairs):
            return default
        p, fn = pairs[i]
        return lambda: lax.cond(p, fn, build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name=None):
    """ref control_flow.py:2715 — ``branch_fns`` is {int: fn} / [(int, fn)]
    / [fn, ...].  Traced index → lax.switch over a dense table."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    if not items:
        raise InvalidArgumentError("switch_case: no branches")
    if default is None:
        default = items[-1][1]
    if not _is_traced(branch_index):
        lookup = dict(items)
        return lookup.get(int(branch_index), default)()
    # dense fn table over [0, max_key]; out-of-range clamps to default
    max_key = items[-1][0]
    table = [default] * (max_key + 2)
    for i, f in items:
        table[i] = f
    idx = jnp.clip(jnp.asarray(branch_index, jnp.int32), 0, max_key + 1)
    # unknown indices inside [0, max_key] that weren't listed hit default
    return lax.switch(idx, table)


def increment(x, value=1.0, in_place=True):
    """ref control_flow.py increment — in graph mode, writes back to the
    SAME variable name (the 1.x in-place contract While loops rely on)."""
    if isinstance(x, Variable):
        return record_call(lambda t: t + jnp.asarray(value, t.dtype), x,
                           out_names=[x.name] if in_place else None,
                           prefix="increment")
    import paddle_tpu as _p

    return _p.increment(x, value)


def less_than(x, y, force_cpu=None, cond=None, name=None):
    """ref layers/control_flow.py less_than — the ``cond=`` out-parameter
    updates an existing bool Variable in place (how While loop conditions
    re-arm each iteration)."""
    if isinstance(x, Variable) or isinstance(y, Variable):
        out_names = [cond.name] if isinstance(cond, Variable) else None
        return record_call(lambda a, b: jnp.less(a, b), x, y,
                           out_names=out_names, prefix="less_than")
    from paddle_tpu.tensor import less_than as _lt

    out = _lt(x, y)
    return out


# -- LoDTensorArray: a Python list eagerly, stacked tensors under trace ----
def create_array(dtype="float32", initialized_list=None):
    """ref control_flow.py create_array — eager arrays are Python lists."""
    return list(initialized_list or [])


def array_write(x, i, array=None):
    """ref control_flow.py:1535 — writes x at index i, growing the array."""
    if array is None:
        array = []
    i = int(i)
    while len(array) <= i:
        array.append(None)
    array[i] = x
    return array


def array_read(array, i):
    """ref control_flow.py:1662."""
    if _is_traced(i):
        return lax.dynamic_index_in_dim(jnp.stack(list(array)),
                                        jnp.asarray(i, jnp.int32), 0,
                                        keepdims=False)
    return array[int(i)]


def array_length(array):
    """ref control_flow.py:1767."""
    return jnp.asarray(len(array), jnp.int64)


def tensor_array_to_tensor(input, axis=0, use_stack=False, name=None):
    """ref tensor.py tensor_array_to_tensor — returns (tensor, sizes)."""
    arrs = [jnp.asarray(a) for a in input]
    if use_stack:
        return jnp.stack(arrs, axis=axis), jnp.asarray(
            [1] * len(arrs), jnp.int32)
    sizes = jnp.asarray([a.shape[axis] for a in arrs], jnp.int32)
    return jnp.concatenate(arrs, axis=axis), sizes


def Assert(cond, data=None, summarize=20, name=None):
    """ref control_flow.py Assert — eager check; under trace it becomes a
    checkify-style no-op with a documented limitation (XLA has no abort)."""
    if _is_traced(cond):
        return  # compiled graphs cannot abort; parity with is_test prune
    if not bool(jnp.all(jnp.asarray(cond))):
        parts = [] if data is None else [np.asarray(d)[:summarize]
                                         for d in data]
        raise AssertionError(f"fluid.layers.Assert failed: {parts}")


import numpy as np  # noqa: E402  (Assert uses it lazily)


# -- graph-mode block control flow ------------------------------------------
class _BlockCapture:
    """Context manager: ops recorded inside land in ``self.ops`` instead of
    staying on the program."""

    def __init__(self):
        self.ops: List[Op] = []
        self._start = None
        self.pre_vars: set = set()

    def __enter__(self):
        self._prog = default_main_program()
        self._start = len(self._prog.ops)
        self.pre_vars = set(self._prog.vars)
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.ops = self._prog.ops[self._start:]
            del self._prog.ops[self._start:]
            self._prog._version += 1
        return False


def _body_param_names(ops):
    ps, bs = [], []
    for op in ops:
        ps.extend(op.param_names)
        bs.extend(op.buffer_names)
    return tuple(dict.fromkeys(ps)), tuple(dict.fromkeys(bs))


def _external_reads(ops, produced0: set) -> List[Variable]:
    """Variables read by ``ops`` that are not produced inside them."""
    produced = set(produced0)
    ext: Dict[str, Variable] = {}
    is_var = lambda x: isinstance(x, Variable)  # noqa: E731
    for op in ops:
        for leaf in jax.tree_util.tree_leaves((op.args, op.kwargs),
                                              is_leaf=is_var):
            if isinstance(leaf, Variable) and leaf.name not in produced \
                    and not leaf.is_parameter:
                ext.setdefault(leaf.name, leaf)
        produced.update(op.out_names)
    return list(ext.values())


class While:
    """ref control_flow.py:971 — Program-block while loop:

        i = fluid.layers.fill_constant([1], 'int64', 0)
        cond = fluid.layers.less_than(i, limit)
        loop = fluid.layers.While(cond)
        with loop.block():
            ...  # ops; update `cond` via less_than(..., cond=cond)

    The block's recorded ops replay inside ``lax.while_loop``; every name
    the block assigns (including in-place ``increment``/``less_than(cond=)``
    writes) is loop-carried, and its post-loop value shadows the name for
    subsequent ops — the 1.x mutation semantics."""

    def __init__(self, cond, is_test=False, name=None):
        if not isinstance(cond, Variable):
            raise InvalidArgumentError(
                "While needs a graph-mode bool Variable; for eager/traced "
                "loops use fluid.layers.while_loop")
        self.cond_var = cond
        self._cap = _BlockCapture()

    def block(self):
        return _WhileBlock(self)


class _WhileBlock:
    def __init__(self, w: While):
        self.w = w

    def __enter__(self):
        self.w._cap.__enter__()
        return self

    def __exit__(self, *exc):
        self.w._cap.__exit__(*exc)
        if exc[0] is not None:
            return False
        w = self.w
        body_ops = w._cap.ops
        pnames, bnames = _body_param_names(body_ops)
        if bnames:
            raise InvalidArgumentError(
                "While blocks cannot contain buffered layers (running-stat "
                "updates cannot cross lax.while_loop)")
        cond_name = w.cond_var.name
        # every name the body assigns is loop-carried (its post-loop value
        # shadows the name for subsequent ops); read-before-write names are
        # also external inputs supplying the initial carry
        carried = [n for n in dict.fromkeys(
            n for op in body_ops for n in op.out_names) if n != cond_name]
        ext = _external_reads(body_ops, set())
        ext = [e for e in ext if e.name != cond_name]
        ext_names = [e.name for e in ext]
        prog = default_main_program()
        carry_shapes = {}
        for n in carried:
            if n not in ext_names:  # write-only: synthesize a zeros init
                v = prog.vars.get(n)
                if v is None or any(d is None for d in v.shape):
                    raise InvalidArgumentError(
                        f"While: cannot infer an initial value for loop "
                        f"variable {n!r} (dynamic shape); assign it before "
                        f"the loop")
                carry_shapes[n] = (tuple(v.shape), v.dtype)

        def fn(pv, bv, cond0, *ext_vals, training=False, rngs=None):
            ext_env = dict(zip(ext_names, ext_vals))
            carry0 = tuple(
                ext_env[n] if n in ext_env
                else jnp.zeros(*carry_shapes[n]) for n in carried)

            def cond_f(state):
                c, _, _ = state
                return c.reshape(()).astype(bool)

            def body_f(state):
                c, it, carry = state
                env = dict(ext_env)
                env[cond_name] = c
                env.update(zip(carried, carry))
                # fresh randomness per iteration, not one draw for all
                key = (jax.random.fold_in(rngs, it)
                       if rngs is not None else None)
                run_ops(body_ops, env, pv, {}, training, rng=key)
                return (env[cond_name], it + 1,
                        tuple(env[n] for n in carried))

            final_c, _, final_carry = lax.while_loop(
                cond_f, body_f, (cond0, jnp.int32(0), carry0))
            return (final_c,) + final_carry

        # the op re-assigns the cond and every carried name: later ops see
        # post-loop values
        record_call(fn, w.cond_var, *ext,
                    out_names=[cond_name] + carried,
                    param_names=pnames, scoped=True, prefix="while")
        return False


class StaticRNN:
    """ref control_flow.py:449 — build-once stepwise RNN:

        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            word = rnn.step_input(x_seq)      # x_seq: [T, ...] seq-major
            prev = rnn.memory(shape=[-1, H], batch_ref=word)
            hidden = fluid.layers.fc(input=[word, prev], ...)
            rnn.update_memory(prev, hidden)
            rnn.step_output(hidden)
        outs = rnn()

    The with-block records ops once (exactly like the reference, which
    traces the block into a sub-Program re-executed per step); execution
    replays them under ``lax.scan`` over the leading (time) dim."""

    def __init__(self, name=None):
        self._cap = _BlockCapture()
        self._seq_inputs: List[tuple] = []   # (placeholder, source var)
        self._memories: List[dict] = []
        self._outputs: List[Variable] = []
        self._built = False

    def step(self):
        return _RNNStep(self)

    def step_input(self, x):
        if not isinstance(x, Variable):
            raise InvalidArgumentError(
                "StaticRNN.step_input needs a graph Variable [T, ...]; "
                "eager RNNs: paddle.nn.RNN")
        prog = default_main_program()
        ph = Variable(prog, prog.unique_name("rnn_x"), x.shape[1:], x.dtype)
        prog.add_var(ph)
        self._seq_inputs.append((ph, x))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=0):
        prog = default_main_program()
        if init is not None:
            mshape, mdtype = init.shape, init.dtype
        else:
            if shape is None or batch_ref is None:
                raise InvalidArgumentError(
                    "StaticRNN.memory needs init= or (shape= and "
                    "batch_ref=)")
            mshape = tuple(batch_ref.shape[ref_batch_dim_idx]
                           if d in (-1, None) else int(d) for d in shape)
            mdtype = batch_ref.dtype
        ph = Variable(prog, prog.unique_name("rnn_mem"), mshape, mdtype)
        prog.add_var(ph)
        self._memories.append({"ph": ph, "init": init,
                               "init_value": init_value, "new": None})
        return ph

    def update_memory(self, mem, new):
        for m in self._memories:
            if m["ph"] is mem:
                m["new"] = new
                return
        raise InvalidArgumentError("update_memory: unknown memory variable")

    def step_output(self, out):
        self._outputs.append(out)

    output = step_output

    def __call__(self, *args):
        if not self._built:
            raise InvalidArgumentError("StaticRNN: exit the step() block "
                                       "before calling rnn()")
        return self._result

    def _finalize(self, body_ops):
        pnames, bnames = _body_param_names(body_ops)
        if bnames:
            raise InvalidArgumentError(
                "StaticRNN steps cannot contain buffered layers")
        for m in self._memories:
            if m["new"] is None:
                raise InvalidArgumentError(
                    "StaticRNN: every memory needs update_memory()")
        if not self._outputs:
            raise InvalidArgumentError("StaticRNN: no step_output declared")
        seq_ph_names = [ph.name for ph, _ in self._seq_inputs]
        mem_ph_names = [m["ph"].name for m in self._memories]
        out_names = [o.name for o in self._outputs]
        new_names = [m["new"].name for m in self._memories]
        ext = _external_reads(
            body_ops, set(seq_ph_names) | set(mem_ph_names))
        ext = [e for e in ext
               if e.name not in {v.name for _, v in self._seq_inputs}]
        srcs = [v for _, v in self._seq_inputs]
        inits = [m["init"] for m in self._memories if m["init"] is not None]
        n_src = len(srcs)

        mems = self._memories

        def fn(pv, bv, *all_args, training=False, rngs=None):
            xs_vals = all_args[:n_src]
            rest = all_args[n_src:]
            init_vals = list(rest[:len(inits)])
            ext_vals = rest[len(inits):]
            ext_env = dict(zip([e.name for e in ext], ext_vals))
            carry0 = []
            ii = 0
            for m in mems:
                if m["init"] is not None:
                    carry0.append(init_vals[ii])
                    ii += 1
                else:
                    shape = tuple(m["ph"].shape)
                    carry0.append(jnp.full(shape, m["init_value"],
                                           m["ph"].dtype))

            def step_f(carry, t_and_xs):
                t_idx, xs_t = t_and_xs
                env = dict(ext_env)
                env.update(zip(seq_ph_names, xs_t))
                env.update(zip(mem_ph_names, carry))
                key = (jax.random.fold_in(rngs, t_idx)
                       if rngs is not None else None)
                run_ops(body_ops, env, pv, dict(bv), training, rng=key)
                new_carry = tuple(env[n] for n in new_names)
                outs = tuple(env[n] for n in out_names)
                return new_carry, outs

            T = xs_vals[0].shape[0]
            _, stacked = lax.scan(
                step_f, tuple(carry0),
                (jnp.arange(T, dtype=jnp.int32), tuple(xs_vals)))
            return stacked if len(out_names) > 1 else stacked[0]

        result = record_call(fn, *srcs, *inits, *ext,
                             param_names=pnames, scoped=True,
                             prefix="static_rnn")
        self._result = result
        self._built = True


class _RNNStep:
    def __init__(self, rnn: StaticRNN):
        self.rnn = rnn

    def __enter__(self):
        self.rnn._cap.__enter__()
        return self

    def __exit__(self, *exc):
        self.rnn._cap.__exit__(*exc)
        if exc[0] is None:
            self.rnn._finalize(self.rnn._cap.ops)
        return False


class Switch:
    """ref control_flow.py Switch (:fluid 1.x) — Program-block case
    dispatch:

        with fluid.layers.Switch() as switch:
            with switch.case(cond1):
                fluid.layers.assign(v1, output=out)
            with switch.case(cond2):
                ...
            with switch.default():
                fluid.layers.assign(v0, output=out)

    Each case's captured ops replay under a nested lax.cond chain; the
    FIRST true condition wins (reference semantics), and names assigned
    in untaken cases keep their prior values (assign into pre-created
    Variables, the 1.x idiom)."""

    def __init__(self, name=None):
        self._cases: List[tuple] = []   # (cond Variable | None, ops)
        self._entered = False

    def __enter__(self):
        self._entered = True
        return self

    def case(self, condition):
        if not isinstance(condition, Variable):
            raise InvalidArgumentError(
                "Switch.case needs a graph-mode bool Variable; eager "
                "dispatch is fluid.layers.case / switch_case")
        if any(c is None for c, _ in self._cases):
            raise InvalidArgumentError(
                "Switch: case() after default() would be unreachable "
                "(the reference rejects this ordering too)")
        return _SwitchCase(self, condition)

    def default(self):
        return _SwitchCase(self, None)

    def __exit__(self, *exc):
        if exc[0] is not None:
            return False
        if not self._cases:
            raise InvalidArgumentError("Switch: no case blocks recorded")
        # assemble one op: nested first-match-wins conds over the blocks.
        # only MUTATIONS of pre-existing names are the Switch's outputs —
        # temps created inside a case stay internal to its replay
        pre = self._pre_vars
        all_ops = [ops for _, ops in self._cases]
        assigned = list(dict.fromkeys(
            n for ops in all_ops for op in ops for n in op.out_names
            if n in pre))
        pnames, bnames = _body_param_names(
            [op for ops in all_ops for op in ops])
        if bnames:
            raise InvalidArgumentError(
                "Switch cases cannot contain buffered layers")
        ext = _external_reads(
            [op for ops in all_ops for op in ops], set())
        ext_names = [e.name for e in ext]
        conds = [c for c, _ in self._cases]
        # names assigned by cases but not read inside them still need an
        # incoming value (the no-match path keeps it): feed the program's
        # pre-Switch Variable of the same name
        prog = default_main_program()
        for n in assigned:
            if n not in ext_names:
                v = prog.vars.get(n)
                if v is None:
                    raise InvalidArgumentError(
                        f"Switch: assigned name {n!r} has no value before "
                        f"the Switch (create it with fill_constant first)")
                ext.append(v)
                ext_names.append(n)

        def fn(pv, bv, *args, training=False, rngs=None):
            n_conds = sum(1 for c in conds if c is not None)
            cond_vals = list(args[:n_conds])
            ext_vals = args[n_conds:]
            base_env = dict(zip(ext_names, ext_vals))

            def run_block(ops):
                env = dict(base_env)
                run_ops(ops, env, pv, {}, training, rng=rngs)
                return tuple(env[n] for n in assigned)

            def chain(i, ci):
                c, ops = self._cases[i]
                if c is None:  # default: unconditional
                    return run_block(ops)
                this = lambda: run_block(ops)  # noqa: E731
                if i + 1 < len(self._cases):
                    rest = lambda: chain(i + 1, ci + 1)  # noqa: E731
                else:
                    rest = lambda: tuple(  # no match: keep incoming
                        base_env[n] for n in assigned)  # noqa: E731
                return lax.cond(cond_vals[ci].reshape(()).astype(bool),
                                this, rest)

            return chain(0, 0)

        cond_args = [c for c in conds if c is not None]
        record_call(fn, *cond_args, *ext, out_names=assigned,
                    param_names=pnames, scoped=True, prefix="switch")
        return False


class _SwitchCase:
    def __init__(self, switch: Switch, condition):
        self._switch = switch
        self._cond = condition
        self._cap = _BlockCapture()

    def __enter__(self):
        self._cap.__enter__()
        # the mutable surface is every name existing when a case OPENS —
        # variables created between cases are assignable by later cases,
        # but temps created INSIDE earlier cases stay internal
        sw = self._switch
        if not hasattr(sw, "_pre_vars"):
            sw._pre_vars = set()
            sw._case_internal = set()
        sw._pre_vars |= (set(self._cap.pre_vars) - sw._case_internal)
        return self

    def __exit__(self, *exc):
        prog = default_main_program()
        self._cap.__exit__(*exc)
        if exc[0] is None:
            self._switch._case_internal |= (
                set(prog.vars) - set(self._cap.pre_vars))
            self._switch._cases.append((self._cond, self._cap.ops))
        return False


class IfElse:
    """ref control_flow.py IfElse — row-wise conditional: ``cond`` is a
    [N, 1] bool mask; the true block sees (conceptually) the rows where
    cond holds, the false block the rest, and outputs merge row-wise.

    Dense form: both blocks run on the FULL batch (XLA computes both
    sides of a select anyway) and ``output()`` pairs merge with
    ``where(cond, true_row, false_row)`` — mathematically the reference's
    split/merge for elementwise blocks, without LoD scatter plumbing."""

    def __init__(self, cond, name=None):
        if not isinstance(cond, Variable):
            raise InvalidArgumentError(
                "IfElse needs a graph-mode bool Variable mask [N, 1]")
        self._cond = cond
        self._blocks = {}   # True/False -> (ops, outputs)
        self._cur = None
        self._cur_outs: List[Variable] = []
        self._cap = None

    def _block(self, flag):
        return _IfElseBlock(self, flag)

    def true_block(self):
        return self._block(True)

    def false_block(self):
        return self._block(False)

    def input(self, x):
        """Inside a block: the reference slices x to the branch's rows;
        dense form passes it through (merging happens at output())."""
        return x

    def output(self, *outs):
        if self._cur is None:
            raise InvalidArgumentError(
                "IfElse.output() must be called inside true_block()/"
                "false_block()")
        self._cur_outs.extend(outs)

    def __call__(self):
        t = self._blocks.get(True)
        f = self._blocks.get(False)
        if not t or not f:
            raise InvalidArgumentError(
                "IfElse: both true_block() and false_block() must run "
                "and declare output()s")
        t_ops, t_outs = t
        f_ops, f_outs = f
        if len(t_outs) != len(f_outs):
            raise InvalidArgumentError(
                "IfElse: the two blocks declared different output counts")
        cond = self._cond
        results = []
        for to, fo in zip(t_outs, f_outs):
            def merge(c, a, b):
                c = jnp.asarray(c)
                mask = c.reshape(c.shape[0], *([1] * (jnp.asarray(a).ndim - 1)))
                return jnp.where(mask.astype(bool), a, b)

            results.append(record_call(merge, cond, to, fo,
                                       prefix="ifelse_merge"))
        return results


class _IfElseBlock:
    def __init__(self, ie: IfElse, flag: bool):
        self._ie = ie
        self._flag = flag
        self._cap = _BlockCapture()

    def __enter__(self):
        self._ie._cur = self._flag
        self._ie._cur_outs = []
        self._cap.__enter__()
        return self

    def __exit__(self, *exc):
        self._cap.__exit__(*exc)
        ie = self._ie
        if exc[0] is None:
            # re-append the block's ops: both branches execute on the full
            # batch (dense row-select replaces the reference's LoD split)
            prog = default_main_program()
            for op in self._cap.ops:
                prog.append_op(op)
            ie._blocks[self._flag] = (self._cap.ops, list(ie._cur_outs))
        ie._cur = None
        return False

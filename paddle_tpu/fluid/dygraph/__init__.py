"""paddle.fluid.dygraph — the 1.x imperative API.

Parity: python/paddle/fluid/dygraph/ (nn.py layer classes with 1.x
constructor signatures, base.py guard/to_variable, checkpoint.py
save/load_dygraph, parallel.py).  The classes here are thin adapters
over the 2.0 layers: same parameters, 1.x argument names, built-in
``act=`` activations — there is ONE implementation underneath.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

import paddle_tpu as _p
from paddle_tpu import nn as _nn
from paddle_tpu.nn import functional as _F
from ...framework.errors import UnimplementedError

from paddle_tpu.nn import Layer  # noqa: F401
from paddle_tpu.nn import Sequential  # noqa: F401
from paddle_tpu.nn import ParameterList, LayerList  # noqa: F401
from paddle_tpu.nn import Pool2D, BilinearTensorProduct  # noqa: F401
from paddle_tpu.distributed import (  # noqa: F401
    DataParallel, ParallelEnv, prepare_context,
)
from paddle_tpu import jit  # noqa: F401
from paddle_tpu.jit import ProgramTranslator, TracedLayer  # noqa: F401
from paddle_tpu.jit import to_static as declarative  # noqa: F401
from paddle_tpu import no_grad, grad  # noqa: F401
from paddle_tpu import to_variable  # noqa: F401

__all__ = [
    "Layer", "guard", "to_variable", "no_grad", "grad", "enabled",
    "Linear", "Conv2D", "Pool2D", "BatchNorm", "Embedding", "LayerNorm",
    "Dropout", "GRUUnit", "PRelu", "BilinearTensorProduct", "NCE",
    "Sequential", "ParameterList", "LayerList", "DataParallel",
    "ParallelEnv", "prepare_context", "save_dygraph", "load_dygraph",
    "declarative", "ProgramTranslator", "TracedLayer",
]


@contextlib.contextmanager
def guard(place=None):
    """1.x dygraph scope (ref: dygraph/base.py guard) — eager is the only
    mode here, so this only optionally pins the device."""
    if place is not None:
        _p.set_device(place)
    yield


def enabled():
    """Parity: fluid.dygraph.enabled — always True (single runtime)."""
    return True


_OPT_SLOT_SUFFIXES = (".moment", ".moment1", ".moment2", ".master",
                      ".squared", ".linear", ".velocity", ".inf_norm",
                      ".mean_square", ".mean_grad", ".avg_squared_grad",
                      ".avg_squared_update")


def save_dygraph(state_dict, model_path):
    """Ref: dygraph/checkpoint.py save_dygraph — chooses .pdparams or
    .pdopt by content like the reference does.  Optimizer state_dicts
    here carry the step 'count', 'LR_Scheduler', or dotted slot keys."""
    is_opt = ("count" in state_dict or "LR_Scheduler" in state_dict
              or any(k.endswith(_OPT_SLOT_SUFFIXES) for k in state_dict)
              or any(not hasattr(v, "shape") for v in state_dict.values()))
    suffix = ".pdopt" if is_opt else ".pdparams"
    return _p.save(state_dict, model_path + suffix)


def load_dygraph(model_path, **configs):
    """Ref: dygraph/checkpoint.py load_dygraph → (param_dict, opt_dict);
    either may be None when the file doesn't exist."""
    import os

    params = opt = None
    if os.path.exists(model_path + ".pdparams"):
        params = _p.load(model_path + ".pdparams")
    if os.path.exists(model_path + ".pdopt"):
        opt = _p.load(model_path + ".pdopt")
    if params is None and opt is None:
        raise ValueError(
            f"no .pdparams/.pdopt found for prefix {model_path!r}")
    return params, opt


class Linear(Layer):
    """1.x Linear(input_dim, output_dim, act=...) (ref:
    fluid/dygraph/nn.py:893) over the 2.0 weight layout."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self._act = act
        self._linear = _nn.Linear(input_dim, output_dim,
                                  weight_attr=param_attr,
                                  bias_attr=bias_attr)
        self.weight = self._linear.weight
        self.bias = self._linear.bias

    def forward(self, input):
        out = self._linear(input)
        return getattr(_F, self._act)(out) if self._act else out


class Conv2D(Layer):
    """1.x Conv2D(num_channels, num_filters, filter_size, ..., act=)
    (ref: fluid/dygraph/nn.py:44)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        self._act = act
        self._conv = _nn.Conv2D(num_channels, num_filters, filter_size,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups or 1,
                                weight_attr=param_attr, bias_attr=bias_attr)
        self.weight = self._conv.weight
        self.bias = self._conv.bias

    def forward(self, input):
        out = self._conv(input)
        return getattr(_F, self._act)(out) if self._act else out


class BatchNorm(Layer):
    """1.x BatchNorm(num_channels, act=, is_test=, momentum=, ...)
    (ref: fluid/dygraph/nn.py:1145).  ``momentum`` keeps paddle's
    running-stat convention (new = m·old + (1-m)·batch)."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__()
        self._act = act
        self._bn = _nn.BatchNorm(num_channels, momentum=momentum,
                                 epsilon=epsilon, weight_attr=param_attr,
                                 bias_attr=bias_attr,
                                 data_format=data_layout,
                                 use_global_stats=use_global_stats)
        self.weight = self._bn.weight
        self.bias = self._bn.bias
        if is_test:
            self.eval()

    def forward(self, input):
        out = self._bn(input)
        return getattr(_F, self._act)(out) if self._act else out


class Embedding(Layer):
    """1.x Embedding(size=[vocab, dim], padding_idx=, ...) (ref:
    fluid/dygraph/nn.py:1494)."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__()
        if is_distributed:
            raise UnimplementedError(
                "is_distributed embeddings: use "
                "paddle.distributed.meta_parallel.VocabParallelEmbedding "
                "(sharded tables replace the parameter server)")
        self._emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                                  weight_attr=param_attr)
        self.weight = self._emb.weight

    def forward(self, input):
        return self._emb(input)


class LayerNorm(Layer):
    """1.x LayerNorm(normalized_shape, scale=, shift=, act=) (ref:
    fluid/dygraph/nn.py:1654)."""

    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__()
        self._act = act
        self._ln = _nn.LayerNorm(normalized_shape, epsilon=epsilon,
                                 weight_attr=param_attr if scale else False,
                                 bias_attr=bias_attr if shift else False)

    def forward(self, input):
        out = self._ln(input)
        return getattr(_F, self._act)(out) if self._act else out


class Dropout(Layer):
    """1.x Dropout(p, dropout_implementation=) (ref:
    fluid/dygraph/nn.py:1385)."""

    def __init__(self, p=0.5, seed=None, dropout_implementation=
                 "downgrade_in_infer", is_test=False):
        super().__init__()
        self._mode = ("downscale_in_infer"
                      if dropout_implementation == "downgrade_in_infer"
                      else "upscale_in_train")
        self._p = p
        if is_test:
            self.eval()

    def forward(self, input):
        return _F.dropout(input, p=self._p, training=self.training,
                          mode=self._mode)


class PRelu(Layer):
    """1.x PRelu(mode, ...) (ref: fluid/dygraph/nn.py:2244): mode 'all'
    (one alpha), 'channel' (per channel), 'element' (per element,
    requires input_shape)."""

    def __init__(self, mode, channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            if channel is None:
                raise ValueError("channel mode needs `channel`")
            shape = [channel]
        elif mode == "element":
            if input_shape is None:
                raise ValueError("element mode needs `input_shape`")
            shape = list(input_shape)[1:]
        else:
            raise ValueError(f"unknown PRelu mode {mode!r}")
        from paddle_tpu.nn.initializer import Constant

        self.weight = self.create_parameter(
            shape, attr=param_attr, default_initializer=Constant(0.25))
        self._mode = mode

    def forward(self, input):
        x = jnp.asarray(input)
        a = self.weight.value
        if self._mode == "channel" and x.ndim > 2:
            a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, a * x)


class GRUUnit(Layer):
    """1.x GRUUnit — single-step GRU cell with the fused 1.x parameter
    layout (ref: fluid/dygraph/nn.py:1828 over operators/gru_unit_op).
    size = 3 × hidden."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        hidden = size // 3
        self._hidden = hidden
        self._origin_mode = origin_mode
        self._act = activation
        self._gate_act = gate_activation
        # 1.x layout: weight [hidden, 3*hidden] (update|reset gates first
        # 2*hidden, candidate last hidden), bias [1, 3*hidden]
        self.weight = self.create_parameter([hidden, 3 * hidden],
                                            attr=param_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([1, 3 * hidden], attr=bias_attr,
                                           is_bias=True))

    def forward(self, input, hidden):
        """input [B, 3*hidden] (pre-projected x), hidden [B, hidden] →
        (new_hidden, reset_hidden_prev, gate)."""
        x = jnp.asarray(input)
        h = jnp.asarray(hidden)
        H = self._hidden
        w_gates = self.weight.value[:, : 2 * H]
        w_cand = self.weight.value[:, 2 * H:]
        gates = x[:, : 2 * H] + h @ w_gates
        if self.bias is not None:
            gates = gates + self.bias.value[0, : 2 * H]
        gact = getattr(_F, self._gate_act)
        u, r = jnp.split(gact(gates), 2, axis=-1)
        rhp = r * h
        c = x[:, 2 * H:] + rhp @ w_cand
        if self.bias is not None:
            c = c + self.bias.value[0, 2 * H:]
        c = getattr(_F, self._act)(c)
        if self._origin_mode:
            new_h = u * h + (1 - u) * c
        else:
            new_h = (1 - u) * h + u * c
        gate = jnp.concatenate([u, r, c], axis=-1)
        return new_h, rhp, gate


class NCE(Layer):
    """1.x NCE layer — noise-contrastive estimation loss head (ref:
    fluid/dygraph/nn.py:2006 over operators/nce_op).  Holds the
    [num_total_classes, dim] weight table; forward computes the NCE loss
    against ``sample_weights`` uniform negative sampling."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False, dtype="float32"):
        super().__init__()
        if sampler != "uniform" or custom_dist is not None:
            raise UnimplementedError(
                "NCE: only uniform negative sampling is implemented")
        self._num_classes = num_total_classes
        self._num_neg = num_neg_samples
        self.weight = self.create_parameter([num_total_classes, dim],
                                            attr=param_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_total_classes, 1],
                                           attr=bias_attr, is_bias=True))

    def forward(self, input, label, sample_weight=None):
        from paddle_tpu.nn.layer_base import current_rng_key

        x = jnp.asarray(input)  # [B, D]
        lab = jnp.asarray(label).reshape(-1)  # [B]
        B = x.shape[0]
        key = current_rng_key()
        import jax

        neg = jax.random.randint(key, (B, self._num_neg), 0,
                                 self._num_classes)
        ids = jnp.concatenate([lab[:, None], neg], axis=1)  # [B, 1+K]
        w = self.weight.value[ids]  # [B, 1+K, D]
        logits = jnp.einsum("bd,bkd->bk", x, w)
        if self.bias is not None:
            logits = logits + self.bias.value[ids, 0]
        # NCE: positive → label 1, negatives → label 0, uniform noise
        logq = jnp.log(jnp.asarray(self._num_neg / self._num_classes,
                                   x.dtype))
        logits = logits - logq
        targets = jnp.zeros_like(logits).at[:, 0].set(1.0)
        loss = _F.binary_cross_entropy_with_logits(logits, targets,
                                                   reduction="none")
        return loss.sum(-1, keepdims=True)

"""paddle.fluid — the 1.x root namespace.

Parity: python/paddle/fluid/__init__.py.  Everything here is an adapter
over the one TPU-native implementation: layer functions (fluid.layers),
1.x dygraph classes (fluid.dygraph), 1.x optimizer spellings
(fluid.optimizer), places/ParamAttr/initializer/regularizer re-exports,
and honest Program-machinery shims shared with paddle.static.  A 1.x
script migrating to this framework finds every fluid name it touches:
implemented, or raising with the eager replacement spelled out.
"""
from __future__ import annotations

from paddle_tpu.framework import (  # noqa: F401
    CPUPlace, TPUPlace, CUDAPlace, XPUPlace,
    set_flags, get_flags,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
from paddle_tpu import CUDAPinnedPlace  # noqa: F401
from paddle_tpu.nn import ParamAttr  # noqa: F401
from paddle_tpu.nn.layer_base import Parameter  # noqa: F401
from paddle_tpu import in_dygraph_mode  # noqa: F401
from paddle_tpu.framework.serialization import save, load  # noqa: F401

from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import optimizer  # noqa: F401
from . import initializer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import io  # noqa: F401
from . import core  # noqa: F401
from . import metrics  # noqa: F401
from . import unique_name  # noqa: F401
from . import contrib  # noqa: F401
from .param_attr import WeightNormParamAttr  # noqa: F401
from paddle_tpu import profiler  # noqa: F401

# 1.x entry points: the lazy-graph Program/Executor (static/graph.py)
from paddle_tpu.static import (  # noqa: F401
    cpu_places, cuda_places, name_scope,
    Program, Executor, CompiledProgram, ParallelExecutor, Scope,
    Variable, global_scope, scope_guard, program_guard,
    default_main_program, default_startup_program, BuildStrategy,
    ExecutionStrategy,
)
# fluid.data declares a graph feed slot (a symbolic Variable), unlike
# paddle.static.data which doubles as the 2.0 export InputSpec
from paddle_tpu.static.graph import data  # noqa: F401
from paddle_tpu.static import (  # noqa: F401
    save_inference_model, load_inference_model, load_program_state,
    set_program_state,
)
from paddle_tpu.fluid.dygraph import guard as dygraph_guard  # noqa: F401
from paddle_tpu import (  # noqa: F401
    enable_dygraph, disable_dygraph, enable_static, disable_static,
)
from paddle_tpu.io import DataLoader  # noqa: F401
from paddle_tpu.io import InMemoryDataset  # noqa: F401


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """fluid.embedding / fluid.input.embedding — the op-builder form;
    points at the Layer (same contract as fluid.layers.embedding)."""
    from ..framework.errors import UnimplementedError

    raise UnimplementedError(
        "fluid.embedding builds Program ops; construct "
        "paddle.nn.Embedding(size[0], size[1]) once and call it "
        "(fluid.dygraph.Embedding keeps the 1.x size=[v,d] spelling)")


def one_hot(input, depth, allow_out_of_range=False):
    return layers.one_hot(input, depth, allow_out_of_range)


class LoDTensor:
    """The reference's ragged runtime value (lod_tensor.h:114).  The
    dense-padding policy (SURVEY §7g) replaces LoD with plain arrays +
    lengths; constructing one raises with that guidance."""

    def __init__(self, *a, **k):
        from ..framework.errors import UnimplementedError

        raise UnimplementedError(
            "LoDTensor: ragged batches are dense-padded arrays + a "
            "lengths tensor here (SURVEY §7g) — use "
            "paddle.nn.functional.sequence_mask for masking")


def create_lod_tensor(data, recursive_seq_lens, place=None):
    LoDTensor()


def create_random_int_lodtensor(*a, **k):
    LoDTensor()

"""paddle.fluid.param_attr — ParamAttr + WeightNormParamAttr."""
from paddle_tpu.nn import ParamAttr  # noqa: F401
from paddle_tpu.static import WeightNormParamAttr  # noqa: F401

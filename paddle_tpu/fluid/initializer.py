"""paddle.fluid.initializer — 1.x initializer names.

Parity: python/paddle/fluid/initializer.py — the 1.x surface exposes
both class names (ConstantInitializer) and aliases (Constant); all map
to the 2.0 nn.initializer implementations.
"""
from paddle_tpu.nn.initializer import (  # noqa: F401
    Bilinear, Constant, Normal, TruncatedNormal, Uniform, XavierNormal,
    XavierUniform, KaimingNormal, KaimingUniform, Assign,
)

# 1.x class spellings
ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
NumpyArrayInitializer = Assign


class Xavier(XavierNormal):
    """1.x Xavier(uniform=True) switch (ref: initializer.py Xavier)."""

    def __new__(cls, uniform=True, fan_in=None, fan_out=None, seed=0):
        if uniform:
            return XavierUniform(fan_in=fan_in, fan_out=fan_out)
        return XavierNormal(fan_in=fan_in, fan_out=fan_out)


class MSRA(KaimingNormal):
    """1.x MSRA(uniform=True) switch (ref: initializer.py:639
    MSRAInitializer — uniform is the DEFAULT there)."""

    def __new__(cls, uniform=True, fan_in=None, seed=0):
        if uniform:
            return KaimingUniform(fan_in=fan_in)
        return KaimingNormal(fan_in=fan_in)


XavierInitializer = Xavier
MSRAInitializer = MSRA
BilinearInitializer = Bilinear

"""paddle.fluid.io — 1.x persistence + reader decorators.

Parity: python/paddle/fluid/io.py (save/load_persistables:598,966,
save/load_inference_model:1164,1374, program-state save/load:1669,1730)
+ the reader decorators re-exported there.
"""
from __future__ import annotations

from paddle_tpu.framework.serialization import save, load  # noqa: F401
from paddle_tpu.static import (  # noqa: F401
    save_inference_model, load_inference_model, load_program_state,
    set_program_state,
)
from paddle_tpu.io import DataLoader  # noqa: F401
from paddle_tpu.reader import (  # noqa: F401
    cache, map_readers, buffered, compose, chain, shuffle,
    firstn, xmap_readers, multiprocess_reader,
)
from paddle_tpu import batch  # noqa: F401


def _persistables(what):
    from ..framework.errors import UnimplementedError

    raise UnimplementedError(
        f"fluid.io.{what} walked the Program for persistable Variables; "
        f"state lives in Layers here — paddle.save(layer.state_dict(), "
        f"path) / layer.set_state_dict(paddle.load(path))")


def save_persistables(executor, dirname, main_program=None, filename=None):
    _persistables("save_persistables")


def load_persistables(executor, dirname, main_program=None, filename=None):
    _persistables("load_persistables")


def save_params(executor, dirname, main_program=None, filename=None):
    _persistables("save_params")


def load_params(executor, dirname, main_program=None, filename=None):
    _persistables("load_params")


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    _persistables("save_vars")


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    _persistables("load_vars")

"""paddle.fluid.io — 1.x persistence + reader decorators.

Parity: python/paddle/fluid/io.py (save/load_persistables:598,966,
save_params:598, save_vars:168, save/load_inference_model:1164,1374,
program-state save/load:1669,1730) + the reader decorators re-exported
there.

Since round 5 the save side emits the REFERENCE'S binary formats
(framework/paddle_export.py — LoDTensor streams, sorted-name combined
files, ``__model__`` ProgramDesc) and the load side reads them back
through framework/paddle_import.py, so artifacts round-trip both with
this framework and with reference-Paddle tooling (``protoc --decode``
against framework.proto is part of the test gate).
"""
from __future__ import annotations

from paddle_tpu.framework.serialization import save, load  # noqa: F401
from paddle_tpu.static import (  # noqa: F401
    save_inference_model, load_inference_model, load_program_state,
    set_program_state,
)
from paddle_tpu.io import DataLoader  # noqa: F401
from paddle_tpu.reader import (  # noqa: F401
    cache, map_readers, buffered, compose, chain, shuffle,
    firstn, xmap_readers, multiprocess_reader,
)
from paddle_tpu import batch  # noqa: F401


def _resolve_state(main_program, params_only: bool):
    """State dict of a Program (the 1.x flow), a Layer (eager convenience),
    or a plain {name: array} dict."""
    from ..nn.layer_base import Layer
    from ..static.graph import Program, default_main_program

    import numpy as np

    if main_program is None:
        main_program = default_main_program()
    if isinstance(main_program, Program):
        if params_only:
            return {n: np.asarray(v) for n, v in main_program.scope.items()}
        return main_program.state_dict()
    if isinstance(main_program, Layer):
        if params_only:
            return {n: np.asarray(p.value)
                    for n, p in main_program.named_parameters()}
        return {k: np.asarray(v)
                for k, v in main_program.state_dict().items()}
    if isinstance(main_program, dict):
        return {n: np.asarray(v) for n, v in main_program.items()}
    from ..framework.errors import InvalidArgumentError

    raise InvalidArgumentError(
        "main_program must be a static Program, a Layer, or a "
        f"{{name: array}} dict, got {type(main_program).__name__}")


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Write every persistable (parameters + buffers) in the REFERENCE
    binary format (ref: fluid/io.py:598) — per-variable LoDTensor files,
    or one sorted-name combined file when ``filename`` is given, plus a
    ``__model__`` ProgramDesc naming them."""
    from ..framework.paddle_export import save_reference_state

    save_reference_state(_resolve_state(main_program, params_only=False),
                         dirname, filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    """Parameters only (ref: fluid/io.py:598 save_params)."""
    from ..framework.paddle_export import save_reference_state

    save_reference_state(_resolve_state(main_program, params_only=True),
                         dirname, filename=filename)


class _VarView:
    """What a save_vars/load_vars ``predicate`` receives — the Variable
    attributes 1.x predicates read (``lambda var: var.persistable``,
    ``var.name.startswith(...)``; ref fluid/io.py:168)."""

    __slots__ = ("name", "shape", "persistable")

    def __init__(self, name, value):
        import numpy as np

        self.name = name
        self.shape = tuple(np.shape(value))
        self.persistable = True


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Ref: fluid/io.py:168 — explicit variable list (names or Variables)
    filtered by ``predicate`` (which receives a Variable-like view, as in
    the reference)."""
    state = _resolve_state(main_program, params_only=False)
    if vars is not None:
        names = [v if isinstance(v, str) else v.name for v in vars]
        missing = [n for n in names if n not in state]
        if missing:
            from ..framework.errors import NotFoundError

            raise NotFoundError(f"save_vars: no such variables {missing}")
        state = {n: state[n] for n in names}
    if predicate is not None:
        state = {n: v for n, v in state.items()
                 if predicate(_VarView(n, v))}
    from ..framework.paddle_export import save_reference_state

    save_reference_state(state, dirname, filename=filename)


def _adapt_program_names(sd, program, partial: bool = False):
    """Auto-generated names here carry a per-Program prefix (``_<idx>_``,
    static/graph.py unique_name) the way the reference's global
    unique_name counters shift across rebuilds — a checkpoint from one
    build must load into an identically-built fresh Program.  Exact names
    first; non-matches map by the idx-stripped name (builder order is
    deterministic, so stripped names are unique per program).  Entries
    that map nowhere raise (Program.set_state_dict would silently ignore
    them and the restore would be partial) — unless ``partial`` (the
    explicit-subset load_vars flow)."""
    import re

    strip = lambda n: re.sub(r"^_\d+_", "", n)  # noqa: E731
    targets = list(program.scope) + list(program.buffers)
    by_stripped = {}
    for n in targets:
        by_stripped.setdefault(strip(n), []).append(n)
    out = {}
    dropped = []
    for n, v in sd.items():
        if n in program.scope or n in program.buffers:
            out[n] = v
            continue
        cands = by_stripped.get(strip(n), [])
        if len(cands) == 1:
            out[cands[0]] = v
        else:
            dropped.append(n)
    if dropped and not partial:
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"checkpoint variables {dropped[:5]}"
            f"{'…' if len(dropped) > 5 else ''} have no (unique) "
            "counterpart in the target Program — was it built "
            "differently? (load_vars with an explicit list allows "
            "partial restores)")
    return out


def _load_into(dirname, main_program, filename):
    from ..framework.paddle_import import load_reference_state_dict
    from ..nn.layer_base import Layer
    from ..static.graph import Program, default_main_program

    sd = load_reference_state_dict(dirname, params_filename=filename)
    target = main_program if main_program is not None \
        else default_main_program()
    if isinstance(target, Program):
        target.set_state_dict(_adapt_program_names(sd, target))
    elif isinstance(target, Layer):
        from ..framework.paddle_import import adapt_state_dict

        target.set_state_dict(adapt_state_dict(sd, target))
    else:
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            "load target must be a static Program or a Layer")
    return sd


def load_persistables(executor, dirname, main_program=None, filename=None):
    """Read a reference-format checkpoint back into the Program/Layer
    (ref: fluid/io.py:966)."""
    return _load_into(dirname, main_program, filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return _load_into(dirname, main_program, filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from ..framework.paddle_import import load_reference_state_dict
    from ..nn.layer_base import Layer
    from ..static.graph import Program, default_main_program

    sd = load_reference_state_dict(dirname, params_filename=filename)
    if vars is not None:
        names = [v if isinstance(v, str) else v.name for v in vars]
        missing = [n for n in names if n not in sd]
        if missing:
            from ..framework.errors import NotFoundError

            raise NotFoundError(
                f"load_vars: checkpoint at {dirname!r} has no variables "
                f"{missing}")
        sd = {n: sd[n] for n in names}
    if predicate is not None:
        sd = {n: v for n, v in sd.items()
              if predicate(_VarView(n, v))}
    target = main_program if main_program is not None \
        else default_main_program()
    if isinstance(target, Program):
        target.set_state_dict(_adapt_program_names(sd, target,
                                                   partial=True))
    elif isinstance(target, Layer):
        # explicit subset: apply exact-name matches (adapt_state_dict's
        # structural mapping needs the full set to line groups up)
        target.set_state_dict(sd)
    return sd

"""paddle.fluid.unique_name — alias of paddle.utils.unique_name."""
from paddle_tpu.utils.unique_name import (  # noqa: F401
    generate, guard, switch, UniqueNameGenerator,
)

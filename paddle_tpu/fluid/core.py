"""paddle.fluid.core — the pybind module's Python-visible surface.

Parity: paddle/fluid/pybind/pybind.cc:353 (module ``core_avx``).  The
reference's core is the C++ bridge; here jax IS the bridge (SURVEY §7,
L4 row), so this module exposes the handful of core names migration
code actually touches: places, flag access, device queries.  Everything
op-level (``core.ops.*``) is deliberately absent — the generated
per-op fast path is replaced by the public tensor/functional API.
"""
from __future__ import annotations

from paddle_tpu.framework import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace,
)
from paddle_tpu import CUDAPinnedPlace  # noqa: F401
from paddle_tpu.framework import get_flags, set_flags  # noqa: F401


def is_compiled_with_cuda() -> bool:
    from paddle_tpu.framework import is_compiled_with_cuda as f

    return f()


def is_compiled_with_xpu() -> bool:
    return False


def get_cuda_device_count() -> int:
    return 0


def globals():  # noqa: A001  (reference name: core.globals() flag map)
    """Flag registry view (ref: pybind's global_value_getter_setter) —
    read-only mapping of FLAGS_* values."""
    from paddle_tpu.framework import flags as _flags

    return {f"FLAGS_{k}" if not k.startswith("FLAGS_") else k: v["value"]
            for k, v in _flags._REGISTRY.items()}


class _OpsShim:
    """core.ops.* — the build-time generated per-op C functions
    (op_function_generator.cc:35).  Dygraph layers here call jnp
    directly; anything poking core.ops gets a pointed error."""

    def __getattr__(self, name):
        from ..framework.errors import UnimplementedError

        raise UnimplementedError(
            f"core.ops.{name}: the generated pybind fast path does not "
            f"exist — call the public API (paddle.{name} / "
            f"paddle.nn.functional.{name}) which lowers to XLA directly")


ops = _OpsShim()


class EOFException(Exception):
    """Raised by Executor.run when a started py_reader's pass ends
    (ref: paddle/fluid/framework/../platform EOFException → the Python
    ``fluid.core.EOFException`` 1.x training loops catch)."""

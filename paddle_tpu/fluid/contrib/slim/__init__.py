"""fluid.contrib.slim compat — re-exports paddle_tpu.slim."""
from paddle_tpu.slim import quantization  # noqa: F401

"""fluid.contrib compat namespace (reference: python/paddle/fluid/contrib)."""
from . import slim  # noqa: F401

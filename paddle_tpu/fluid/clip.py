"""paddle.fluid.clip — 1.x gradient-clip names.

Parity: python/paddle/fluid/clip.py — GradientClipBy{Value,Norm,
GlobalNorm} are the same strategies the 2.0 optimizers consume
(optimizer/clip.py); set_gradient_clip's Program-global registration
maps to the optimizer's ``grad_clip=`` argument.
"""
from paddle_tpu.optimizer.clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)

GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def set_gradient_clip(clip, param_list=None, program=None):
    from ..framework.errors import UnimplementedError

    raise UnimplementedError(
        "set_gradient_clip registered a clip on the global Program; pass "
        "grad_clip=GradientClipBy...(...) to the optimizer instead "
        "(the 2.0-recommended spelling, which the reference also "
        "deprecates toward)")

"""paddle.fluid.optimizer — 1.x optimizer names and conventions.

Parity: python/paddle/fluid/optimizer.py (SGD:1185-area class list).
The 1.x classes differ from 2.0 in name (``SGDOptimizer``) and argument
spelling (``parameter_list``/``regularization``); each alias below
adapts those and delegates — one optimizer implementation underneath
(paddle_tpu/optimizer).  Program-rewriting wrappers (Pipeline/Recompute/
GradientMerge/Lookahead...) map to the fleet DistributedStrategy or the
2.0 weight-averaging optimizers.
"""
from __future__ import annotations

from paddle_tpu import optimizer as _opt
from ..framework.errors import UnimplementedError

__all__ = [
    "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer", "Adam",
    "AdamOptimizer", "Adamax", "AdamaxOptimizer", "Adagrad",
    "AdagradOptimizer", "Adadelta", "AdadeltaOptimizer", "RMSProp",
    "RMSPropOptimizer", "Ftrl", "FtrlOptimizer", "Lamb", "LambOptimizer",
    "LarsMomentum", "LarsMomentumOptimizer", "ExponentialMovingAverage",
    "ModelAverage", "LookaheadOptimizer", "PipelineOptimizer",
    "RecomputeOptimizer", "GradientMergeOptimizer", "DGCMomentumOptimizer",
    "DpsgdOptimizer", "DecayedAdagradOptimizer",
]


def _one_x(cls, lr_default=0.001, **renames):
    """Build a 1.x-convention subclass of a 2.0 optimizer: accepts
    ``parameter_list`` and ``regularization`` spellings."""

    class OneX(cls):
        def __init__(self, learning_rate=lr_default, *args,
                     parameter_list=None, regularization=None,
                     grad_clip=None, name=None, **kwargs):
            # positional extras (e.g. Momentum's momentum, Adam's betas)
            # line up with the 2.0 signature and pass straight through
            kwargs.setdefault("parameters", parameter_list)
            kwargs.setdefault("weight_decay", regularization)
            kwargs.setdefault("grad_clip", grad_clip)
            super().__init__(learning_rate, *args, **kwargs)

    OneX.__name__ = cls.__name__ + "Optimizer"
    OneX.__qualname__ = OneX.__name__
    OneX.__doc__ = (f"1.x spelling of paddle.optimizer.{cls.__name__} "
                    f"(parameter_list/regularization arg names).")
    return OneX


SGDOptimizer = _one_x(_opt.SGD)
MomentumOptimizer = _one_x(_opt.Momentum)
AdamOptimizer = _one_x(_opt.Adam)
AdamaxOptimizer = _one_x(_opt.Adamax)
AdagradOptimizer = _one_x(_opt.Adagrad)
AdadeltaOptimizer = _one_x(_opt.Adadelta)
RMSPropOptimizer = _one_x(_opt.RMSProp)
LambOptimizer = _one_x(_opt.Lamb)

# the reference also exposes the short names from fluid.optimizer
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer

FtrlOptimizer = _one_x(_opt.Ftrl)
Ftrl = FtrlOptimizer

LarsMomentumOptimizer = _one_x(_opt.Lars)
LarsMomentum = LarsMomentumOptimizer

from paddle_tpu.optimizer import (  # noqa: E402
    ExponentialMovingAverage as _EMA,
    ModelAverage as _MA,
    Lookahead as _Lookahead,
)


class ExponentialMovingAverage(_EMA):
    """1.x EMA(decay, thres_steps) harvested parameters from the global
    Program; there is no Program, so ``parameter_list`` is required
    (pass ``layer.parameters()``)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 parameter_list=None):
        if parameter_list is None:
            raise UnimplementedError(
                "fluid.optimizer.ExponentialMovingAverage: pass "
                "parameter_list=layer.parameters() — no global Program "
                "exists to collect parameters from")
        super().__init__(parameter_list, decay=decay,
                         thres_steps=bool(thres_steps))


class ModelAverage(_MA):
    """1.x ModelAverage(average_window_rate, ...) — same Program note as
    EMA above; ``parameter_list`` is required."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None,
                 parameter_list=None):
        if parameter_list is None:
            raise UnimplementedError(
                "fluid.optimizer.ModelAverage: pass "
                "parameter_list=layer.parameters()")
        super().__init__(parameter_list,
                         average_window_rate=average_window_rate,
                         min_average_window=min_average_window,
                         max_average_window=max_average_window)


class LookaheadOptimizer(_Lookahead):
    """1.x spelling: LookaheadOptimizer(inner_optimizer, alpha, k)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        super().__init__(inner_optimizer, alpha=alpha, k=k)


def _strategy_shim(name, field, instead):
    class Shim:
        def __init__(self, *a, **k):
            raise UnimplementedError(
                f"fluid.optimizer.{name} rewrote the Program; here the "
                f"capability is a fleet strategy toggle: set "
                f"DistributedStrategy().{field} (see {instead})")

    Shim.__name__ = name
    Shim.__qualname__ = name
    return Shim


PipelineOptimizer = _strategy_shim(
    "PipelineOptimizer", "pipeline=True, pipeline_configs={...}",
    "distributed/pipeline_parallel.py")
RecomputeOptimizer = _strategy_shim(
    "RecomputeOptimizer", "recompute=True, recompute_configs={...}",
    "nn/recompute.py")
GradientMergeOptimizer = _strategy_shim(
    "GradientMergeOptimizer", "gradient_merge=True",
    "optimizer/gradient_merge.py")
DGCMomentumOptimizer = _strategy_shim(
    "DGCMomentumOptimizer", "dgc=True, dgc_configs={...}",
    "distributed/fleet/dgc.py")
DpsgdOptimizer = _strategy_shim(
    "DpsgdOptimizer", "(differential privacy not implemented)",
    "paddle.optimizer")
DecayedAdagradOptimizer = _strategy_shim(
    "DecayedAdagradOptimizer", "(use Adagrad/RMSProp)", "paddle.optimizer")

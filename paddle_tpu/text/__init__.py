"""paddle_tpu.text — NLP dataset surface (parity: python/paddle/text/)."""
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)

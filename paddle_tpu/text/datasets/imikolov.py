"""imikolov (PTB) language-model dataset.

Parity: python/paddle/text/datasets/imikolov.py (Imikolov(data_file, mode,
data_type='NGRAM'|'SEQ', window_size, min_word_freq, download) over the
simple-examples tar — ``./simple-examples/data/ptb.{train,valid,test}.txt``;
dict from train+valid with freq > min_word_freq, '<unk>' last).
"""
from __future__ import annotations

import collections
import tarfile

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["Imikolov"]

URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tar.gz"


class Imikolov(Dataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        if mode not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode!r}")
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError(f"data_type must be NGRAM or SEQ, got {data_type!r}")
        if data_type == "NGRAM" and window_size <= 0:
            raise ValueError("NGRAM mode needs window_size > 0")
        self.mode = mode
        self.data_type = data_type
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        self.data_file = resolve_data_file(
            data_file, "imikolov", "simple-examples.tar.gz", URL, download)
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    def _word_count(self, f, word_freq=None):
        if word_freq is None:
            word_freq = collections.defaultdict(int)
        for line in f:
            for w in str(line, encoding="utf-8").strip().split():
                word_freq[w] += 1
            word_freq["<s>"] += 1
            word_freq["<e>"] += 1
        return word_freq

    def _build_word_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            trainf = tf.extractfile("./simple-examples/data/ptb.train.txt")
            validf = tf.extractfile("./simple-examples/data/ptb.valid.txt")
            word_freq = self._word_count(validf, self._word_count(trainf))
        word_freq.pop("<unk>", None)  # re-added as the last index
        word_freq = [x for x in word_freq.items() if x[1] > cutoff]
        dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(dictionary)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        self.data = []
        name = {"train": "train", "test": "valid"}[self.mode]
        unk = self.word_idx["<unk>"]
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(f"./simple-examples/data/ptb.{name}.txt")
            for line in f:
                words = str(line, encoding="utf-8").strip().split()
                if self.data_type == "NGRAM":
                    seq = ["<s>"] + words + ["<e>"]
                    if len(seq) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in seq]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(tuple(ids[i - self.window_size:i]))
                else:  # SEQ
                    ids = [self.word_idx.get(w, unk) for w in words]
                    src = [self.word_idx.get("<s>", unk)] + ids
                    trg = ids + [self.word_idx.get("<e>", unk)]
                    if self.window_size > 0 and len(src) > self.window_size:
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)

"""Shared plumbing for text datasets (no-egress file resolution)."""
from __future__ import annotations

import os

from ...framework.errors import NotFoundError

from ...io.dataset import DEFAULT_DATA_ROOT as _DEFAULT_ROOT


def resolve_data_file(data_file, name: str, filename: str, url_hint: str,
                      download: bool = True) -> str:
    """Return a readable local path for ``name`` or raise with instructions.

    Mirrors the reference's _check_exists_and_download
    (dataset/common.py) minus the fetch: this environment has no egress.
    """
    if data_file:
        if not os.path.exists(data_file):
            raise NotFoundError(f"{name}: data_file {data_file!r} not found")
        return data_file
    cached = os.path.join(_DEFAULT_ROOT, name, filename)
    if os.path.exists(cached):
        return cached
    hint = (f"place the file at {cached!r} or pass data_file=;"
            f" upstream source: {url_hint}")
    if download:
        raise NotFoundError(
            f"{name}: no local copy and this environment cannot download — {hint}")
    raise NotFoundError(f"{name}: data_file not set and download=False — {hint}")

"""WMT14 en→fr translation dataset.

Parity: python/paddle/text/datasets/wmt14.py (WMT14(data_file, mode,
dict_size, download) over the paddle wmt14 tar: ``*/src.dict``,
``*/trg.dict`` and ``<mode>/<mode>`` tab-separated sentence pairs; samples
(src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> framing and the >80
token filter in all modes).
"""
from __future__ import annotations

import tarfile

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["WMT14"]

URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"
START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        if mode not in ("train", "test", "gen"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'gen', got {mode!r}")
        if dict_size <= 0:
            raise ValueError("dict_size should be a positive number")
        self.mode = mode
        self.dict_size = dict_size
        self.data_file = resolve_data_file(
            data_file, "wmt14", "wmt14.tgz", URL, download)
        self._load_data()

    def _to_dict(self, fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[str(line, encoding="utf-8").strip()] = i
        return out

    def _load_data(self):
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file, mode="r") as f:
            names = [m.name for m in f if m.name.endswith("src.dict")]
            assert len(names) == 1, f"need exactly one src.dict, got {names}"
            self.src_dict = self._to_dict(f.extractfile(names[0]),
                                          self.dict_size)
            names = [m.name for m in f if m.name.endswith("trg.dict")]
            assert len(names) == 1, f"need exactly one trg.dict, got {names}"
            self.trg_dict = self._to_dict(f.extractfile(names[0]),
                                          self.dict_size)
            file_name = f"{self.mode}/{self.mode}"
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    line = str(line, encoding="utf-8")
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [self.src_dict.get(w, UNK_IDX)
                               for w in [START] + src_words + [END]]
                    trg_words = parts[1].split()
                    trg_ids = [self.trg_dict.get(w, UNK_IDX)
                               for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids.append([self.trg_dict[START]] + trg_ids)
                    self.trg_ids_next.append(trg_ids + [self.trg_dict[END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        src, trg = self.src_dict, self.trg_dict
        if reverse:
            src = {v: k for k, v in src.items()}
            trg = {v: k for k, v in trg.items()}
        return src, trg

"""CoNLL-2005 semantic-role-labeling dataset.

Parity: python/paddle/text/datasets/conll05.py (Conll05st(data_file,
word_dict_file, verb_dict_file, target_dict_file, download) over the
conll05st-tests tar — ``conll05st-release/test.wsj/words/test.wsj.words.gz``
+ ``.../props/test.wsj.props.gz``; bracketed prop labels expand to BIO
sequences and each sample is the 9-column SRL feature tuple: word ids, five
predicate-context windows, predicate id, mark vector, label ids).
"""
from __future__ import annotations

import gzip
import tarfile

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["Conll05st"]

DATA_URL = "http://paddlemodels.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"
WORDDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2FwordDict.txt"
VERBDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2FverbDict.txt"
TRGDICT_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2FtargetDict.txt"
UNK_IDX = 0


class Conll05st(Dataset):
    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, download=True):
        self.data_file = resolve_data_file(
            data_file, "conll05st", "conll05st-tests.tar.gz", DATA_URL,
            download)
        self.word_dict_file = resolve_data_file(
            word_dict_file, "conll05st", "wordDict.txt", WORDDICT_URL,
            download)
        self.verb_dict_file = resolve_data_file(
            verb_dict_file, "conll05st", "verbDict.txt", VERBDICT_URL,
            download)
        self.target_dict_file = resolve_data_file(
            target_dict_file, "conll05st", "targetDict.txt", TRGDICT_URL,
            download)
        self.word_dict = self._load_dict(self.word_dict_file)
        self.predicate_dict = self._load_dict(self.verb_dict_file)
        self.label_dict = self._load_label_dict(self.target_dict_file)
        self._load_anno()

    def _load_dict(self, filename):
        with open(filename) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    def _load_label_dict(self, filename):
        tags = []
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")) and line[2:] not in tags:
                    tags.append(line[2:])
        d = {}
        for tag in tags:
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    def _expand_bio(self, lbl):
        """One props column (bracket notation) → BIO tag sequence."""
        cur_tag, in_bracket, seq = "O", False, []
        for l in lbl:
            if l == "*" and not in_bracket:
                seq.append("O")
            elif l == "*" and in_bracket:
                seq.append("I-" + cur_tag)
            elif l == "*)":
                seq.append("I-" + cur_tag)
                in_bracket = False
            elif "(" in l and ")" in l:
                cur_tag = l[1:l.find("*")]
                seq.append("B-" + cur_tag)
                in_bracket = False
            elif "(" in l:
                cur_tag = l[1:l.find("*")]
                seq.append("B-" + cur_tag)
                in_bracket = True
            else:
                raise RuntimeError(f"Unexpected label: {l}")
        return seq

    def _load_anno(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentence, one_seg = [], []
                for word, label in zip(words_file, props_file):
                    word = str(word, encoding="utf-8").strip()
                    label = str(label, encoding="utf-8").strip().split()
                    if label:
                        sentence.append(word)
                        one_seg.append(label)
                        continue
                    # end of sentence: column 0 is the verb column, the
                    # rest are one bracketed role row per predicate
                    if one_seg:
                        cols = list(zip(*one_seg))
                        verbs = [v for v in cols[0] if v != "-"]
                        for i, lbl in enumerate(cols[1:]):
                            seq = self._expand_bio(lbl)
                            self.sentences.append(list(sentence))
                            self.predicates.append(verbs[i])
                            self.labels.append(seq)
                    sentence, one_seg = [], []

    def __getitem__(self, idx):
        sentence = self.sentences[idx]
        predicate = self.predicates[idx]
        labels = self.labels[idx]
        sen_len = len(sentence)

        verb_index = labels.index("B-V")
        mark = [0] * len(labels)
        ctx = {}
        for off, name, fallback in ((-2, "ctx_n2", "bos"),
                                    (-1, "ctx_n1", "bos"),
                                    (0, "ctx_0", None),
                                    (1, "ctx_p1", "eos"),
                                    (2, "ctx_p2", "eos")):
            j = verb_index + off
            if 0 <= j < len(labels):
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = fallback

        word_idx = [self.word_dict.get(w, UNK_IDX) for w in sentence]
        ctx_cols = [
            [self.word_dict.get(ctx[name], UNK_IDX)] * sen_len
            for name in ("ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2")
        ]
        pred_idx = [self.predicate_dict.get(predicate)] * sen_len
        label_idx = [self.label_dict.get(w) for w in labels]
        return tuple(
            np.array(a) for a in
            [word_idx] + ctx_cols + [pred_idx, mark, label_idx])

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

"""WMT16 en↔de translation dataset.

Parity: python/paddle/text/datasets/wmt16.py (WMT16(data_file, mode,
src_dict_size, trg_dict_size, lang, download) over the paddle wmt16 tar:
``wmt16/{train,val,test}`` tab-separated en/de pairs; dictionaries built
from the train split by frequency with <s>/<e>/<unk> as ids 0/1/2; samples
(src_ids, trg_ids, trg_ids_next)).  The reference caches built dicts under
DATA_HOME; here they are built in memory each construction (same content).
"""
from __future__ import annotations

import tarfile
from collections import defaultdict

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["WMT16"]

URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"


class WMT16(Dataset):
    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        if mode.lower() not in ("train", "test", "val"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'val', got {mode!r}")
        if src_dict_size <= 0 or trg_dict_size <= 0:
            raise ValueError("dict sizes should be positive numbers")
        if lang not in ("en", "de"):
            raise ValueError(f"lang should be 'en' or 'de', got {lang!r}")
        self.mode = mode.lower()
        self.lang = lang
        self.data_file = resolve_data_file(
            data_file, "wmt16", "wmt16.tar.gz", URL, download)
        # one pass over wmt16/train counts BOTH language columns
        en_freq, de_freq = self._count_words()
        src_freq, trg_freq = ((en_freq, de_freq) if lang == "en"
                              else (de_freq, en_freq))
        self.src_dict = self._build_dict(src_freq, src_dict_size)
        self.trg_dict = self._build_dict(trg_freq, trg_dict_size)
        self._load_data()

    def _count_words(self):
        en, de = defaultdict(int), defaultdict(int)
        with tarfile.open(self.data_file, mode="r") as f:
            for line in f.extractfile("wmt16/train"):
                parts = str(line, encoding="utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[0].split():
                    en[w] += 1
                for w in parts[1].split():
                    de[w] += 1
        return en, de

    def _build_dict(self, freq, dict_size):
        words = [w for w, _ in sorted(freq.items(), key=lambda x: x[1],
                                      reverse=True)]
        words = words[: max(dict_size - 3, 0)]
        return {w: i for i, w in enumerate(
            [START_MARK, END_MARK, UNK_MARK] + words)}

    def _load_data(self):
        start_id = self.src_dict[START_MARK]
        end_id = self.src_dict[END_MARK]
        unk_id = self.src_dict[UNK_MARK]
        src_col = 0 if self.lang == "en" else 1
        trg_col = 1 - src_col
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file, mode="r") as f:
            for line in f.extractfile(f"wmt16/{self.mode}"):
                parts = str(line, encoding="utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                src_words = parts[src_col].split()
                src_ids = ([start_id]
                           + [self.src_dict.get(w, unk_id) for w in src_words]
                           + [end_id])
                trg_words = parts[trg_col].split()
                trg_ids = [self.trg_dict.get(w, unk_id) for w in trg_words]
                self.src_ids.append(src_ids)
                self.trg_ids.append([start_id] + trg_ids)
                self.trg_ids_next.append(trg_ids + [end_id])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else dict(d)

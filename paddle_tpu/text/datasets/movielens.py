"""MovieLens-1M rating dataset.

Parity: python/paddle/text/datasets/movielens.py (Movielens(data_file, mode,
test_ratio, rand_seed, download) over the ml-1m zip — movies.dat/users.dat/
ratings.dat, '::'-separated, latin-1; samples are user features + movie
features + [rating*2-5]).
"""
from __future__ import annotations

import re
import zipfile

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["Movielens", "MovieInfo", "UserInfo"]

URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        """[movie_id, [category ids], [title word ids]]."""
        return [
            [self.index],
            [categories_dict[c] for c in self.categories],
            [movie_title_dict[w.lower()] for w in self.title.split()],
        ]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = [1, 18, 25, 35, 45, 50, 56].index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        if mode not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        self.data_file = resolve_data_file(
            data_file, "movielens", "ml-1m.zip", URL, download)
        np.random.seed(rand_seed)
        self._load_meta_info()
        self._load_data()

    def _load_meta_info(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info = {}
        self.movie_title_dict = {}
        self.categories_dict = {}
        self.user_info = {}
        with zipfile.ZipFile(self.data_file) as package:
            for info in package.namelist():
                if info.endswith("movies.dat"):
                    with package.open(info) as f:
                        for line in f:
                            line = str(line, encoding="latin")
                            movie_id, title, categories = \
                                line.strip().split("::")
                            categories = categories.split("|")
                            for c in categories:
                                self.categories_dict.setdefault(
                                    c, len(self.categories_dict))
                            m = pattern.match(title)
                            title = m.group(1) if m else title
                            for w in title.split():
                                self.movie_title_dict.setdefault(
                                    w.lower(), len(self.movie_title_dict))
                            self.movie_info[int(movie_id)] = MovieInfo(
                                movie_id, categories, title)
                elif info.endswith("users.dat"):
                    with package.open(info) as f:
                        for line in f:
                            line = str(line, encoding="latin")
                            uid, gender, age, job, _ = \
                                line.strip().split("::")
                            self.user_info[int(uid)] = UserInfo(
                                uid, gender, age, job)

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as package:
            ratings = [n for n in package.namelist()
                       if n.endswith("ratings.dat")]
            with package.open(ratings[0]) as f:
                for line in f:
                    line = str(line, encoding="latin")
                    if (np.random.random() < self.test_ratio) != is_test:
                        continue
                    uid, mov_id, rating, _ = line.strip().split("::")
                    mov = self.movie_info[int(mov_id)]
                    usr = self.user_info[int(uid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[float(rating) * 2 - 5.0]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)

"""IMDB sentiment dataset.

Parity: python/paddle/text/datasets/imdb.py:33 (Imdb(data_file, mode,
cutoff, download) over the aclImdb tar: ``aclImdb/<mode>/<pos|neg>/*.txt``;
word dict built from the train split with frequency > cutoff; samples are
(doc_ids int64[], label) with pos→0, neg→1).
"""
from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["Imdb"]

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode!r}")
        self.mode = mode.lower()
        self.data_file = resolve_data_file(
            data_file, "imdb", "aclImdb_v1.tar.gz", URL, download)
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, raw: bytes):
        tok = str(raw, encoding="utf-8", errors="ignore").lower()
        return tok.translate(str.maketrans("", "", string.punctuation)).split()

    def _iter_docs(self, pattern: re.Pattern):
        with tarfile.open(self.data_file) as tarf:
            member = tarf.next()
            while member is not None:
                if bool(pattern.match(member.name)):
                    yield self._tokenize(tarf.extractfile(member).read())
                member = tarf.next()

    def _build_word_dict(self, cutoff):
        pattern = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        word_freq = collections.defaultdict(int)
        for doc in self._iter_docs(pattern):
            for w in doc:
                word_freq[w] += 1
        word_freq = [x for x in word_freq.items() if x[1] > cutoff]
        dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(dictionary)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs = []
        self.labels = []
        for label, tag in ((0, "pos"), (1, "neg")):
            pattern = re.compile(
                rf"aclImdb/{self.mode}/{tag}/.*\.txt$")
            for doc in self._iter_docs(pattern):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array(self.labels[idx])

    def __len__(self):
        return len(self.docs)

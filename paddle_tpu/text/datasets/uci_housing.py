"""UCI housing regression dataset.

Parity: python/paddle/text/datasets/uci_housing.py:34 (UCIHousing(data_file,
mode, download) → (feature[13] f32, target[1] f32) samples, features
min/max-normalized, 80/20 train/test split).
"""
from __future__ import annotations

import numpy as np

from ...io import Dataset
from ._base import resolve_data_file

__all__ = ["UCIHousing"]

URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"
FEATURE_NUM = 14


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        if mode not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.data_file = resolve_data_file(
            data_file, "uci_housing", "housing.data", URL, download)
        self._load_data()

    def _load_data(self, feature_num=FEATURE_NUM, ratio=0.8):
        data = np.loadtxt(self.data_file).astype(np.float32)
        if data.size % feature_num:
            raise ValueError(
                f"{self.data_file}: not a whitespace table of "
                f"{feature_num}-column rows")
        data = data.reshape(-1, feature_num)
        maxs, mins, avgs = (data.max(0), data.min(0),
                            data.sum(0) / data.shape[0])
        span = np.where(maxs - mins == 0, 1.0, maxs - mins)
        data[:, :-1] = (data[:, :-1] - avgs[:-1]) / span[:-1]
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return np.array(row[:-1]), np.array(row[-1:])

    def __len__(self):
        return len(self.data)

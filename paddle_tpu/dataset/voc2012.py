"""paddle.dataset.voc2012 (ref: dataset/voc2012.py)."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "val", "test", "fetch"]


def _make(mode):
    def creator(data_file=None):
        from ..vision.datasets import VOC2012

        return dataset_reader(lambda: VOC2012(data_file=data_file,
                                              mode=mode))

    return creator


train = _make("train")
val = _make("valid")
test = _make("test")
fetch = no_fetch("voc2012")

"""paddle.dataset.flowers (ref: dataset/flowers.py)."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "valid", "test", "fetch"]


def _make(mode):
    def creator(data_file=None, label_file=None, setid_file=None):
        from ..vision.datasets import Flowers

        return dataset_reader(lambda: Flowers(
            data_file=data_file, label_file=label_file,
            setid_file=setid_file, mode=mode))

    return creator


train = _make("train")
valid = _make("valid")
test = _make("test")
fetch = no_fetch("flowers")

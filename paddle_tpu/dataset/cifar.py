"""paddle.dataset.cifar (ref: dataset/cifar.py) — samples are the
Cifar Dataset tuples: (f32 image [3,32,32], int64 label)."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train10", "test10", "train100", "test100", "fetch"]


def train10(data_file=None):
    from ..vision.datasets import Cifar10

    return dataset_reader(lambda: Cifar10(data_file=data_file, mode="train"))


def test10(data_file=None):
    from ..vision.datasets import Cifar10

    return dataset_reader(lambda: Cifar10(data_file=data_file, mode="test"))


def train100(data_file=None):
    from ..vision.datasets import Cifar100

    return dataset_reader(lambda: Cifar100(data_file=data_file, mode="train"))


def test100(data_file=None):
    from ..vision.datasets import Cifar100

    return dataset_reader(lambda: Cifar100(data_file=data_file, mode="test"))


fetch = no_fetch("cifar")

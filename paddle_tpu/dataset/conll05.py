"""paddle.dataset.conll05 (ref: dataset/conll05.py) — SRL samples."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["test", "get_dict", "fetch"]


def test(data_file=None, word_dict_file=None, verb_dict_file=None,
         target_dict_file=None):
    from ..text.datasets import Conll05st

    return dataset_reader(lambda: Conll05st(
        data_file=data_file, word_dict_file=word_dict_file,
        verb_dict_file=verb_dict_file, target_dict_file=target_dict_file))


def get_dict(data_file=None, word_dict_file=None, verb_dict_file=None,
             target_dict_file=None):
    """(word_dict, verb_dict, label_dict) — reference conll05.get_dict."""
    from ..text.datasets import Conll05st

    ds = Conll05st(data_file=data_file, word_dict_file=word_dict_file,
                   verb_dict_file=verb_dict_file,
                   target_dict_file=target_dict_file)
    return ds.word_dict, ds.predicate_dict, ds.label_dict


fetch = no_fetch("conll05")

"""paddle.dataset — 1.x module-level reader creators.

Parity: python/paddle/dataset/ (mnist.py:91 train/test, cifar.py,
uci_housing.py, imdb.py, imikolov.py, movielens.py, conll05.py,
flowers.py, voc2012.py, wmt14.py, wmt16.py) — each module exposes
``train()``/``test()`` returning a *reader*: a zero-arg callable
yielding samples, composable with ``paddle.reader`` decorators and
``paddle.batch``.

TPU-native design: the modules are thin bridges over the class-based
datasets (paddle_tpu.vision.datasets / paddle_tpu.text.datasets), which
own the file formats.  No network egress exists here, so the reference's
``common.download`` flow is replaced by the datasets' documented
local-file placement; ``fetch()`` raises with those instructions.
"""
from __future__ import annotations

from . import (  # noqa: F401
    cifar,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "conll05", "flowers", "voc2012", "wmt14", "wmt16"]

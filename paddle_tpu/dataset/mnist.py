"""paddle.dataset.mnist (ref: dataset/mnist.py:91) — samples are
(flattened f32 pixels in [-1, 1], int label), the documented 1.x format."""
from __future__ import annotations

import numpy as np

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "test", "fetch"]


def _flatten_norm(sample):
    img, label = sample
    return (np.asarray(img, np.float32).reshape(-1) / 127.5 - 1.0,
            int(label))


def train(image_file=None, label_file=None):
    from ..vision.datasets import MNIST

    return dataset_reader(
        lambda: MNIST(image_path=image_file, label_path=label_file,
                      mode="train"),
        transform=_flatten_norm)


def test(image_file=None, label_file=None):
    from ..vision.datasets import MNIST

    return dataset_reader(
        lambda: MNIST(image_path=image_file, label_path=label_file,
                      mode="test"),
        transform=_flatten_norm)


fetch = no_fetch("mnist")

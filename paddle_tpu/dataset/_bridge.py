"""Shared bridge: class Dataset → 1.x reader creator."""
from __future__ import annotations


def dataset_reader(factory, transform=None):
    """A zero-arg reader yielding ``factory()``'s samples.  Construction
    is lazy (first iteration, per 1.x semantics where ``train()`` is
    cheap) but cached after — the standard epoch loop calls reader()
    every epoch, and rebuilding would rescan archives/vocabs each time."""
    cache = []

    def reader():
        if not cache:
            cache.append(factory())
        ds = cache[0]
        for i in range(len(ds)):
            sample = ds[i]
            yield transform(sample) if transform is not None else sample

    return reader


def no_fetch(name: str):
    def fetch():
        raise RuntimeError(
            f"paddle.dataset.{name}.fetch(): this environment has no "
            f"network egress — place the standard archives locally as the "
            f"{name} Dataset class documents (see its FileNotFoundError "
            f"message for exact paths)")

    return fetch


def _check_word_idx(user_dict, ds_dict, builder: str):
    """The 1.x readers MAP tokens through the caller's word_idx; these
    bridges delegate encoding to the class datasets, which derive the
    same vocab from the same corpus/cutoff — so a dict from ``builder()``
    matches exactly, and anything else must fail loudly rather than
    silently emit ids from a different vocabulary."""
    if user_dict is None or dict(user_dict) == dict(ds_dict):
        return
    from ..framework.errors import InvalidArgumentError

    raise InvalidArgumentError(
        f"word_idx does not match the vocabulary this dataset derives "
        f"from its corpus; build it with {builder}() (same cutoff/"
        f"min_word_freq) — custom vocabularies are not remapped here")

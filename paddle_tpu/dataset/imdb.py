"""paddle.dataset.imdb (ref: dataset/imdb.py) — samples are
(token-id sequence, 0/1 label); word_dict() builds the cutoff vocab."""
from __future__ import annotations

from ._bridge import _check_word_idx, dataset_reader, no_fetch

__all__ = ["train", "test", "word_dict", "fetch"]


def _make(mode):
    def creator(word_idx=None, data_file=None, cutoff=150):
        from ..text.datasets import Imdb

        def factory():
            ds = Imdb(data_file=data_file, mode=mode, cutoff=cutoff)
            _check_word_idx(word_idx, ds.word_idx, "imdb.word_dict")
            return ds

        return dataset_reader(factory)

    return creator


train = _make("train")
test = _make("test")


def word_dict(data_file=None, cutoff=150):
    from ..text.datasets import Imdb

    return Imdb(data_file=data_file, mode="train", cutoff=cutoff).word_idx


fetch = no_fetch("imdb")

"""paddle.dataset.uci_housing (ref: dataset/uci_housing.py) — samples
are (13 f32 features, 1 f32 target)."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "test", "fetch"]


def train(data_file=None):
    from ..text.datasets import UCIHousing

    return dataset_reader(lambda: UCIHousing(data_file=data_file,
                                             mode="train"))


def test(data_file=None):
    from ..text.datasets import UCIHousing

    return dataset_reader(lambda: UCIHousing(data_file=data_file,
                                             mode="test"))


fetch = no_fetch("uci_housing")

"""paddle.dataset.wmt16 (ref: dataset/wmt16.py)."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "test", "validation", "fetch"]


def _make(mode):
    def creator(src_dict_size=-1, trg_dict_size=-1, src_lang="en",
                data_file=None):
        from ..text.datasets import WMT16

        return dataset_reader(lambda: WMT16(
            data_file=data_file, mode=mode, src_dict_size=src_dict_size,
            trg_dict_size=trg_dict_size, lang=src_lang))

    return creator


train = _make("train")
test = _make("test")
validation = _make("val")
fetch = no_fetch("wmt16")

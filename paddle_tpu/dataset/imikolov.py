"""paddle.dataset.imikolov (ref: dataset/imikolov.py) — ngram or seq
samples from the PTB corpus."""
from __future__ import annotations

from ._bridge import _check_word_idx, dataset_reader, no_fetch

__all__ = ["train", "test", "build_dict", "fetch"]


def _make(mode):
    def creator(word_idx=None, n=-1, data_type="NGRAM", data_file=None,
                min_word_freq=50):
        from ..text.datasets import Imikolov

        def factory():
            ds = Imikolov(data_file=data_file, data_type=data_type,
                          window_size=n, mode=mode,
                          min_word_freq=min_word_freq)
            _check_word_idx(word_idx, ds.word_idx, "imikolov.build_dict")
            return ds

        return dataset_reader(factory)

    return creator


train = _make("train")
test = _make("test")


def build_dict(data_file=None, min_word_freq=50):
    from ..text.datasets import Imikolov

    return Imikolov(data_file=data_file, mode="train",
                    min_word_freq=min_word_freq).word_idx


fetch = no_fetch("imikolov")

"""paddle.dataset.wmt14 (ref: dataset/wmt14.py) — (src_ids, trg_in,
trg_next) translation samples."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "test", "fetch"]


def train(dict_size=-1, data_file=None):
    from ..text.datasets import WMT14

    return dataset_reader(lambda: WMT14(data_file=data_file, mode="train",
                                        dict_size=dict_size))


def test(dict_size=-1, data_file=None):
    from ..text.datasets import WMT14

    return dataset_reader(lambda: WMT14(data_file=data_file, mode="test",
                                        dict_size=dict_size))


fetch = no_fetch("wmt14")

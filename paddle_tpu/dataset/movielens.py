"""paddle.dataset.movielens (ref: dataset/movielens.py) — samples are
the Movielens Dataset's 8-tuples (user/movie features + rating)."""
from __future__ import annotations

from ._bridge import dataset_reader, no_fetch

__all__ = ["train", "test", "fetch"]


def train(data_file=None, test_ratio=0.1, rand_seed=0):
    from ..text.datasets import Movielens

    return dataset_reader(lambda: Movielens(
        data_file=data_file, mode="train", test_ratio=test_ratio,
        rand_seed=rand_seed))


def test(data_file=None, test_ratio=0.1, rand_seed=0):
    from ..text.datasets import Movielens

    return dataset_reader(lambda: Movielens(
        data_file=data_file, mode="test", test_ratio=test_ratio,
        rand_seed=rand_seed))


fetch = no_fetch("movielens")

"""paddle.compat — string/number helpers kept for 1.x source compat.

Parity: python/paddle/compat.py (to_text:36, to_bytes:120, round:193,
floor_division:219, get_exception_message:236).  The reference carried
these for the py2→py3 transition; ported scripts still import them.
"""
from __future__ import annotations

import math

__all__ = ["to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]


def _convert(obj, conv, inplace):
    """Elementwise over list/set/dict (keys AND values, like the
    reference compat.py:74 dict branch); scalars through ``conv``."""
    if obj is None:
        return obj
    if isinstance(obj, (list, set)):
        if inplace:
            items = [_convert(i, conv, False) for i in obj]
            obj.clear()
            (obj.extend if isinstance(obj, list) else obj.update)(items)
            return obj
        return type(obj)(_convert(i, conv, False) for i in obj)
    if isinstance(obj, dict):
        new = {_convert(k, conv, False): _convert(v, conv, False)
               for k, v in obj.items()}
        if inplace:
            obj.update(new)
            return obj
        return new
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes → str (elementwise over list/set), str passthrough."""
    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else str(o)

    return _convert(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str → bytes (elementwise over list/set), bytes passthrough."""
    def conv(o):
        return o.encode(encoding) if isinstance(o, str) else bytes(o)

    return _convert(obj, conv, inplace)


def round(x, d=0):  # noqa: A001 — paddle API name
    """Python-2-style half-away-from-zero rounding (compat.py:193)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc) -> str:
    return str(exc)

"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe schedule).

TPU-native replacement for the reference's program-splitting pipeline stack —
PipelineOptimizer (python/paddle/fluid/optimizer.py:3695), the section
program cutter (device_worker.py PipelineWorker) and the C++ SectionWorker
microbatch thread loop (paddle/fluid/framework/section_worker.cc:82-230).
The reference cuts a ProgramDesc into per-device section programs and streams
microbatches through worker threads with explicit send/recv ops; here the
whole schedule is ONE differentiable XLA computation:

* the repeated block stack's parameters are **stacked** along a leading
  stage axis ``[pp, layers_per_stage, ...]`` and shard_map'd over ``pipe``
  (partial-manual: every other mesh axis stays GSPMD-auto, so TP/DP/ZeRO
  shardings compose inside),
* a ``lax.scan`` over ``M + pp - 1`` schedule ticks applies each device's
  stage and rotates activations stage→stage with ``lax.ppermute`` (ICI
  neighbor exchange — the send/recv pair of section_worker.cc, but
  compiler-scheduled),
* reverse-mode autodiff of that scan IS the backward pipeline: the ticks
  replay in reverse with the transposed ppermute, i.e. a GPipe
  fwd-all-then-bwd-all schedule with the same bubble fraction
  ``(pp-1)/(M+pp-1)``.

Parameters stay stored per-block (un-stacked), so optimizers, ZeRO slot
sharding, checkpointing and state_dict round-trips are untouched; the stack
is formed inside the jitted step where XLA turns the backward's unstack into
slices of the scan-accumulated gradient.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.errors import InvalidArgumentError
from ..nn.layer_base import current_rng_key, functional_call
from .mesh import get_mesh

__all__ = ["pipeline_degree", "pipeline_blocks"]


def pipeline_degree(mesh=None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape.get("pipe", 1)


def _stack_block_params(blocks) -> Dict[str, jax.Array]:
    """{param_name_within_block: [L, ...]} — the per-stage weight cube."""
    names = [n for n, _ in blocks[0].named_parameters()]
    per_block = [dict(b.named_parameters()) for b in blocks]
    for i, bp in enumerate(per_block):
        if set(bp) != set(names):
            raise InvalidArgumentError(
                f"pipeline stages must be structurally identical: block {i} "
                f"parameters differ from block 0")
    return {n: jnp.stack([bp[n].value for bp in per_block]) for n in names}


def pipeline_blocks(
    blocks: Sequence,
    x: jax.Array,
    *,
    num_microbatches: Optional[int] = None,
    mesh=None,
    axis_name: str = "pipe",
):
    """Run ``x`` through ``blocks`` (a homogeneous Layer stack) pipelined
    over the ``pipe`` mesh axis.  Semantically identical to

        for b in blocks: x = b(x)

    but executed as a GPipe microbatch schedule: stage ``s`` owns blocks
    ``[s*L/pp, (s+1)*L/pp)`` and the batch is split into ``num_microbatches``
    chunks that flow stage→stage over ICI.

    Constraints: ``len(blocks) % pp == 0``; batch divisible by
    ``num_microbatches``; blocks take/return a single activation and hold no
    buffers (BatchNorm-free — transformer blocks qualify).
    """
    mesh = mesh or get_mesh()
    pp = mesh.shape.get(axis_name, 1)
    if pp == 1:
        for b in blocks:
            x = b(x)
        return x

    L = len(blocks)
    if L % pp:
        raise InvalidArgumentError(
            f"pipeline: {L} blocks not divisible by pp={pp} stages")
    template = blocks[0]
    if list(template.named_buffers()):
        raise InvalidArgumentError(
            "pipeline blocks must be buffer-free (running-stat updates "
            "cannot cross the stage scan); use LayerNorm, not BatchNorm")
    per_stage = L // pp

    M = int(num_microbatches or pp)
    B = x.shape[0]
    if B % M:
        raise InvalidArgumentError(
            f"pipeline: batch {B} not divisible by {M} microbatches")
    mb = B // M

    # per-(block, tick) dropout keys — matches the pp=1 semantics of "every
    # block / every sample draws an independent mask"
    training = bool(getattr(template, "training", False))
    base_key = current_rng_key() if training else jax.random.PRNGKey(0)

    stacked = _stack_block_params(blocks)
    stacked = {
        n: v.reshape((pp, per_stage) + v.shape[1:]) for n, v in stacked.items()
    }

    def block_fn(pdict, h, global_idx, tick):
        key = jax.random.fold_in(
            jax.random.fold_in(base_key, global_idx), tick)
        return functional_call(template, pdict, h, rngs=key)

    def local(stage_params, xin):
        # in_spec P(pipe) leaves a leading length-1 stage dim — drop it:
        # stage_params: {n: [per_stage, ...]}
        stage_params = {n: v[0] for n, v in stage_params.items()}
        stage = lax.axis_index(axis_name)
        micro = xin.reshape((M, mb) + xin.shape[1:])
        state = jnp.zeros((mb,) + xin.shape[1:], xin.dtype)
        outputs = jnp.zeros_like(micro)

        def apply_stage(h, t):
            def body(h, idx_and_params):
                j, pdict = idx_and_params
                return block_fn(pdict, h, stage * per_stage + j, t), None

            h, _ = lax.scan(body, h, (jnp.arange(per_stage), stage_params))
            return h

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects the next microbatch (tail ticks re-feed the
            # last one; its results never reach a valid output slot)
            inject = lax.dynamic_index_in_dim(
                micro, jnp.minimum(t, M - 1), axis=0, keepdims=False)
            state = jnp.where(stage == 0, inject, state)
            state = apply_stage(state, t)
            out_idx = t - (pp - 1)
            upd = lax.dynamic_update_index_in_dim(
                outputs, state, jnp.maximum(out_idx, 0), axis=0)
            valid = (out_idx >= 0) & (stage == pp - 1)
            outputs = jnp.where(valid, upd, outputs)
            state = lax.ppermute(
                state, axis_name, [(i, (i + 1) % pp) for i in range(pp)])
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(M + pp - 1))
        # hand the last stage's collected outputs to every pipe rank (the
        # head/loss run replicated over pipe outside this shard_map)
        outputs = lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs.reshape(xin.shape)

    shmapped = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=({n: P(axis_name) for n in stacked}, P()),
        out_specs=P(),
        axis_names={axis_name},
        check_vma=False,
    )
    return shmapped(stacked, x)

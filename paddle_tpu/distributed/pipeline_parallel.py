"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe schedule).

TPU-native replacement for the reference's program-splitting pipeline stack —
PipelineOptimizer (python/paddle/fluid/optimizer.py:3695), the section
program cutter (device_worker.py PipelineWorker) and the C++ SectionWorker
microbatch thread loop (paddle/fluid/framework/section_worker.cc:82-230).
The reference cuts a ProgramDesc into per-device section programs and streams
microbatches through worker threads with explicit send/recv ops; here the
whole schedule is ONE differentiable XLA computation:

* the repeated block stack's parameters are **stacked** along a leading
  stage axis ``[pp, layers_per_stage, ...]`` and shard_map'd over ``pipe``
  (partial-manual: every other mesh axis stays GSPMD-auto, so TP/DP/ZeRO
  shardings compose inside),
* a ``lax.scan`` over ``M + pp - 1`` schedule ticks applies each device's
  stage and rotates activations stage→stage with ``lax.ppermute`` (ICI
  neighbor exchange — the send/recv pair of section_worker.cc, but
  compiler-scheduled),
* reverse-mode autodiff of that scan IS the backward pipeline: the ticks
  replay in reverse with the transposed ppermute, i.e. a GPipe
  fwd-all-then-bwd-all schedule with the same bubble fraction
  ``(pp-1)/(M+pp-1)``.

Parameters stay stored per-block (un-stacked), so optimizers, ZeRO slot
sharding, checkpointing and state_dict round-trips are untouched; the stack
is formed inside the jitted step where XLA turns the backward's unstack into
slices of the scan-accumulated gradient.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.errors import InvalidArgumentError
from ..nn.layer_base import current_rng_key, functional_call
from .collective import shard_map as _compat_shard_map
from .mesh import get_mesh

__all__ = ["pipeline_degree", "pipeline_blocks", "pipeline_train_step",
           "ring_buffer_slots"]


def pipeline_degree(mesh=None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape.get("pipe", 1)


def _stack_block_params(blocks) -> Dict[str, jax.Array]:
    """{param_name_within_block: [L, ...]} — the per-stage weight cube."""
    names = [n for n, _ in blocks[0].named_parameters()]
    per_block = [dict(b.named_parameters()) for b in blocks]
    for i, bp in enumerate(per_block):
        if set(bp) != set(names):
            raise InvalidArgumentError(
                f"pipeline stages must be structurally identical: block {i} "
                f"parameters differ from block 0")
    return {n: jnp.stack([bp[n].value for bp in per_block]) for n in names}


def pipeline_blocks(
    blocks: Sequence,
    x: jax.Array,
    *,
    num_microbatches: Optional[int] = None,
    mesh=None,
    axis_name: str = "pipe",
    params: Optional[Dict[str, jax.Array]] = None,
):
    """Run ``x`` through ``blocks`` (a homogeneous Layer stack) pipelined
    over the ``pipe`` mesh axis.  Semantically identical to

        for b in blocks: x = b(x)

    but executed as a GPipe microbatch schedule: stage ``s`` owns blocks
    ``[s*L/pp, (s+1)*L/pp)`` and the batch is split into ``num_microbatches``
    chunks that flow stage→stage over ICI.

    Constraints (also enforced with errors below): ``len(blocks) % pp ==
    0``; batch divisible by ``num_microbatches``; blocks must be
    STRUCTURALLY IDENTICAL (same parameter tree — their weights stack into
    one [pp, L/pp, ...] cube), take/return a SINGLE activation tensor, and
    hold no buffers (BatchNorm-free; use LayerNorm).  Transformer block
    stacks (GPT/BERT) satisfy all three; ResNet stages and detection
    heads do not — pipeline those models with recompute + dp/tp instead.
    The same constraints apply to the 1F1B schedule
    (:func:`pipeline_train_step`) and to ``Model.prepare`` with
    ``strategy.pipeline`` (hapi/model.py plumbs blocks through here).
    """
    mesh = mesh or get_mesh()
    pp = mesh.shape.get(axis_name, 1)
    template = blocks[0]
    if pp == 1:
        if params is None:
            for b in blocks:
                x = b(x)
        else:
            for j in range(len(blocks)):
                x = functional_call(
                    template, {n: v[j] for n, v in params.items()}, x,
                    rngs=current_rng_key()
                    if getattr(template, "training", False) else None)
        return x

    L = len(blocks)
    if L % pp:
        raise InvalidArgumentError(
            f"pipeline: {L} blocks not divisible by pp={pp} stages")
    if list(template.named_buffers()):
        raise InvalidArgumentError(
            "pipeline blocks must be buffer-free (running-stat updates "
            "cannot cross the stage scan); use LayerNorm, not BatchNorm")
    per_stage = L // pp

    M = int(num_microbatches or pp)
    B = x.shape[0]
    if B % M:
        raise InvalidArgumentError(
            f"pipeline: batch {B} not divisible by {M} microbatches")
    mb = B // M

    # per-(block, tick) dropout keys — matches the pp=1 semantics of "every
    # block / every sample draws an independent mask"
    training = bool(getattr(template, "training", False))
    base_key = current_rng_key() if training else jax.random.PRNGKey(0)

    stacked = _stack_block_params(blocks) if params is None else params
    stacked = {
        n: v.reshape((pp, per_stage) + v.shape[1:]) for n, v in stacked.items()
    }

    def block_fn(pdict, h, global_idx, tick):
        key = jax.random.fold_in(
            jax.random.fold_in(base_key, global_idx), tick)
        return functional_call(template, pdict, h, rngs=key)

    def local(stage_params, xin):
        # in_spec P(pipe) leaves a leading length-1 stage dim — drop it:
        # stage_params: {n: [per_stage, ...]}
        stage_params = {n: v[0] for n, v in stage_params.items()}
        stage = lax.axis_index(axis_name)
        micro = xin.reshape((M, mb) + xin.shape[1:])
        state = jnp.zeros((mb,) + xin.shape[1:], xin.dtype)
        outputs = jnp.zeros_like(micro)

        def apply_stage(h, t):
            def body(h, idx_and_params):
                j, pdict = idx_and_params
                return block_fn(pdict, h, stage * per_stage + j, t), None

            h, _ = lax.scan(body, h, (jnp.arange(per_stage), stage_params))
            return h

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects the next microbatch (tail ticks re-feed the
            # last one; its results never reach a valid output slot)
            inject = lax.dynamic_index_in_dim(
                micro, jnp.minimum(t, M - 1), axis=0, keepdims=False)
            state = jnp.where(stage == 0, inject, state)
            state = apply_stage(state, t)
            out_idx = t - (pp - 1)
            upd = lax.dynamic_update_index_in_dim(
                outputs, state, jnp.maximum(out_idx, 0), axis=0)
            valid = (out_idx >= 0) & (stage == pp - 1)
            outputs = jnp.where(valid, upd, outputs)
            state = lax.ppermute(
                state, axis_name, [(i, (i + 1) % pp) for i in range(pp)])
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(M + pp - 1))
        # hand the last stage's collected outputs to every pipe rank (the
        # head/loss run replicated over pipe outside this shard_map)
        outputs = lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs.reshape(xin.shape)

    shmapped = _compat_shard_map(
        local,
        mesh=mesh,
        in_specs=({n: P(axis_name) for n in stacked}, P()),
        out_specs=P(),
        axis_names={axis_name},
    )
    return shmapped(stacked, x)


def ring_buffer_slots(pp: int) -> int:
    """Saved activations per stage under the 1F1B schedule: the maximum
    number of in-flight microbatches at stage 0 is ``2·pp − 1`` — a
    constant in ``num_microbatches``, which is the memory win 1F1B exists
    to provide (GPipe holds all M)."""
    return 2 * pp - 1


def pipeline_train_step(
    blocks: Sequence,
    x: jax.Array,
    labels,
    loss_fn,
    *,
    num_microbatches: Optional[int] = None,
    schedule: str = "1f1b",
    mesh=None,
    axis_name: str = "pipe",
    params: Optional[Dict[str, jax.Array]] = None,
    head_params=None,
    head_loss_fn=None,
    head_aux_fn=None,
    return_dx: bool = False,
    rng_key: Optional[jax.Array] = None,
):
    """One pipelined fwd+bwd pass: returns ``(mean_loss, grads)`` with
    ``grads = {param_name_within_block: [L, ...]}`` stacked over blocks.

    Full-model mode (how ``Model.fit`` drives 1F1B, matching the reference
    SectionWorker where the first/last sections hold the embedding and the
    loss): pass ``params`` (the traced stacked block params — so the step
    differentiates the *caller's* pytree, not eager box snapshots),
    ``head_loss_fn(y_mb, label_mb, head_params)`` with ``head_params`` (the
    non-block parameters; the last stage differentiates both per
    microbatch), and ``return_dx=True`` to get the cotangent w.r.t. ``x``
    for the caller's embedding vjp.  Returns
    ``(loss, block_grads, dx, head_grads)`` in that mode.  ``labels`` may
    be any pytree of arrays with leading batch dim.  ``rng_key`` seeds
    per-(block, microbatch) dropout; required under jit (the eager
    generator cannot be read at trace time).

    ``schedule="1f1b"`` interleaves each stage's forwards and backwards in
    ONE lax.scan (the reference SectionWorker's 1F1B thread loop,
    section_worker.cc:82-230, as a compiled SPMD schedule): at tick ``t``
    stage ``s`` forwards microbatch ``t−s`` and backwards microbatch
    ``t−(2·pp−2−s)``, so the last stage backs each microbatch the tick it
    forwards it and live activations are bounded by
    :func:`ring_buffer_slots` (2·pp−1) instead of M.  The backward
    recomputes the stage forward from the saved stage INPUT (activation
    rematerialization — the standard 1F1B companion), so per-microbatch
    state is one activation, not a residual pytree.  Activations ppermute
    down the ``pipe`` ring, cotangents ppermute up, both with the
    one-tick lag the schedule provides naturally.

    ``head_aux_fn(y_mb, label_mb) → pytree`` (full mode only, optional):
    a non-differentiated per-microbatch computation on the LAST stage —
    how fetch-based metrics ride the schedule (the reference SectionWorker
    serves metric fetches from its last section, section_worker.cc:82-230).
    Each leaf must keep the microbatch dim first; leaves are written into
    an (M, mb, ...) buffer at the stage's forward tick and returned
    concatenated to full-batch order as a 5th output
    ``(loss, block_grads, dx, head_grads, aux)``.  Model.prepare(metrics=)
    under 1F1B computes ``metric.compute`` per microbatch here and feeds
    ``metric.update`` on the host — no full-batch logits are ever
    assembled.

    ``schedule="gpipe"`` runs :func:`pipeline_blocks` under
    ``jax.value_and_grad`` (fwd-all-then-bwd-all) with the same signature
    — the two schedules are interchangeable and gradient-equivalent.

    ``loss_fn(y_mb, label_mb) → scalar`` must mean over its microbatch;
    the returned loss is the mean over microbatches.  Gradients w.r.t.
    ``x`` are not returned (training steps differentiate parameters).
    """
    mesh = mesh or get_mesh()
    pp = mesh.shape.get(axis_name, 1)
    L = len(blocks)
    template = blocks[0]
    stacked_flat = (params if params is not None
                    else _stack_block_params(blocks))  # {n: [L, ...]}
    if head_loss_fn is None and return_dx:
        # dx without head params: synthesize the head closure from loss_fn
        head_loss_fn = lambda yy, lbl, _hp: loss_fn(yy, lbl)  # noqa: E731
    full_mode = head_loss_fn is not None
    if full_mode and head_params is None:
        head_params = {}

    schedule = str(schedule).lower()
    if schedule == "f-then-b":  # the reference's name for fwd-all-bwd-all
        schedule = "gpipe"
    if schedule not in ("1f1b", "gpipe"):
        raise InvalidArgumentError(
            f"pipeline schedule must be '1f1b', 'gpipe' or 'F-then-B', "
            f"got {schedule!r}")

    labels = jax.tree_util.tree_map(jnp.asarray, labels)
    if head_aux_fn is not None and not full_mode:
        raise InvalidArgumentError(
            "pipeline_train_step: head_aux_fn needs full-model mode "
            "(pass head_loss_fn)")
    if schedule == "gpipe" or pp == 1:
        if full_mode or return_dx:
            # one differentiable graph: GPipe is plain value_and_grad over
            # the same decomposition (used for 1f1b loss-parity checks and
            # the pp=1 degenerate case)
            def lfn(st, hp, xx):
                y = pipeline_blocks(blocks, xx,
                                    num_microbatches=num_microbatches,
                                    mesh=mesh, axis_name=axis_name,
                                    params=st)
                aux = (jax.lax.stop_gradient(head_aux_fn(y, labels))
                       if head_aux_fn is not None else None)
                return head_loss_fn(y, labels, hp), aux

            (loss, aux), (g_blocks, g_head, dx) = jax.value_and_grad(
                lfn, argnums=(0, 1, 2), has_aux=True)(
                    stacked_flat, head_params, x)
            if head_aux_fn is not None:
                return loss, g_blocks, dx, g_head, aux
            return loss, g_blocks, dx, g_head

        def lfn(st):
            y = pipeline_blocks(blocks, x,
                                num_microbatches=num_microbatches,
                                mesh=mesh, axis_name=axis_name, params=st)
            return loss_fn(y, labels)

        return jax.value_and_grad(lfn)(stacked_flat)

    if L % pp:
        raise InvalidArgumentError(
            f"pipeline: {L} blocks not divisible by pp={pp} stages")
    if list(template.named_buffers()):
        raise InvalidArgumentError(
            "pipeline blocks must be buffer-free (use LayerNorm)")
    per_stage = L // pp
    M = int(num_microbatches or pp)
    B = x.shape[0]
    if B % M:
        raise InvalidArgumentError(
            f"pipeline: batch {B} not divisible by {M} microbatches")
    mb = B // M
    RB = ring_buffer_slots(pp)

    training = bool(getattr(template, "training", False))
    if rng_key is not None:
        base_key = rng_key
    else:
        base_key = current_rng_key() if training else jax.random.PRNGKey(0)

    stacked = {n: v.reshape((pp, per_stage) + v.shape[1:])
               for n, v in stacked_flat.items()}

    def local(stage_params, xin, yin, head_p):
        stage_params = {n: v[0] for n, v in stage_params.items()}
        stage = lax.axis_index(axis_name)
        micro = xin.reshape((M, mb) + xin.shape[1:])
        lmicro = jax.tree_util.tree_map(
            lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), yin)
        act_shape = (mb,) + xin.shape[1:]

        def stage_apply(pdict, h, mb_idx):
            def body(h, idx_and_params):
                j, pd = idx_and_params
                key = jax.random.fold_in(
                    jax.random.fold_in(base_key,
                                       stage * per_stage + j), mb_idx)
                return functional_call(template, pd, h, rngs=key), None

            # scan over the ARGUMENT pdict (not the closure) — the backward
            # tick takes jax.vjp w.r.t. it
            h, _ = lax.scan(body, h, (jnp.arange(per_stage), pdict))
            return h

        zero_grads = jax.tree_util.tree_map(
            lambda v: jnp.zeros_like(v, jnp.float32), stage_params)
        zero_head = jax.tree_util.tree_map(
            lambda v: jnp.zeros_like(v, jnp.float32), head_p)
        if head_aux_fn is not None:
            # per-microbatch metric rows (last stage): discover the aux
            # structure abstractly, buffer (M, mb, ...) rows
            lbl0 = jax.tree_util.tree_map(lambda a: a[0], lmicro)
            aux_avals = jax.eval_shape(
                head_aux_fn, jax.ShapeDtypeStruct(act_shape, x.dtype), lbl0)
            aux_zero = jax.tree_util.tree_map(
                lambda av: jnp.zeros((M,) + av.shape, av.dtype), aux_avals)
        else:
            aux_zero = jnp.zeros((), jnp.float32)
        carry0 = (
            jnp.zeros(act_shape, x.dtype),           # fwd_recv
            jnp.zeros(act_shape, jnp.float32),       # bwd_recv (cotangent)
            jnp.zeros((RB,) + act_shape, x.dtype),   # saved stage inputs
            zero_grads,                              # grad accumulator
            jnp.zeros((), jnp.float32),              # loss accumulator
            zero_head,                               # head grad accumulator
            jnp.zeros((M,) + act_shape, jnp.float32)  # dx per microbatch
            if return_dx else jnp.zeros((), jnp.float32),
            aux_zero,                                # metric rows
        )
        i32 = jnp.int32
        is_last = stage == pp - 1

        def mb_loss(yy, lbl, hp):
            if full_mode:
                return head_loss_fn(yy, lbl, hp)
            return loss_fn(yy, lbl)

        def tick(carry, t):
            (fwd_recv, bwd_recv, ring, grad_acc, loss_acc, head_acc,
             dx_buf, aux_buf) = carry
            t = t.astype(i32)
            f = t - stage
            b = t - (i32(2 * pp - 2) - stage)
            do_f = (f >= 0) & (f < M)
            do_b = (b >= 0) & (b < M)

            # ---- forward tick for microbatch f
            f_safe = jnp.clip(f, 0, M - 1)
            h_in = jnp.where(stage == 0,
                             lax.dynamic_index_in_dim(micro, f_safe, 0,
                                                      keepdims=False),
                             fwd_recv)
            y = stage_apply(stage_params, h_in, f_safe)
            ring = jnp.where(
                do_f,
                lax.dynamic_update_index_in_dim(ring, h_in, f_safe % RB, 0),
                ring)

            # ---- last stage: per-microbatch loss + output cotangent (and,
            # in full mode, the head/loss parameter grads); its backward
            # microbatch b equals f, so dy feeds this very tick
            lbl = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, f_safe, 0,
                                                   keepdims=False), lmicro)
            loss_val, (dy, dhead) = jax.value_and_grad(
                lambda yy, hp: mb_loss(yy, lbl, hp), argnums=(0, 1))(
                    y.astype(jnp.float32), head_p)
            loss_acc = loss_acc + jnp.where(do_f & is_last, loss_val, 0.0)
            head_acc = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(do_f & is_last,
                                           g.astype(jnp.float32), 0.0),
                head_acc, dhead)
            if head_aux_fn is not None:
                aux_mb = head_aux_fn(y, lbl)
                aux_buf = jax.tree_util.tree_map(
                    lambda buf, v: jnp.where(
                        do_f & is_last,
                        lax.dynamic_update_index_in_dim(
                            buf, v.astype(buf.dtype), f_safe, 0),
                        buf),
                    aux_buf, aux_mb)
            dy = dy / M  # total loss is the MEAN over microbatches

            # ---- backward tick for microbatch b (recompute-from-input)
            b_safe = jnp.clip(b, 0, M - 1)
            h_saved = lax.dynamic_index_in_dim(ring, b_safe % RB, 0,
                                               keepdims=False)
            cot_in = jnp.where(is_last, dy, bwd_recv).astype(jnp.float32)
            _, vjp = jax.vjp(
                lambda p, h: stage_apply(p, h, b_safe).astype(jnp.float32),
                stage_params, h_saved)
            dparams, dh = vjp(cot_in)
            grad_acc = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(do_b, g.astype(jnp.float32), 0.0),
                grad_acc, dparams)
            if return_dx:
                # stage 0's input cotangent IS dloss/dx for microbatch b
                dx_buf = jnp.where(
                    do_b & (stage == 0),
                    lax.dynamic_update_index_in_dim(
                        dx_buf, dh.astype(jnp.float32), b_safe, 0),
                    dx_buf)

            # ---- neighbor exchange: activations down, cotangents up
            fwd_recv = lax.ppermute(
                y, axis_name, [(i, (i + 1) % pp) for i in range(pp)])
            bwd_recv = lax.ppermute(
                jnp.where(do_b, dh.astype(jnp.float32), 0.0), axis_name,
                [(i, (i - 1) % pp) for i in range(pp)])
            return (fwd_recv, bwd_recv, ring, grad_acc, loss_acc, head_acc,
                    dx_buf, aux_buf), None

        T = M + 2 * pp - 2
        (fwd_recv, bwd_recv, ring, grad_acc, loss_acc, head_acc,
         dx_buf, aux_buf), _ = lax.scan(tick, carry0, jnp.arange(T))
        loss = lax.psum(loss_acc, axis_name) / M
        # grads live per-stage; shard_map reassembles the pp axis
        grad_acc = jax.tree_util.tree_map(lambda g: g[None], grad_acc)
        # head grads exist on the last stage only; dx on stage 0 only —
        # psum replicates both across the ring
        head_acc = jax.tree_util.tree_map(
            lambda g: lax.psum(
                jnp.where(is_last, g, jnp.zeros_like(g)), axis_name) / M,
            head_acc)
        dx_out = (lax.psum(
            jnp.where(stage == 0, dx_buf, jnp.zeros_like(dx_buf)),
            axis_name) if return_dx else dx_buf)
        if head_aux_fn is not None:
            # metric rows live on the last stage only; psum replicates
            aux_buf = jax.tree_util.tree_map(
                lambda a: lax.psum(
                    jnp.where(is_last, a, jnp.zeros_like(a)), axis_name),
                aux_buf)
        return loss, grad_acc, head_acc, dx_out, aux_buf

    if head_aux_fn is not None:
        lbl0_host = jax.tree_util.tree_map(lambda a: a[:mb], labels)
        aux_struct = jax.eval_shape(
            head_aux_fn, jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype),
            lbl0_host)
        aux_spec = jax.tree_util.tree_map(lambda _: P(), aux_struct)
    else:
        aux_spec = P()
    shmapped = _compat_shard_map(
        local,
        mesh=mesh,
        in_specs=({n: P(axis_name) for n in stacked}, P(), P(), P()),
        out_specs=(P(), {n: P(axis_name) for n in stacked}, P(), P(),
                   aux_spec),
        axis_names={axis_name},
    )
    loss, grads, head_grads, dx, aux = shmapped(stacked, x, labels,
                                                head_params)
    grads = {n: g.reshape((L,) + g.shape[2:]) for n, g in grads.items()}
    if head_aux_fn is not None:
        # (M, mb, ...) rows → full-batch order (each leaf keeps its
        # microbatch dim first — metric.compute preserves the batch dim)
        aux = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]),
            aux)
    if full_mode or return_dx:
        if return_dx:
            dx = dx.reshape((B,) + x.shape[1:])
        else:
            dx = None
        if head_aux_fn is not None:
            return loss, grads, dx, head_grads, aux
        return loss, grads, dx, head_grads
    return loss, grads

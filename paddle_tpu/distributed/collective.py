"""User-facing collectives.

Parity: python/paddle/distributed/collective.py (broadcast:89, all_reduce:146,
reduce:221, all_gather:304, scatter:377, barrier:449) and the c_* collective
ops (operators/collective/c_allreduce_op.h:109 NCCL dispatch).

TPU-native semantics: there is ONE controller per host, not one process per
chip, so "each rank's tensor" is expressed as a *stacked global array* whose
leading dim indexes ranks along a mesh axis (default ``data``).  Each
collective shard_maps a ``lax`` collective over that axis — XLA lowers it to
an ICI/DCN all-reduce/gather/permute exactly like the reference's NCCL ring
call, but compiler-scheduled and fusable.  After the call, every rank slot
holds the value paddle's per-process API would give that rank.

For *in-graph* use (inside your own ``shard_map``), use the primitives
directly: ``psum``/``pmean``/``pmax``/``ppermute``/``all_to_all`` re-exports.
"""
from __future__ import annotations

import functools
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6: top-level export, replication check spelled check_vma
    from jax import shard_map as _shard_map
    _LEGACY_SHARD_MAP = False
except ImportError:  # older jax: experimental module, check_rep + auto
    from jax.experimental.shard_map import shard_map as _shard_map
    _LEGACY_SHARD_MAP = True


def shard_map(f, mesh, in_specs, out_specs, axis_names=None):
    # replication check off: collectives like all_gather produce values
    # that ARE replicated over the group axis, but the static checker
    # can't always infer it.  ``axis_names`` restricts which mesh axes the
    # body is manual over (legacy jax spells that as the ``auto``
    # complement).
    if _LEGACY_SHARD_MAP:
        auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                if axis_names is not None else frozenset())
        if auto and jax.default_backend() == "cpu":
            # XLA's CPU SPMD partitioner can't lower PARTIAL-auto bodies:
            # lax.axis_index emits a PartitionId it rejects outright, and
            # pipe-axis ppermute/all_gather trip a manual-subgroup CHECK
            # in spmd_partitioner.cc.  Fall back to fully-manual (every
            # axis manual) — numerically identical, the auto axes just
            # lose their sharding hints, so the body's ``constrain``
            # calls (which would now name manual axes) are suppressed.
            from .mesh import suppress_constraints

            @functools.wraps(f)
            def f_manual(*args, **kwargs):
                with suppress_constraints():
                    return f(*args, **kwargs)

            return _shard_map(f_manual, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False,
                              auto=frozenset())
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, auto=auto)
    kw = {"axis_names": set(axis_names)} if axis_names is not None else {}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False, **kw)

from ..framework.errors import InvalidArgumentError, TransientDeviceError
from ..framework.flags import flag as _flag
from .mesh import get_mesh

__all__ = [
    "ReduceOp",
    "all_reduce",
    "all_gather",
    "reduce",
    "broadcast",
    "scatter",
    "alltoall",
    "barrier",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "ppermute",
    "all_to_all_single",
]

# in-graph primitive re-exports (for custom shard_map code)
psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute


def all_to_all_single(x, axis_name: str, split_axis: int = 0, concat_axis: int = 0):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
    ReduceOp.PROD: lambda x, a: lax.all_gather(x, a).prod(axis=0),
}


def _group_axis(group) -> str:
    # the one seam every collective passes through — chaos plans inject
    # device/interconnect failures here (site "collective.call")
    from ..resilience.faults import fault_point

    fault_point("collective.call")
    if group is None:
        return "data"
    if isinstance(group, str):
        return group
    return getattr(group, "axis", "data")


def _watchdog(fn):
    """Straggler watchdog: with FLAGS_collective_timeout_s set, the wrapped
    collective runs (through device completion — block_until_ready) in a
    worker thread under a deadline; a wedged interconnect raises
    ``TransientDeviceError`` into the retry/restart path instead of
    hanging the rank forever.  Disabled (the default 0.0) the wrapper is a
    single falsy flag check."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        timeout = _flag("collective_timeout_s")
        if not timeout:
            return fn(*args, **kwargs)
        done = threading.Event()
        box: dict = {}

        def _run():
            try:
                box["value"] = jax.block_until_ready(fn(*args, **kwargs))
            except BaseException as e:  # surfaced in the caller below
                box["error"] = e
            finally:
                done.set()

        # daemon: a wedged device call may never return — the thread must
        # not block interpreter shutdown after the deadline fires
        t = threading.Thread(target=_run, daemon=True,
                             name=f"collective-watchdog-{fn.__name__}")
        t.start()
        if not done.wait(float(timeout)):
            from ..framework import monitor as _monitor
            from ..framework.logging import vlog
            from ..resilience import supervisor as _supervisor

            _monitor.stat_add("collective_watchdog_trips")
            _supervisor.record("watchdog_trips")
            vlog(0, "collective: %s exceeded the %.1fs watchdog deadline "
                    "— raising TransientDeviceError", fn.__name__, timeout)
            raise TransientDeviceError(
                f"collective {fn.__name__} did not complete within "
                f"FLAGS_collective_timeout_s={timeout:g}s — wedged "
                f"interconnect or straggler rank; the call keeps running "
                f"on its watchdog thread but this rank treats it as a "
                f"transient device failure")
        if "error" in box:
            raise box["error"]
        return box["value"]

    return wrapper


def _stacked(tensor, axis: str):
    mesh = get_mesh()
    n = mesh.shape[axis]
    tensor = jnp.asarray(tensor)
    if tensor.shape[0] != n:
        raise InvalidArgumentError(
            f"stacked collective input must have leading dim {n} "
            f"(= size of mesh axis {axis!r}), got {tensor.shape}"
        )
    return mesh, tensor


@functools.partial(jax.jit, static_argnames=("op", "axis", "mesh"))
def _all_reduce_jit(tensor, op, axis, mesh):
    reducer = _REDUCERS[op]

    def f(t):  # t: [1, ...] per rank
        return reducer(t, axis)

    return shard_map(f, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(tensor)


def _all_reduce_impl(tensor, op, axis):
    # the mesh is a static jit key: set_mesh() must never hit a stale cache
    return _all_reduce_jit(tensor, op, axis, get_mesh())


@_watchdog
def all_reduce(tensor, op: str = ReduceOp.SUM, group=None, sync_op: bool = True):
    """Every rank slot ends with the reduction over all rank slots."""
    axis = _group_axis(group)
    _, tensor = _stacked(tensor, axis)
    return _all_reduce_impl(tensor, op, axis)


@_watchdog
def all_gather(tensor_or_list, tensor=None, group=None, sync_op: bool = True) -> List[jax.Array]:
    """Returns the list of per-rank tensors (replicated everywhere).

    Call styles: ``all_gather(stacked)`` or paddle-style
    ``all_gather(out_list, stacked)`` which extends ``out_list``.
    """
    out_list = None
    if tensor is None:
        stacked = tensor_or_list
    else:
        out_list, stacked = tensor_or_list, tensor
    axis = _group_axis(group)
    mesh, stacked = _stacked(stacked, axis)

    def f(t):  # [1, ...] → gather to [n, ...] on every rank
        return lax.all_gather(t, axis, axis=0, tiled=True)

    gathered = shard_map(f, mesh=mesh, in_specs=P(axis), out_specs=P(None))(stacked)
    result = [gathered[i] for i in range(gathered.shape[0])]
    if out_list is not None:
        out_list.extend(result)
    return result


@_watchdog
def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group=None, sync_op: bool = True):
    """Rank ``dst``'s slot gets the reduction; other slots keep their value."""
    axis = _group_axis(group)
    mesh, tensor = _stacked(tensor, axis)
    reducer = _REDUCERS[op]

    def f(t):
        total = reducer(t, axis)
        i = lax.axis_index(axis)
        return jnp.where(i == dst, total, t)

    return shard_map(f, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(tensor)


@_watchdog
def broadcast(tensor, src: int = 0, group=None, sync_op: bool = True):
    """Every rank slot ends with rank ``src``'s value."""
    axis = _group_axis(group)
    mesh, tensor = _stacked(tensor, axis)

    def f(t):
        # mask-and-sum: contributes only src's shard, summed over the axis —
        # lowers to a one-hot all-reduce (XLA folds it into a broadcast)
        i = lax.axis_index(axis)
        contrib = jnp.where(i == src, t, jnp.zeros_like(t))
        return lax.psum(contrib, axis)

    return shard_map(f, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(tensor)


@_watchdog
def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op: bool = True):
    """Rank i's slot gets ``tensor_list[i]`` (from rank src).  With the
    stacked representation the rows ARE the per-rank values, so this
    broadcasts src's stacked rows and selects row i for rank i."""
    axis = _group_axis(group)
    if tensor_list is not None:
        tensor = jnp.stack([jnp.asarray(t) for t in tensor_list], axis=0)
    mesh, tensor = _stacked(tensor, axis)
    return tensor  # row i is already rank i's result


@_watchdog
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op: bool = True):
    """result[i][j] = input[j][i] over the group axis (ragged-free)."""
    axis = _group_axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        stacked = jnp.stack([jnp.asarray(t) for t in in_tensor_list], axis=0)
    else:
        stacked = jnp.asarray(in_tensor_list)
    mesh, stacked = _stacked(stacked, axis)

    def f(t):  # t: [1, n, ...] per rank — swap rank/slot dims globally
        return lax.all_to_all(t, axis, split_axis=1, concat_axis=0, tiled=False)

    n = mesh.shape[axis]
    if stacked.shape[1] != n:
        raise InvalidArgumentError(
            f"alltoall needs [n, n, ...] stacked input, got {stacked.shape}"
        )
    out = shard_map(f, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(stacked)
    out = out.reshape(stacked.shape)
    result = [out[i] for i in range(n)]
    if out_tensor_list is not None:
        out_tensor_list.extend(result)
    return result


@_watchdog
def barrier(group=None):
    """Block until all prior device work completes (XLA programs are
    compiler-ordered; the host-visible barrier is block_until_ready)."""
    axis = _group_axis(group)
    mesh = get_mesh()
    n = mesh.shape[axis]
    token = jnp.zeros((n,), jnp.int32)
    out = _all_reduce_impl(token, ReduceOp.SUM, axis)
    jax.block_until_ready(out)


# ---------------------------------------------------------------------------
# Overlap schedules: WHERE the decode-path collectives land, as a tunable.
#
# The megatron layers never call collectives directly — they annotate
# (`constrain`) and GSPMD inserts the tensor/expert-parallel all-reduces at
# the annotation points.  GSPMD is semantics-preserving, so moving an
# annotation never changes the value, only WHERE the reduce materializes —
# which decides how much neighboring compute XLA's latency-hiding scheduler
# can overlap the ICI transfer with.  A decode step is latency-bound, so
# the placement is worth real microseconds per layer; instead of
# hand-picking, the dials below are searched by `tuning.plan_space.
# tune_decode_schedule` on REAL decode steps (the `overlap_grad_sync`
# treatment, applied to inference collectives).
#
# Dials (all 0/1, read at TRACE time — retrace after changing them):
#   defer_row_reduce     — RowParallelLinear skips its immediate
#                          output-replication constrain; the all-reduce
#                          slides to the next annotation (after bias/
#                          residual), freeing the scheduler to overlap it
#                          with the adjacent elementwise work.
#   mlp_collective_split — GPTBlock splits the decode residual stream
#                          around the MLP: the MLP's row-parallel reduce is
#                          deferred past the residual add and pinned there,
#                          so it can run concurrently with the add.
_OVERLAP_DIALS = ("defer_row_reduce", "mlp_collective_split")
_overlap_schedule = {k: 0 for k in _OVERLAP_DIALS}
_overlap_lock = threading.Lock()


def get_overlap_schedule() -> dict:
    """The active overlap-schedule dials (a copy)."""
    with _overlap_lock:
        return dict(_overlap_schedule)


def set_overlap_schedule(config: Optional[dict] = None, **dials) -> dict:
    """Set overlap dials (unknown keys rejected; unset dials keep their
    value).  Returns the previous schedule.  Functions traced AFTER the
    call see the new placement; already-compiled executables keep the
    schedule they were traced under."""
    from ..framework.errors import InvalidArgumentError

    merged = dict(config or ())
    merged.update(dials)
    for k in merged:
        if k not in _OVERLAP_DIALS:
            raise InvalidArgumentError(
                f"unknown overlap dial {k!r} (have {_OVERLAP_DIALS})")
    with _overlap_lock:
        prev = dict(_overlap_schedule)
        for k, v in merged.items():
            _overlap_schedule[k] = int(v)
    return prev


class overlap_schedule:
    """Context manager: apply overlap dials for the trace inside, restore
    the previous schedule on exit."""

    def __init__(self, config: Optional[dict] = None, **dials):
        self._new = dict(config or ())
        self._new.update(dials)

    def __enter__(self):
        self._prev = set_overlap_schedule(self._new)
        return get_overlap_schedule()

    def __exit__(self, *exc):
        set_overlap_schedule(self._prev)


def all_reduce_start(x, axis_name: str):
    """Stage an in-graph all-reduce (for explicit ``shard_map`` bodies):
    returns an opaque handle; the reduce itself happens at
    :func:`all_reduce_finish`.  The pair is a SCHEDULING seam, not an
    async runtime: everything the caller computes between start and
    finish is, by data dependence, free to execute while the reduce is
    in flight — XLA's latency-hiding scheduler does the actual overlap
    (the same contract as `overlap_grad_sync` staging for grad syncs).
    """
    return (x, str(axis_name))


def all_reduce_finish(handle):
    """Complete a staged in-graph all-reduce: the ``lax.psum`` over the
    axis captured at :func:`all_reduce_start`."""
    x, axis_name = handle
    return lax.psum(x, axis_name)


__all__ += [
    "all_reduce_start",
    "all_reduce_finish",
    "get_overlap_schedule",
    "set_overlap_schedule",
    "overlap_schedule",
]

"""Global device mesh management.

The named ``jax.sharding.Mesh`` replaces the reference's ring_id→communicator
registry (platform/collective_helper.h:62 NCCLCommContext) and its
multi-ring/hierarchical NCCL plumbing (nccl_helper.h:185): every parallelism
axis is a *named mesh dimension* (``data``, ``model``, ``pipe``, ``sep``)
and XLA lowers collectives onto ICI/DCN along those axes.

Axis-order convention (outer→inner): ``pipe``, ``data``, ``sharding``,
``sep``, ``expert``, ``model`` — the model axis is innermost so
tensor-parallel collectives (the most latency-sensitive) map onto
directly-wired ICI neighbors; the ``expert`` axis (MoE all-to-alls, see
paddle_tpu/moe) sits next-innermost so dispatch/combine also ride ICI,
while data/pipeline axes can span DCN.  This mirrors the scaling-book
recipe rather than anything in the reference (which has no TP/PP mesh
concept at all).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.errors import InvalidArgumentError

__all__ = [
    "build_mesh",
    "get_mesh",
    "set_mesh",
    "mesh_axis_size",
    "data_axes",
    "suppress_constraints",
    "constraints_suppressed",
    "PartitionSpec",
    "NamedSharding",
    "Mesh",
]

# canonical axis names, outer→inner
AXIS_ORDER = ("pipe", "data", "sharding", "sep", "expert", "model")

_global_mesh: Optional[Mesh] = None


def build_mesh(
    dp: int = 0,
    mp: int = 1,
    pp: int = 1,
    sep: int = 1,
    sharding: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_dcn_inner: bool = False,
) -> Mesh:
    """Construct the hybrid-parallel mesh.  ``dp=0`` means "all remaining
    devices".  Degrees multiply to the device count.

    Multi-host (a ``jax.distributed``-joined gang): the mesh is DCN x ICI
    aware.  Devices are ordered **process-major** so, with the
    outer→inner ``AXIS_ORDER`` reshape, the outer axes (``pipe``,
    ``data``) span process/DCN boundaries while the inner axes
    (``sharding``/``sep``/``expert``/``model`` — the latency-sensitive
    collectives) stay inside a host's directly-wired ICI domain.  An
    inner-axis block that would straddle hosts (inner degrees not fitting
    the per-host device count) is rejected with guidance unless
    ``allow_dcn_inner=True`` — tensor-parallel allreduce over DCN is
    usually a config bug, not a plan.
    """
    if devices is None:
        devices = list(jax.devices())
        if jax.process_count() > 1:
            # process-major: contiguous ICI blocks per host, DCN on the
            # outer axes.  jax.devices() usually already satisfies this,
            # but the mesh must not depend on backend enumeration luck.
            devices.sort(key=lambda d: (d.process_index, d.id))
            local = len(devices) // jax.process_count()
            inner = mp * ep * sep * sharding
            if local and inner > 1 and local % inner != 0 \
                    and not allow_dcn_inner:
                raise InvalidArgumentError(
                    f"inner (ICI) axes model*expert*sep*sharding={inner} "
                    f"do not fit the {local} devices of one host — a "
                    "tensor/expert-parallel group would cross DCN.  Move "
                    "parallelism to data/pipe, or pass "
                    "allow_dcn_inner=True if cross-host inner collectives "
                    "are intended")
    else:
        devices = list(devices)
    n = len(devices)
    fixed = mp * pp * sep * sharding * ep
    if fixed <= 0:
        raise InvalidArgumentError("parallel degrees must be positive")
    if dp in (0, -1, None):
        if n % fixed != 0:
            raise InvalidArgumentError(
                f"device count {n} not divisible by mp*pp*sep*sharding*ep="
                f"{fixed}"
            )
        dp = n // fixed
    if dp * fixed != n:
        raise InvalidArgumentError(
            f"dp*mp*pp*sep*sharding*ep = {dp * fixed} != device count {n}"
        )
    sizes = {"pipe": pp, "data": dp, "sharding": sharding, "sep": sep,
             "expert": ep, "model": mp}
    shape = [sizes[a] for a in AXIS_ORDER]
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Mesh:
    """The active global mesh; defaults to pure data-parallel over all
    devices (every chip in the ``data`` axis)."""
    global _global_mesh
    if _global_mesh is None:
        _global_mesh = build_mesh()
    return _global_mesh


def mesh_axis_size(axis: str, mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape[axis]


_suppress_tls = threading.local()


def constraints_suppressed() -> bool:
    """True while inside a :func:`suppress_constraints` scope (per thread)."""
    return getattr(_suppress_tls, "depth", 0) > 0


@contextlib.contextmanager
def suppress_constraints():
    """Make ``meta_parallel.constrain`` a no-op while tracing.

    Needed when a region is traced inside a FULLY-manual ``shard_map``:
    every mesh axis is manual there, so ``with_sharding_constraint`` over
    ``model``/``data`` is both illegal (jax rejects specs naming manual
    axes) and meaningless (the body already sees per-device values).  The
    pipeline schedules use this on backends where partial-auto shard_map
    can't lower (see ``collective.shard_map``)."""
    _suppress_tls.depth = getattr(_suppress_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _suppress_tls.depth -= 1


def data_axes(mesh: Optional[Mesh] = None) -> List[str]:
    """Axes a global batch is split over: data + (ZeRO) sharding — the
    sharding axis is data-parallel for the forward pass."""
    mesh = mesh or get_mesh()
    return [a for a in ("data", "sharding") if mesh.shape.get(a, 1) > 1] or ["data"]

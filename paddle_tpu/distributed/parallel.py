"""DataParallel wrapper + spawn/launch helpers.

Parity: paddle.DataParallel (fluid/dygraph/parallel.py:335 — grad coalescing
+ allreduce hooks) and paddle.distributed.spawn/launch.

Under SPMD none of the reference's machinery (coalesced grad buffers
:229-284, imperative allreduce, nccl bootstrap) exists as user-visible
moving parts: wrapping a Layer just replicates its parameters over the mesh
and records that batches should be split over the data axes.  The hapi
Model / fleet path does this automatically; DataParallel exists for users
who write their own step functions.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

import jax

from ..framework.errors import InvalidArgumentError
from ..nn.layer_base import Layer
from . import env as _env
from .mesh import get_mesh

__all__ = ["DataParallel", "spawn", "launch", "shard_batch",
           "RESTART_STORM_EXIT_CODE", "GANG_RESTART_EXIT_CODE"]

#: watch() exit code when the restart-storm window trips: the trainer
#: crash-looped (storm_restarts restarts inside storm_window seconds), so
#: restarting again would hot-spin the host.  Distinct from the child's own
#: codes so schedulers can tell "gave up on a crash loop" from "trainer
#: failed once and exhausted the budget".
RESTART_STORM_EXIT_CODE = 77

#: a trainer exits with this code to REQUEST a gang restart from its
#: watchdog: its gang generation was abandoned (a peer reincarnated while
#: a collective was in flight — Gang raises TransientDeviceError) and
#: only a relaunch-and-rejoin re-forms the group.  Like a peer-loss gang
#: restart this consumes no failure budget: the peer's death is not this
#: trainer's fault.  It exists because a SIGKILLed host can relaunch
#: FASTER than the peer-heartbeat timeout — no watchdog ever sees a stale
#: beat, yet the old generation is dead; the blocked survivors must break
#: the livelock themselves (see Gang._check_reincarnation).
GANG_RESTART_EXIT_CODE = 76


class DataParallel(Layer):
    """Replicate a Layer across the mesh; forward = inner forward.

    ``scale_loss``/``apply_collective_grads`` are kept as no-ops for source
    compatibility with reference training loops (gradient averaging falls
    out of psum/mean in the SPMD step).
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1, find_unused_parameters: bool = False):
        super().__init__()
        self._layers = layers
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = get_mesh()
        repl = NamedSharding(mesh, P())
        for _, p in layers.named_parameters():
            p.value = jax.device_put(p.value, repl)
        for _, b in layers.named_buffers():
            b.value = jax.device_put(b.value, repl)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


def shard_batch(x, mesh=None, axes=None):
    """Assemble a *global* batch array from this host's local shard.

    Each host's ``DataLoader`` (with a ``DistributedBatchSampler`` ranked
    by ``process_index``) loads only its slice; this places that slice on
    the local devices and stitches the global sharded array via
    ``jax.make_array_from_process_local_data`` — no host ever
    materializes (or transfers) the full batch.  Single-process: a plain
    ``device_put`` with the same sharding, so step functions are
    identical on a laptop and a pod.

    ``axes`` defaults to :func:`mesh.data_axes` (data + ZeRO sharding)
    over the leading batch dimension.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import data_axes

    mesh = mesh or get_mesh()
    if axes is None:
        axes = data_axes(mesh)
    x = np.asarray(x)
    spec = P(tuple(axes)) if x.ndim else P()
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)


def spawn(func, args=(), nprocs: Optional[int] = None, join: bool = True, **kwargs):
    """Parity: paddle.distributed.spawn.  On TPU the unit of spawning is a
    *host process driving all local chips* — inside one host there is nothing
    to spawn (SPMD covers the local devices), so this runs ``func`` once.
    Multi-host pods launch one process per host externally (see launch)."""
    if nprocs not in (None, 1) and jax.process_count() == 1:
        raise InvalidArgumentError(
            "spawn(nprocs>1) maps to multi-host launch on TPU: one process "
            "drives all local chips (SPMD), so per-device process spawning "
            "does not exist.  Use paddle_tpu.distributed.launch across hosts."
        )
    _env.init_parallel_env()
    func(*args)


def launch(argv=None):
    """`python -m paddle_tpu.distributed.launch [--max-restarts=N] script.py`
    (reference: fleet/launch.py:183).  One process per host — the pod
    runtime starts this command on every host.

    Default: exec the training script in-process.  With ``--max-restarts``
    the script runs as a watched subprocess instead (the reference's
    launch_utils.py TrainerProc watch loop): a non-zero exit restarts it up
    to N times — pair with incubate.checkpoint auto-resume and a preempted/
    crashed trainer continues from its last snapshot (the elastic-lite
    story; the reference's `strategy.elastic` proto field was never
    implemented)."""
    import runpy

    argv = list(sys.argv[1:] if argv is None else argv)
    usage = ("usage: python -m paddle_tpu.distributed.launch "
             "[--max-restarts=N] [--hang-timeout=SECONDS] "
             "[--peer-timeout=SECONDS] [--storm-window=SECONDS] "
             "[--storm-restarts=N] script.py [args...]")
    max_restarts = 0
    watched = False
    hang_timeout = None
    peer_timeout = None
    storm_window = None
    storm_restarts = 5

    def _flag_value(flag, argv):
        return flag.split("=", 1)[1] if "=" in flag else argv.pop(0)

    while argv and argv[0].startswith("--"):
        flag = argv.pop(0)
        if flag == "--max-restarts" or flag.startswith("--max-restarts="):
            watched = True
            try:
                max_restarts = int(_flag_value(flag, argv))
            except (IndexError, ValueError):
                print(f"--max-restarts needs an integer value\n{usage}")
                return 2
        elif flag == "--hang-timeout" or flag.startswith("--hang-timeout="):
            watched = True
            try:
                hang_timeout = float(_flag_value(flag, argv))
                if hang_timeout <= 0:
                    raise ValueError
            except (IndexError, ValueError):
                print(f"--hang-timeout needs a positive number of "
                      f"seconds\n{usage}")
                return 2
        elif flag == "--peer-timeout" or flag.startswith("--peer-timeout="):
            watched = True
            try:
                peer_timeout = float(_flag_value(flag, argv))
                if peer_timeout <= 0:
                    raise ValueError
            except (IndexError, ValueError):
                print(f"--peer-timeout needs a positive number of "
                      f"seconds\n{usage}")
                return 2
        elif flag == "--storm-window" or flag.startswith("--storm-window="):
            try:
                storm_window = float(_flag_value(flag, argv))
                if storm_window <= 0:
                    raise ValueError
            except (IndexError, ValueError):
                print(f"--storm-window needs a positive number of "
                      f"seconds\n{usage}")
                return 2
        elif flag == "--storm-restarts" or flag.startswith(
                "--storm-restarts="):
            try:
                storm_restarts = int(_flag_value(flag, argv))
                if storm_restarts < 1:
                    raise ValueError
            except (IndexError, ValueError):
                print(f"--storm-restarts needs an integer >= 1\n{usage}")
                return 2
        else:
            print(f"unknown launch flag {flag}\n{usage}")
            return 2
    if not argv:
        print(usage)
        return 1
    script, *rest = argv
    if watched:
        # child re-enters launch in-process mode so init_parallel_env runs
        # inside each (re)started trainer, exactly like the unwatched path
        return _watch_host(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             script] + rest, max_restarts=max_restarts,
            hang_timeout=hang_timeout, peer_timeout=peer_timeout,
            storm_window=storm_window, storm_restarts=storm_restarts)
    sys.argv = [script] + rest
    _env.init_parallel_env()
    runpy.run_path(script, run_name="__main__")
    return 0


def _watch_host(cmd, max_restarts: int, hang_timeout, peer_timeout,
                storm_window, storm_restarts) -> int:
    """Arm :func:`watch` with the gang wiring the environment describes.

    With ``PADDLE_TPU_GANG_DIR`` + a multi-rank ``PADDLE_TRAINERS_NUM``
    this watchdog becomes a *gang member*: the child's heartbeat file
    moves into the shared gang directory (``beat.p<rank>`` — every peer
    watchdog reads it) and a :class:`heartbeat.PeerHeartbeatMonitor`
    feeds the gang-restore decision (``peer_timeout``, default
    ``PADDLE_TPU_PEER_TIMEOUT_S`` or 10s).  On exit, the watchdog's gang
    counters (``gang_restores``...) are appended to the per-rank metrics
    JSONL so ``exporters.merge_jsonl`` collates them pod-wide.
    """
    from ..framework import monitor as _monitor
    from .heartbeat import PeerHeartbeatMonitor, gang_beat_path

    gang_dir = os.environ.get(_env.ENV_GANG_DIR)
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    peer_monitor = None
    heartbeat_path = None
    if gang_dir and world > 1:
        if peer_timeout is None:
            peer_timeout = float(
                os.environ.get("PADDLE_TPU_PEER_TIMEOUT_S", "10") or 10)
        heartbeat_path = gang_beat_path(gang_dir, rank)
        peer_monitor = PeerHeartbeatMonitor(
            gang_dir, world, rank, timeout=peer_timeout).start()
        if hang_timeout is None:
            # gang members always need the beat file written (peers read
            # it); arm local hang detection too, generously
            hang_timeout = max(30.0, 6 * peer_timeout)
    for key in ("gang_restores", "trainer_restarts", "hung_trainers",
                "restart_storms", "preemption_restarts"):
        _monitor.reset_stat(key)
    try:
        rc = watch(cmd, max_restarts=max_restarts,
                   hang_timeout=hang_timeout,
                   storm_window=storm_window,
                   storm_restarts=storm_restarts,
                   peer_monitor=peer_monitor,
                   heartbeat_path=heartbeat_path,
                   gang_label=f"watch.p{rank}")
    finally:
        if peer_monitor is not None:
            peer_monitor.stop()
        metrics = os.environ.get("PADDLE_TPU_METRICS_JSONL")
        if metrics:
            try:
                from ..observability.exporters import process_jsonl_path
                import json
                import time as _time

                path = process_jsonl_path(metrics, rank)
                with open(path, "a") as f:
                    f.write(json.dumps({
                        "ts": _time.time(), "process_index": rank,
                        "kind": "gang_watch",
                        "gang_restores":
                            _monitor.get_stat("gang_restores"),
                        "trainer_restarts":
                            _monitor.get_stat("trainer_restarts"),
                        "hung_trainers":
                            _monitor.get_stat("hung_trainers"),
                        "restart_storms":
                            _monitor.get_stat("restart_storms"),
                    }) + "\n")
            except Exception:  # noqa: BLE001 — metrics are a side channel
                pass
    return rc


def watch(cmd, max_restarts: int = 0, _sleep: float = 1.0,
          hang_timeout: Optional[float] = None,
          startup_grace: Optional[float] = None,
          backoff_cap: float = 60.0,
          storm_window: Optional[float] = None, storm_restarts: int = 5,
          peer_monitor=None, heartbeat_path: Optional[str] = None,
          gang_label: str = "watch") -> int:
    """Run ``cmd`` as a watched subprocess; restart on non-zero exit up to
    ``max_restarts`` times (reference: launch_utils.py watch_local_trainers /
    terminate_local_procs).  Returns the final exit code.  SIGTERM/SIGINT
    to the watchdog tears the child down (pod preemption path).  A child
    exiting ``resilience.PREEMPTION_EXIT_CODE`` (75 — it saved a final
    checkpoint under SIGTERM) is restarted WITHOUT consuming the restart
    budget: evictions are the platform's fault, not the trainer's.

    ``hang_timeout`` arms liveness monitoring (reference:
    heart_beat_monitor.h:51): the child gets a heartbeat file via
    ``PADDLE_TPU_HEARTBEAT_FILE`` (the training loop touches it each
    step); when its mtime goes stale past the timeout the child is KILLED
    and the restart budget applies — catching hung ranks (wedged
    collective, deadlocked input pipeline) that exit-code watching never
    sees.  ``hang_timeout`` must exceed the longest legitimately silent
    phase of the trainer (beats come from train/eval/predict batches, not
    from inside user callbacks).  It arms only after the trainer's FIRST
    beat (the
    reference monitor skips UNINITED workers); until then a separate
    ``startup_grace`` applies (default ``max(60, 4x hang_timeout)``) so
    slow interpreter/plugin startup isn't mistaken for a hang.

    Restart pacing: the delay before each failure restart doubles from
    ``_sleep`` up to ``backoff_cap`` (a crash-looping trainer must not
    hot-spin the host); preemption restarts keep the base delay (evictions
    are the platform's fault).  ``storm_window``/``storm_restarts`` arm
    the storm detector: ``storm_restarts`` restarts of ANY kind inside
    ``storm_window`` seconds → give up with
    :data:`RESTART_STORM_EXIT_CODE` even if the budget has room.

    ``peer_monitor`` (a started ``heartbeat.HeartBeatMonitor`` /
    ``PeerHeartbeatMonitor`` fed by the gang's beat transport) arms the
    gang-restore decision: when a peer goes lost (``lost_workers()``
    non-empty) this watchdog kills its OWN healthy child and restarts it
    — a rank whose peer died is wedged in a collective it can never
    finish, and only a gang restart re-forms the group.  Gang restarts
    don't consume the failure budget (a peer's death is not this
    trainer's fault); after each one the monitor is re-armed
    (``rearm()``) so the whole gang's relaunch window isn't instantly
    re-flagged as another loss (which would hot-loop into the storm
    breaker).  Each gang restart publishes a ``("gang", gang_label)``
    trace snapshot (``gang_restores``, ``post_restore_lost``, the lost
    ranks) — the input to analysis rule F803.

    ``heartbeat_path`` pins the child's beat file to a fixed location
    (the shared gang directory) instead of a private tempdir.  The file
    is then shared state: it is NOT reset between attempts, and the
    watchdog never stamps it — only the trainer's own beats may make
    this rank look alive to its peers."""
    import collections
    import os as _os
    import signal
    import subprocess
    import tempfile
    import time

    from ..framework import monitor as _monitor
    from ..framework.logging import vlog
    from .heartbeat import BEAT_MIN_INTERVAL, ENV_FILE, FileHeartbeat

    if hang_timeout is not None and hang_timeout < 2 * BEAT_MIN_INTERVAL:
        raise InvalidArgumentError(
            f"hang_timeout must be >= {2 * BEAT_MIN_INTERVAL:g}s — the "
            "training loop throttles beats to one per "
            f"{BEAT_MIN_INTERVAL:g}s, so shorter timeouts kill healthy "
            "trainers")
    if storm_window is not None and (storm_window <= 0 or storm_restarts < 1):
        raise InvalidArgumentError(
            "storm_window must be > 0 and storm_restarts >= 1")
    attempts = 0
    failure_restarts = 0  # drives the exponential backoff
    restart_times = collections.deque(maxlen=max(storm_restarts, 1))
    child = None
    hb_dir = None
    gang_restores_n = 0
    post_restore_lost_n = 0
    prev_gang_lost: set = set()

    def _storm_tripped() -> bool:
        """Record one restart; True when the storm window just filled."""
        now = time.monotonic()
        restart_times.append(now)
        if storm_window is None or len(restart_times) < storm_restarts:
            return False
        return now - restart_times[0] <= storm_window

    def _peers_lost():
        return peer_monitor.lost_workers() if peer_monitor is not None else ()

    def _publish_gang(lost, reformed: bool) -> None:
        from ..framework import trace_events

        if not trace_events.active():
            return
        trace_events.notify(("gang", gang_label), {
            "gang_restores": gang_restores_n,
            "post_restore_lost": post_restore_lost_n,
            "lost": tuple(lost), "reformed": int(reformed),
        })

    def _note_gang_restart(lost):
        # a peer that is STILL lost after a completed gang restore never
        # came back — that is a stuck-gang signal (F803), not churn
        nonlocal gang_restores_n, post_restore_lost_n, prev_gang_lost
        gang_restores_n += 1
        again = prev_gang_lost & set(lost)
        if again:
            post_restore_lost_n += len(again)
        prev_gang_lost = set(lost)
        _monitor.stat_add("gang_restores")
        _publish_gang(lost, reformed=False)

    def _teardown(signum, frame):
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                child.kill()
        raise SystemExit(128 + signum)

    old_term = signal.signal(signal.SIGTERM, _teardown)
    old_int = signal.signal(signal.SIGINT, _teardown)
    try:
        while True:
            vlog(1, "watchdog: starting %s (attempt %d)", cmd, attempts + 1)
            hb = None
            env = None
            if heartbeat_path is not None:
                # gang beat file: shared state read by every peer's
                # watchdog.  Never reset between attempts, and adopted
                # without stamping (touch only on first creation) — a
                # watchdog stamp would advertise a trainer that is still
                # relaunching as alive
                hb = FileHeartbeat(heartbeat_path,
                                   touch=not _os.path.exists(heartbeat_path))
                env = dict(_os.environ, **{ENV_FILE: heartbeat_path})
            elif hang_timeout is not None:
                if hb_dir is None:
                    hb_dir = tempfile.mkdtemp(prefix="pt_hb_")
                hb_path = _os.path.join(hb_dir, "beat")
                try:  # fresh stamp per attempt, one dir per launch
                    _os.unlink(hb_path)
                except OSError:
                    pass
                hb = FileHeartbeat(hb_path)  # creates + stamps t0
                env = dict(_os.environ, **{ENV_FILE: hb_path})
            if hb is not None and hang_timeout is None:
                hb = None  # beat file for peers only; no local hang watch
            child = subprocess.Popen(cmd, env=env)
            gang_restart = False
            if hb is None and peer_monitor is None:
                rc = child.wait()
            elif hb is None:
                # no hang monitoring, but gang liveness still needs polling
                while True:
                    rc = child.poll()
                    if rc is not None:
                        break
                    lost = _peers_lost()
                    if lost:
                        vlog(0, "watchdog: peer worker(s) %s lost — gang "
                                "restart of the local trainer", lost)
                        _note_gang_restart(lost)
                        gang_restart = True
                        child.kill()
                        rc = child.wait()
                        break
                    time.sleep(0.05)
            else:
                grace = (startup_grace if startup_grace is not None
                         else max(60.0, 4 * hang_timeout))
                st0 = _os.stat(hb.path)
                poll = min(max(hang_timeout / 4, 0.05), 1.0)
                beaten = False  # sticky: once any change is seen, switch
                #                 from startup grace to the hang timeout
                while True:
                    rc = child.poll()
                    if rc is not None:
                        break
                    lost = _peers_lost()
                    if lost:
                        vlog(0, "watchdog: peer worker(s) %s lost — gang "
                                "restart of the local trainer", lost)
                        _note_gang_restart(lost)
                        gang_restart = True
                        child.kill()
                        rc = child.wait()
                        break
                    if not beaten:
                        try:
                            st = _os.stat(hb.path)
                            # mtime OR size change: beat() appends a byte,
                            # so coarse-mtime filesystems still register a
                            # first beat in the same timestamp quantum
                            beaten = (st.st_mtime > st0.st_mtime
                                      or st.st_size != st0.st_size)
                        except OSError:
                            pass
                    limit = hang_timeout if beaten else grace
                    if hb.age() > limit:
                        vlog(0, "watchdog: trainer hung (no heartbeat for "
                                "%.1fs) — killing", hb.age())
                        _monitor.stat_add("hung_trainers")
                        child.kill()
                        rc = child.wait()
                        # rc == 0 here means the child finished cleanly in
                        # the race window before the kill landed — that is
                        # a success, not a hang
                        break
                    time.sleep(poll)
            if rc == 0 and not gang_restart:
                return 0
            if _storm_tripped():
                # N restarts inside W seconds: the trainer is crash-looping
                # (or the gang keeps dying) — more restarts would hot-spin
                # the host, so give up with the distinct storm code
                vlog(0, "watchdog: %d restarts inside %.1fs — restart "
                        "storm, giving up (exit %d)", storm_restarts,
                     storm_window, RESTART_STORM_EXIT_CODE)
                _monitor.stat_add("restart_storms")
                return RESTART_STORM_EXIT_CODE
            if gang_restart:
                # a peer died: this child was healthy, the restart exists
                # only to re-form the gang — no budget, base delay.  Re-arm
                # the monitor so every peer gets a fresh grace window to
                # relaunch and rejoin; without it the gang's own restart
                # latency reads as another loss and hot-loops into the
                # storm breaker.
                if peer_monitor is not None and hasattr(peer_monitor,
                                                        "rearm"):
                    peer_monitor.rearm()
                time.sleep(_sleep)
                continue
            if rc == GANG_RESTART_EXIT_CODE:
                # the trainer ITSELF detected an abandoned gang generation
                # (a peer reincarnated mid-collective — too fast for the
                # peer heartbeat to ever look stale) and asked for a gang
                # restart.  Same contract as the peer-loss path: no
                # budget, counters, fresh monitor grace.
                vlog(0, "watchdog: trainer requested a gang restart "
                        "(rc=%d: gang generation abandoned) — rejoining",
                     rc)
                _note_gang_restart(())
                if peer_monitor is not None and hasattr(peer_monitor,
                                                        "rearm"):
                    peer_monitor.rearm()
                time.sleep(_sleep)
                continue
            from ..resilience.preemption import PREEMPTION_EXIT_CODE

            if rc == PREEMPTION_EXIT_CODE:
                # clean preemption: the trainer saved a final checkpoint
                # and exited 75 (resilience.preemption) — an eviction is
                # the platform's fault, so restart WITHOUT consuming the
                # failure budget
                vlog(1, "watchdog: trainer preempted cleanly (rc=%d) — "
                        "restarting without consuming the restart budget",
                     rc)
                _monitor.stat_add("preemption_restarts")
                time.sleep(_sleep)
                continue
            vlog(1, "watchdog: trainer exited rc=%d", rc)
            if attempts >= max_restarts:
                vlog(1, "watchdog: restart budget exhausted (%d)", attempts)
                return rc
            attempts += 1
            _monitor.stat_add("trainer_restarts")  # an actual restart
            # exponential backoff: 1x, 2x, 4x ... capped — a trainer that
            # dies instantly must not restart at full poll speed
            time.sleep(min(_sleep * (2 ** failure_restarts),
                           max(backoff_cap, _sleep)))
            failure_restarts += 1
    finally:
        if hb_dir is not None:
            import shutil

            shutil.rmtree(hb_dir, ignore_errors=True)
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)

"""DataParallel wrapper + spawn/launch helpers.

Parity: paddle.DataParallel (fluid/dygraph/parallel.py:335 — grad coalescing
+ allreduce hooks) and paddle.distributed.spawn/launch.

Under SPMD none of the reference's machinery (coalesced grad buffers
:229-284, imperative allreduce, nccl bootstrap) exists as user-visible
moving parts: wrapping a Layer just replicates its parameters over the mesh
and records that batches should be split over the data axes.  The hapi
Model / fleet path does this automatically; DataParallel exists for users
who write their own step functions.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

import jax

from ..framework.errors import InvalidArgumentError
from ..nn.layer_base import Layer
from . import env as _env
from .mesh import get_mesh

__all__ = ["DataParallel", "spawn", "launch", "RESTART_STORM_EXIT_CODE"]

#: watch() exit code when the restart-storm window trips: the trainer
#: crash-looped (storm_restarts restarts inside storm_window seconds), so
#: restarting again would hot-spin the host.  Distinct from the child's own
#: codes so schedulers can tell "gave up on a crash loop" from "trainer
#: failed once and exhausted the budget".
RESTART_STORM_EXIT_CODE = 77


class DataParallel(Layer):
    """Replicate a Layer across the mesh; forward = inner forward.

    ``scale_loss``/``apply_collective_grads`` are kept as no-ops for source
    compatibility with reference training loops (gradient averaging falls
    out of psum/mean in the SPMD step).
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1, find_unused_parameters: bool = False):
        super().__init__()
        self._layers = layers
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = get_mesh()
        repl = NamedSharding(mesh, P())
        for _, p in layers.named_parameters():
            p.value = jax.device_put(p.value, repl)
        for _, b in layers.named_buffers():
            b.value = jax.device_put(b.value, repl)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


def spawn(func, args=(), nprocs: Optional[int] = None, join: bool = True, **kwargs):
    """Parity: paddle.distributed.spawn.  On TPU the unit of spawning is a
    *host process driving all local chips* — inside one host there is nothing
    to spawn (SPMD covers the local devices), so this runs ``func`` once.
    Multi-host pods launch one process per host externally (see launch)."""
    if nprocs not in (None, 1) and jax.process_count() == 1:
        raise InvalidArgumentError(
            "spawn(nprocs>1) maps to multi-host launch on TPU: one process "
            "drives all local chips (SPMD), so per-device process spawning "
            "does not exist.  Use paddle_tpu.distributed.launch across hosts."
        )
    _env.init_parallel_env()
    func(*args)


def launch(argv=None):
    """`python -m paddle_tpu.distributed.launch [--max-restarts=N] script.py`
    (reference: fleet/launch.py:183).  One process per host — the pod
    runtime starts this command on every host.

    Default: exec the training script in-process.  With ``--max-restarts``
    the script runs as a watched subprocess instead (the reference's
    launch_utils.py TrainerProc watch loop): a non-zero exit restarts it up
    to N times — pair with incubate.checkpoint auto-resume and a preempted/
    crashed trainer continues from its last snapshot (the elastic-lite
    story; the reference's `strategy.elastic` proto field was never
    implemented)."""
    import runpy

    argv = list(sys.argv[1:] if argv is None else argv)
    usage = ("usage: python -m paddle_tpu.distributed.launch "
             "[--max-restarts=N] [--hang-timeout=SECONDS] "
             "script.py [args...]")
    max_restarts = 0
    watched = False
    hang_timeout = None
    while argv and argv[0].startswith("--"):
        flag = argv.pop(0)
        if flag == "--max-restarts" or flag.startswith("--max-restarts="):
            watched = True
            try:
                value = (flag.split("=", 1)[1] if "=" in flag
                         else argv.pop(0))
                max_restarts = int(value)
            except (IndexError, ValueError):
                print(f"--max-restarts needs an integer value\n{usage}")
                return 2
        elif flag == "--hang-timeout" or flag.startswith("--hang-timeout="):
            watched = True
            try:
                value = (flag.split("=", 1)[1] if "=" in flag
                         else argv.pop(0))
                hang_timeout = float(value)
                if hang_timeout <= 0:
                    raise ValueError
            except (IndexError, ValueError):
                print(f"--hang-timeout needs a positive number of "
                      f"seconds\n{usage}")
                return 2
        else:
            print(f"unknown launch flag {flag}\n{usage}")
            return 2
    if not argv:
        print(usage)
        return 1
    script, *rest = argv
    if watched:
        # child re-enters launch in-process mode so init_parallel_env runs
        # inside each (re)started trainer, exactly like the unwatched path
        return watch([sys.executable, "-m", "paddle_tpu.distributed.launch",
                      script] + rest, max_restarts=max_restarts,
                     hang_timeout=hang_timeout)
    sys.argv = [script] + rest
    _env.init_parallel_env()
    runpy.run_path(script, run_name="__main__")
    return 0


def watch(cmd, max_restarts: int = 0, _sleep: float = 1.0,
          hang_timeout: Optional[float] = None,
          startup_grace: Optional[float] = None,
          backoff_cap: float = 60.0,
          storm_window: Optional[float] = None, storm_restarts: int = 5,
          peer_monitor=None) -> int:
    """Run ``cmd`` as a watched subprocess; restart on non-zero exit up to
    ``max_restarts`` times (reference: launch_utils.py watch_local_trainers /
    terminate_local_procs).  Returns the final exit code.  SIGTERM/SIGINT
    to the watchdog tears the child down (pod preemption path).  A child
    exiting ``resilience.PREEMPTION_EXIT_CODE`` (75 — it saved a final
    checkpoint under SIGTERM) is restarted WITHOUT consuming the restart
    budget: evictions are the platform's fault, not the trainer's.

    ``hang_timeout`` arms liveness monitoring (reference:
    heart_beat_monitor.h:51): the child gets a heartbeat file via
    ``PADDLE_TPU_HEARTBEAT_FILE`` (the training loop touches it each
    step); when its mtime goes stale past the timeout the child is KILLED
    and the restart budget applies — catching hung ranks (wedged
    collective, deadlocked input pipeline) that exit-code watching never
    sees.  ``hang_timeout`` must exceed the longest legitimately silent
    phase of the trainer (beats come from train/eval/predict batches, not
    from inside user callbacks).  It arms only after the trainer's FIRST
    beat (the
    reference monitor skips UNINITED workers); until then a separate
    ``startup_grace`` applies (default ``max(60, 4x hang_timeout)``) so
    slow interpreter/plugin startup isn't mistaken for a hang.

    Restart pacing: the delay before each failure restart doubles from
    ``_sleep`` up to ``backoff_cap`` (a crash-looping trainer must not
    hot-spin the host); preemption restarts keep the base delay (evictions
    are the platform's fault).  ``storm_window``/``storm_restarts`` arm
    the storm detector: ``storm_restarts`` restarts of ANY kind inside
    ``storm_window`` seconds → give up with
    :data:`RESTART_STORM_EXIT_CODE` even if the budget has room.

    ``peer_monitor`` (a started ``heartbeat.HeartBeatMonitor`` fed by the
    gang's beat transport) arms the gang-restore decision: when a peer
    goes lost (``lost_workers()`` non-empty) this watchdog kills its OWN
    healthy child and restarts it — a rank whose peer died is wedged in a
    collective it can never finish, and only a gang restart re-forms the
    group.  Gang restarts don't consume the failure budget (a peer's
    death is not this trainer's fault)."""
    import collections
    import os as _os
    import signal
    import subprocess
    import tempfile
    import time

    from ..framework import monitor as _monitor
    from ..framework.logging import vlog
    from .heartbeat import BEAT_MIN_INTERVAL, ENV_FILE, FileHeartbeat

    if hang_timeout is not None and hang_timeout < 2 * BEAT_MIN_INTERVAL:
        raise InvalidArgumentError(
            f"hang_timeout must be >= {2 * BEAT_MIN_INTERVAL:g}s — the "
            "training loop throttles beats to one per "
            f"{BEAT_MIN_INTERVAL:g}s, so shorter timeouts kill healthy "
            "trainers")
    if storm_window is not None and (storm_window <= 0 or storm_restarts < 1):
        raise InvalidArgumentError(
            "storm_window must be > 0 and storm_restarts >= 1")
    attempts = 0
    failure_restarts = 0  # drives the exponential backoff
    restart_times = collections.deque(maxlen=max(storm_restarts, 1))
    child = None
    hb_dir = None

    def _storm_tripped() -> bool:
        """Record one restart; True when the storm window just filled."""
        now = time.monotonic()
        restart_times.append(now)
        if storm_window is None or len(restart_times) < storm_restarts:
            return False
        return now - restart_times[0] <= storm_window

    def _peers_lost():
        return peer_monitor.lost_workers() if peer_monitor is not None else ()

    def _teardown(signum, frame):
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                child.kill()
        raise SystemExit(128 + signum)

    old_term = signal.signal(signal.SIGTERM, _teardown)
    old_int = signal.signal(signal.SIGINT, _teardown)
    try:
        while True:
            vlog(1, "watchdog: starting %s (attempt %d)", cmd, attempts + 1)
            hb = None
            env = None
            if hang_timeout is not None:
                if hb_dir is None:
                    hb_dir = tempfile.mkdtemp(prefix="pt_hb_")
                hb_path = _os.path.join(hb_dir, "beat")
                try:  # fresh stamp per attempt, one dir per launch
                    _os.unlink(hb_path)
                except OSError:
                    pass
                hb = FileHeartbeat(hb_path)  # creates + stamps t0
                env = dict(_os.environ, **{ENV_FILE: hb_path})
            child = subprocess.Popen(cmd, env=env)
            gang_restart = False
            if hb is None and peer_monitor is None:
                rc = child.wait()
            elif hb is None:
                # no hang monitoring, but gang liveness still needs polling
                while True:
                    rc = child.poll()
                    if rc is not None:
                        break
                    lost = _peers_lost()
                    if lost:
                        vlog(0, "watchdog: peer worker(s) %s lost — gang "
                                "restart of the local trainer", lost)
                        _monitor.stat_add("gang_restores")
                        gang_restart = True
                        child.kill()
                        rc = child.wait()
                        break
                    time.sleep(0.05)
            else:
                grace = (startup_grace if startup_grace is not None
                         else max(60.0, 4 * hang_timeout))
                st0 = _os.stat(hb.path)
                poll = min(max(hang_timeout / 4, 0.05), 1.0)
                beaten = False  # sticky: once any change is seen, switch
                #                 from startup grace to the hang timeout
                while True:
                    rc = child.poll()
                    if rc is not None:
                        break
                    lost = _peers_lost()
                    if lost:
                        vlog(0, "watchdog: peer worker(s) %s lost — gang "
                                "restart of the local trainer", lost)
                        _monitor.stat_add("gang_restores")
                        gang_restart = True
                        child.kill()
                        rc = child.wait()
                        break
                    if not beaten:
                        try:
                            st = _os.stat(hb.path)
                            # mtime OR size change: beat() appends a byte,
                            # so coarse-mtime filesystems still register a
                            # first beat in the same timestamp quantum
                            beaten = (st.st_mtime > st0.st_mtime
                                      or st.st_size != st0.st_size)
                        except OSError:
                            pass
                    limit = hang_timeout if beaten else grace
                    if hb.age() > limit:
                        vlog(0, "watchdog: trainer hung (no heartbeat for "
                                "%.1fs) — killing", hb.age())
                        _monitor.stat_add("hung_trainers")
                        child.kill()
                        rc = child.wait()
                        # rc == 0 here means the child finished cleanly in
                        # the race window before the kill landed — that is
                        # a success, not a hang
                        break
                    time.sleep(poll)
            if rc == 0 and not gang_restart:
                return 0
            if _storm_tripped():
                # N restarts inside W seconds: the trainer is crash-looping
                # (or the gang keeps dying) — more restarts would hot-spin
                # the host, so give up with the distinct storm code
                vlog(0, "watchdog: %d restarts inside %.1fs — restart "
                        "storm, giving up (exit %d)", storm_restarts,
                     storm_window, RESTART_STORM_EXIT_CODE)
                _monitor.stat_add("restart_storms")
                return RESTART_STORM_EXIT_CODE
            if gang_restart:
                # a peer died: this child was healthy, the restart exists
                # only to re-form the gang — no budget, base delay
                time.sleep(_sleep)
                continue
            from ..resilience.preemption import PREEMPTION_EXIT_CODE

            if rc == PREEMPTION_EXIT_CODE:
                # clean preemption: the trainer saved a final checkpoint
                # and exited 75 (resilience.preemption) — an eviction is
                # the platform's fault, so restart WITHOUT consuming the
                # failure budget
                vlog(1, "watchdog: trainer preempted cleanly (rc=%d) — "
                        "restarting without consuming the restart budget",
                     rc)
                _monitor.stat_add("preemption_restarts")
                time.sleep(_sleep)
                continue
            vlog(1, "watchdog: trainer exited rc=%d", rc)
            if attempts >= max_restarts:
                vlog(1, "watchdog: restart budget exhausted (%d)", attempts)
                return rc
            attempts += 1
            _monitor.stat_add("trainer_restarts")  # an actual restart
            # exponential backoff: 1x, 2x, 4x ... capped — a trainer that
            # dies instantly must not restart at full poll speed
            time.sleep(min(_sleep * (2 ** failure_restarts),
                           max(backoff_cap, _sleep)))
            failure_restarts += 1
    finally:
        if hb_dir is not None:
            import shutil

            shutil.rmtree(hb_dir, ignore_errors=True)
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)

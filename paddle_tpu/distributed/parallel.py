"""DataParallel wrapper + spawn/launch helpers.

Parity: paddle.DataParallel (fluid/dygraph/parallel.py:335 — grad coalescing
+ allreduce hooks) and paddle.distributed.spawn/launch.

Under SPMD none of the reference's machinery (coalesced grad buffers
:229-284, imperative allreduce, nccl bootstrap) exists as user-visible
moving parts: wrapping a Layer just replicates its parameters over the mesh
and records that batches should be split over the data axes.  The hapi
Model / fleet path does this automatically; DataParallel exists for users
who write their own step functions.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

import jax

from ..framework.errors import InvalidArgumentError
from ..nn.layer_base import Layer
from . import env as _env
from .mesh import get_mesh

__all__ = ["DataParallel", "spawn", "launch"]


class DataParallel(Layer):
    """Replicate a Layer across the mesh; forward = inner forward.

    ``scale_loss``/``apply_collective_grads`` are kept as no-ops for source
    compatibility with reference training loops (gradient averaging falls
    out of psum/mean in the SPMD step).
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size_MB: int = 25,
                 last_comm_buffer_size_MB: int = 1, find_unused_parameters: bool = False):
        super().__init__()
        self._layers = layers
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = get_mesh()
        repl = NamedSharding(mesh, P())
        for _, p in layers.named_parameters():
            p.value = jax.device_put(p.value, repl)
        for _, b in layers.named_buffers():
            b.value = jax.device_put(b.value, repl)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


def spawn(func, args=(), nprocs: Optional[int] = None, join: bool = True, **kwargs):
    """Parity: paddle.distributed.spawn.  On TPU the unit of spawning is a
    *host process driving all local chips* — inside one host there is nothing
    to spawn (SPMD covers the local devices), so this runs ``func`` once.
    Multi-host pods launch one process per host externally (see launch)."""
    if nprocs not in (None, 1) and jax.process_count() == 1:
        raise InvalidArgumentError(
            "spawn(nprocs>1) maps to multi-host launch on TPU: one process "
            "drives all local chips (SPMD), so per-device process spawning "
            "does not exist.  Use paddle_tpu.distributed.launch across hosts."
        )
    _env.init_parallel_env()
    func(*args)


def launch(argv=None):
    """Minimal `python -m paddle_tpu.distributed.launch script.py` analogue
    (reference: fleet/launch.py:183).  Sets the env vars init_parallel_env
    reads and execs the training script in-process (one process per host —
    the pod runtime starts this command on every host)."""
    import runpy

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m paddle_tpu.distributed.launch script.py [args...]")
        return 1
    script, *rest = argv
    sys.argv = [script] + rest
    _env.init_parallel_env()
    runpy.run_path(script, run_name="__main__")
    return 0

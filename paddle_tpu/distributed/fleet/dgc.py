"""DGC execution plan — runs the train step under shard_map so gradients
stay per-device for the DGCMomentum optimizer's sparse exchange.

Parity: the reference's DGC meta-optimizer path (fluid/optimizer.py:1129
DGCMomentumOptimizer + operators/dgc_op.cc), which rewrites the Program to
encode top-k gradients before NCCL.  Here the structure is the LocalSGD
pattern (fleet/localsgd.py): parameters stay replicated (the post-exchange
update is identical on every device), while the u/v accumulators — which
hold each replica's unsent gradient mass — ride stacked [ndp, ...] in the
optimizer state, sharded over ``data``.  The sparsity ramp-up resolves on
the host: each phase (dense warmup, then each sparsity level) is its own
compiled step, since top-k needs a static k.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...framework.errors import InvalidArgumentError
from ..collective import shard_map
from .plan import ShardingPlan

__all__ = ["DGCPlan"]


class DGCPlan(ShardingPlan):
    def __init__(self, network, optimizer, strategy, mesh=None):
        super().__init__(network, optimizer, strategy, mesh)
        self._require_pure_dp("dgc")
        from ...optimizer.dgc import DGCMomentum

        if not isinstance(optimizer, DGCMomentum):
            raise InvalidArgumentError(
                "strategy.dgc requires a Momentum optimizer (reference "
                "_can_apply); fleet.distributed_optimizer converts one")
        self.axis = "data"
        self.ndp = self.mesh.shape["data"]

    # -- state ---------------------------------------------------------------
    def init_opt_state(self, optimizer, params, buffers=None):
        ndp = self.ndp

        def init_fn(params):
            st = optimizer.init(params)
            stack = lambda t: jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (ndp,) + x.shape), t)
            return {"count": st["count"], "velocity": st["velocity"],
                    "u": stack(st["u"]), "v": stack(st["v"])}

        shapes = jax.eval_shape(init_fn, params)
        local = self.named(P(self.axis))
        shardings = {
            "count": self.named(P()),
            "velocity": jax.tree.map(lambda _: self.named(P()),
                                     shapes["velocity"]),
            "u": jax.tree.map(lambda _: local, shapes["u"]),
            "v": jax.tree.map(lambda _: local, shapes["v"]),
        }
        return jax.jit(init_fn, out_shardings=shardings)(params)

    # -- step ----------------------------------------------------------------
    def jit_train_step(self, train_step):
        plan = self
        opt = self.optimizer
        mesh, axis = self.mesh, self.axis
        spec_l = P(axis)

        def make(n_batch):
            def step(params, opt_state, buffers, key, lr, *batch):
                def body(params, buffers, vel, count, l_u, l_v,
                         key, lr, *batch):
                    sq = lambda t: jax.tree.map(lambda x: x[0], t)
                    st = lambda t: jax.tree.map(lambda x: x[None], t)
                    state_in = {"count": count, "velocity": vel,
                                "u": sq(l_u), "v": sq(l_v)}
                    key = jax.random.fold_in(key, lax.axis_index(axis))
                    loss, out, new_p, ns, new_b = train_step(
                        params, state_in, buffers, key, lr, *batch)
                    loss = lax.pmean(loss, axis)
                    # buffers (BN stats) are computed on the local shard —
                    # average to keep the GSPMD global-batch semantics
                    new_b = jax.tree.map(lambda x: lax.pmean(x, axis), new_b)
                    return (loss, out, new_p, ns["velocity"], ns["count"],
                            st(ns["u"]), st(ns["v"]), new_b)

                local = opt_state
                in_specs = (P(), P(), P(), P(), spec_l, spec_l, P(), P()) \
                    + (spec_l,) * n_batch
                out_specs = (P(), spec_l, P(), P(), P(), spec_l, spec_l, P())
                loss, out, g_params, vel, count, nu, nv, g_bufs = shard_map(
                    body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                )(params, buffers, local["velocity"], local["count"],
                  local["u"], local["v"], key, lr, *batch)
                new_state = {"count": count, "velocity": vel,
                             "u": nu, "v": nv}
                return loss, out, g_params, new_state, g_bufs

            return step

        compiled = {}

        def wrapped(params, opt_state, buffers, key, lr, *batch):
            t = (plan._t if plan._t is not None
                 else int(opt_state["count"])) + 1
            phase = opt.sparsity_at(t)
            kk = (phase, len(batch))
            # _sparsity_now is read at TRACE time only; keep it current so
            # a fresh cache entry compiles the right phase
            opt._sparsity_now = phase
            if kk not in compiled:
                compiled[kk] = jax.jit(make(len(batch)),
                                       donate_argnums=(0, 1, 2))
            out = compiled[kk](params, opt_state, buffers, key, lr, *batch)
            plan._t = t
            return out

        return wrapped

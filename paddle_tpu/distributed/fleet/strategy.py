"""DistributedStrategy — declarative parallelism config.

Parity: python/paddle/distributed/fleet/base/distributed_strategy.py over
framework/distributed_strategy.proto:110 (fields amp:113, recompute:114,
gradient_merge:117, pipeline:120, sharding, …).  The reference's strategy
toggles *meta-optimizer program rewrites*; here each knob selects mesh axis
degrees and sharding rules consumed by the ShardingPlan (no program
rewriting exists — XLA partitions one jitted step).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

__all__ = ["DistributedStrategy"]


@dataclass
class DistributedStrategy:
    # hybrid mesh degrees (paddle 2.x fleet "hybrid_configs" analogue)
    dp_degree: int = 0          # 0 = all remaining devices
    mp_degree: int = 1          # tensor (model) parallel
    pp_degree: int = 1          # pipeline stages
    sep_degree: int = 1         # sequence/context parallel
    sharding_degree: int = 1    # ZeRO optimizer-state sharding
    ep_degree: int = 1          # expert parallel (MoE; paddle_tpu/moe)

    # feature toggles (proto parity)
    amp: bool = False
    amp_configs: Dict = field(default_factory=dict)
    recompute: bool = False
    recompute_configs: Dict = field(default_factory=dict)
    gradient_merge: bool = False
    gradient_merge_configs: Dict = field(default_factory=lambda: {"k_steps": 1})
    sharding: bool = False      # convenience: sets sharding_degree if unset
    sharding_configs: Dict = field(default_factory=dict)
    tensor_parallel: bool = False
    tensor_parallel_configs: Dict = field(default_factory=dict)
    pipeline: bool = False
    pipeline_configs: Dict = field(default_factory=lambda: {"accumulate_steps": 1})
    sequence_parallel: bool = False
    sequence_parallel_configs: Dict = field(
        default_factory=lambda: {"method": "ring"})
    expert_parallel: bool = False
    expert_parallel_configs: Dict = field(default_factory=dict)
    localsgd: bool = False
    localsgd_configs: Dict = field(default_factory=dict)
    adaptive_localsgd: bool = False  # step-adaptive sync period (ref:
    # localsgd_optimizer.py:194 AdaptiveLocalSGDOptimizer)
    adaptive_localsgd_configs: Dict = field(
        default_factory=lambda: {"init_k_steps": 1, "begin_step": 1})
    fp16_allreduce: bool = False  # comm-precision: cast grads for the
    # cross-replica reduction (ref: fp16_allreduce_optimizer.py:18)
    fp16_allreduce_configs: Dict = field(
        default_factory=lambda: {"dtype": "float16"})
    # collective schedule dials the measured-search plan tuner owns
    # (paddle.fleet analogue: fuse_grad_size_in_MB / comm overlap in
    # graph_execution_optimizer).  0 = one reduction per gradient leaf
    # (the historical behavior); >0 asks plans to fuse reductions into
    # ~N MB buckets.  overlap_grad_sync keeps XLA free to run the grad
    # collectives concurrently with independent compute (latency hiding).
    allreduce_bucket_mb: int = 0
    overlap_grad_sync: bool = True
    dgc: bool = False
    dgc_configs: Dict = field(default_factory=dict)
    lamb: bool = False
    lamb_configs: Dict = field(default_factory=dict)
    lars: bool = False
    lars_configs: Dict = field(default_factory=dict)
    a_sync: bool = False        # PS async mode; with a_sync_configs
    # {"k_steps": N>0} this is Geo-SGD (ref: geo_sgd_transpiler.py:1,
    # communicator.h:413 GeoCommunicator) — local steps + periodic
    # parameter-DELTA push, served here by GeoSgdPlan.  Pure async
    # (k_steps=0) has no TPU counterpart and raises with the migration
    # paths (GeoSGD / LocalSGD / incubate.HostEmbeddingTable).
    a_sync_configs: Dict = field(default_factory=dict)
    hybrid_configs: Optional[Dict] = None

    def __post_init__(self):
        if self.hybrid_configs:
            self.dp_degree = self.hybrid_configs.get("dp_degree", self.dp_degree)
            self.mp_degree = self.hybrid_configs.get("mp_degree", self.mp_degree)
            self.pp_degree = self.hybrid_configs.get("pp_degree", self.pp_degree)
            self.sep_degree = self.hybrid_configs.get("sep_degree", self.sep_degree)
            self.sharding_degree = self.hybrid_configs.get(
                "sharding_degree", self.sharding_degree)
            self.ep_degree = self.hybrid_configs.get(
                "ep_degree", self.ep_degree)
        if self.expert_parallel and self.ep_degree == 1:
            self.ep_degree = int(self.expert_parallel_configs.get(
                "expert_parallel_degree", 1))
        if self.tensor_parallel and self.mp_degree == 1:
            self.mp_degree = int(self.tensor_parallel_configs.get(
                "tensor_parallel_degree", 1))
        if self.sharding and self.sharding_degree == 1:
            self.sharding_degree = int(self.sharding_configs.get(
                "sharding_degree", 0)) or 0  # 0 → span the data dimension
        if self.pipeline and self.pp_degree == 1:
            self.pp_degree = int(self.pipeline_configs.get("pp_degree", 1))
        sched = str((self.pipeline_configs or {}).get(
            "schedule", "gpipe")).lower()
        # F-then-B is the reference's name for the fwd-all-then-bwd-all
        # schedule — the GPipe execution this package already provides
        if sched not in ("gpipe", "f-then-b", "1f1b"):
            raise ValueError(
                "pipeline_configs['schedule'] must be 'gpipe'/'F-then-B'/"
                f"'1F1B' (case-insensitive), got {sched!r}")

    def apply_tuned(self, config: Dict) -> "DistributedStrategy":
        """Apply a measured-search plan winner's collective dials (the
        ``tuning.plan_space`` config keys this class owns) in place and
        return self.  Unknown keys — the per-group axis assignment, which
        ``tuning.apply_plan`` lowers onto parameter annotations — are
        ignored here."""
        if "fp16_allreduce" in config:
            self.fp16_allreduce = bool(config["fp16_allreduce"])
        if "allreduce_bucket_mb" in config:
            self.allreduce_bucket_mb = int(config["allreduce_bucket_mb"])
        if "overlap_grad_sync" in config:
            self.overlap_grad_sync = bool(config["overlap_grad_sync"])
        return self

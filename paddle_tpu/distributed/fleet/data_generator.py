"""fleet.data_generator — user-defined sample → MultiSlot text pipeline.

Parity: python/paddle/distributed/fleet/data_generator/data_generator.py
(DataGenerator:19, MultiSlotStringDataGenerator:232,
MultiSlotDataGenerator:273).  Users subclass and implement
``generate_sample(line)``; ``run_from_stdin`` streams parsed samples to
stdout in the ``<len> id id ...`` MultiSlot format — the preprocessing
half of the CTR ingest pipeline, feeding files that
paddle.io.InMemoryDataset (native/ingest.cc) then loads and shuffles.
"""
from __future__ import annotations

import sys

from ...framework.errors import InvalidArgumentError

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base class: subclass and implement ``generate_sample`` (and
    optionally ``generate_batch``)."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """→ a no-arg iterator yielding [(slot_name, [values]), ...]
        per sample (None entries are skipped)."""
        raise NotImplementedError(
            "implement generate_sample(line) returning a local iterator")

    def generate_batch(self, samples):
        """Optional batch-level hook; default passes samples through."""

        def local_iter():
            for sample in samples:
                yield sample

        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator / MultiSlotStringDataGenerator "
            "(or implement _gen_str for a custom feed format)")

    def _drain(self, batch_samples, out):
        for sample in self.generate_batch(batch_samples)():
            out.write(self._gen_str(sample))

    def run_from_memory(self, out=None):
        """Emit from generate_sample(None) — debugging/benchmarks
        (ref :57)."""
        out = out or sys.stdout
        batch = []
        for sample in self.generate_sample(None)():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                self._drain(batch, out)
                batch = []
        if batch:
            self._drain(batch, out)

    def run_from_stdin(self, source=None, out=None):
        """Line-streamed parse → MultiSlot text on stdout (ref :92).
        ``source``/``out`` are injectable for tests; defaults are the
        reference's stdin/stdout."""
        source = source or sys.stdin
        out = out or sys.stdout
        batch = []
        for line in source:
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._drain(batch, out)
                    batch = []
        if batch:
            self._drain(batch, out)


def _check_slots(line):
    if not isinstance(line, (list, tuple)):
        raise InvalidArgumentError(
            "the output of generate_sample must be a list/tuple of "
            "(slot_name, values) pairs, e.g. "
            "[('words', [1926, 8, 17]), ('label', [1])]")


class MultiSlotStringDataGenerator(DataGenerator):
    """String feasigns, no type checking (ref :232): fastest emit path."""

    def _gen_str(self, line):
        _check_slots(line)
        parts = []
        for name, elements in line:
            parts.append(" ".join([str(len(elements)), *elements]))
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Typed feasigns (ref :273): first sample fixes each slot's type
    (int → uint64 slot, float promotes the slot to float); later samples
    must match the slot order and arity."""

    def _gen_str(self, line):
        _check_slots(line)
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                dtype = "uint64"
                for v in elements:
                    if isinstance(v, float):
                        dtype = "float"
                    elif not isinstance(v, int):
                        raise InvalidArgumentError(
                            f"slot {name!r}: feasigns must be int or "
                            f"float, got {type(v).__name__}")
                self._proto_info.append((name, dtype))
        else:
            if len(line) != len(self._proto_info):
                raise InvalidArgumentError(
                    f"expected {len(self._proto_info)} slots "
                    f"(as in the first sample), got {len(line)}")
            for (name, elements), (pname, ptype) in zip(line,
                                                        self._proto_info):
                if name != pname:
                    raise InvalidArgumentError(
                        f"slot order changed: expected {pname!r}, "
                        f"got {name!r}")
        parts = []
        for name, elements in line:
            parts.append(" ".join([str(len(elements)),
                                   *(str(v) for v in elements)]))
        return " ".join(parts) + "\n"

"""Comm-precision data parallelism: reduced-precision gradient all-reduce.

Reference capability: FP16AllReduceOptimizer
(fleet/meta_optimizers/fp16_allreduce_optimizer.py:18) — it rewrites the
Program to cast each grad to fp16 before its c_allreduce_sum and back
after.  TPU-native: GSPMD's implicit DP all-reduce cannot be re-typed from
the outside, so this plan runs the train step per-replica under shard_map
and performs the gradient reduction EXPLICITLY — cast to fp16/bf16,
``lax.pmean`` over the ``data`` axis (rides ICI at half the bytes), cast
back to f32 for the (replicated, deterministic) optimizer update.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ...framework.errors import InvalidArgumentError
from ..collective import shard_map  # check_vma=False: per-replica grads
from .plan import ShardingPlan

__all__ = ["Fp16AllReducePlan"]

_DTYPES = {"float16": jnp.float16, "bfloat16": jnp.bfloat16}


class Fp16AllReducePlan(ShardingPlan):
    def __init__(self, network, optimizer, strategy, mesh=None):
        super().__init__(network, optimizer, strategy, mesh)
        self._require_pure_dp("fp16_allreduce")
        cfg = strategy.fp16_allreduce_configs or {}
        name = cfg.get("dtype")
        if name is None:
            # Pre-scaling by 1/n before the cast (see transform_gradients)
            # trades psum overflow for underflow: grads below ~6e-8*n flush
            # to zero in fp16.  That narrowing grows with replica count, so
            # past 8 replicas default to bfloat16 — same wire bytes, f32
            # exponent range, no underflow cliff.  An explicit dtype in
            # fp16_allreduce_configs always wins.
            n_replicas = self.mesh.shape.get("data", 1)
            name = "float16" if n_replicas <= 8 else "bfloat16"
        name = str(name)
        if name not in _DTYPES:
            raise InvalidArgumentError(
                f"fp16_allreduce dtype must be float16/bfloat16, got {name!r}")
        self.comm_dtype = _DTYPES[name]
        self.axis = "data"

    def transform_gradients(self, grads):
        """Called by the train step between grad and update — inside this
        plan's shard_map body, so grads are PER-REPLICA here: reduce them
        across replicas in the compressed dtype.  SelectedRows leaves ride
        the sparse allreduce (rows gathered, values on the wire in the
        comm dtype) instead of a dense psum — the reference composes the
        two the same way (details/sparse_all_reduce_op_handle.cc:1)."""
        from ...framework.selected_rows import SelectedRows, all_gather_rows

        cd = self.comm_dtype
        n = self.mesh.shape[self.axis]

        def reduce(g):
            if isinstance(g, SelectedRows):
                return all_gather_rows(g, self.axis, scale=1.0 / n,
                                       wire_dtype=cd)
            # pre-scale by 1/n BEFORE the cast: psum of fp16 values can
            # overflow (n*|g| > 65504) even when the mean is representable
            return lax.psum((g / n).astype(cd), self.axis).astype(g.dtype)

        return jax.tree_util.tree_map(
            reduce, grads, is_leaf=lambda x: isinstance(x, SelectedRows))

    def jit_train_step(self, train_step):
        mesh, axis = self.mesh, self.axis
        spec_l = P(axis)

        def make(n_batch):
            def step(params, opt_state, buffers, key, lr, *batch):
                def body(params, opt_state, buffers, key, lr, *batch):
                    # every replica sees the same key (the update must be
                    # replicated-deterministic); dropout masks therefore
                    # differ per-SAMPLE via batch position, like GSPMD
                    loss, out, new_p, ns, new_b = train_step(
                        params, opt_state, buffers, key, lr, *batch)
                    loss = lax.pmean(loss, axis)
                    new_b = jax.tree_util.tree_map(
                        lambda x: lax.pmean(x, axis), new_b)
                    return loss, out, new_p, ns, new_b

                in_specs = (P(), P(), P(), P(), P()) + (spec_l,) * n_batch
                out_specs = (P(), spec_l, P(), P(), P())
                return shard_map(
                    body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                )(params, opt_state, buffers, key, lr, *batch)

            return step

        compiled = {}

        def wrapped(params, opt_state, buffers, key, lr, *batch):
            k = len(batch)
            if k not in compiled:
                compiled[k] = jax.jit(make(k), donate_argnums=(0, 1, 2))
            return compiled[k](params, opt_state, buffers, key, lr, *batch)

        return wrapped

"""Geo-SGD — local steps with periodic parameter-DELTA synchronization.

Parity: the reference's Geo-SGD mode (transpiler/geo_sgd_transpiler.py:1 +
operators/distributed/communicator.h:413 GeoCommunicator) — the
stale-tolerant parameter-server strategy: workers train locally; every
``k_steps`` each worker SENDS the delta of its parameters since its last
send (divided by the worker count) and RECEIVES the server's aggregate
drift, merging it into its local parameters WITHOUT resetting them.
Replicas therefore keep their individual exploration between syncs — the
property that distinguishes Geo from LocalSGD's full reset-to-average.

TPU-native design: like LocalSGD, per-replica state rides stacked
``[ndp, ...]`` inside the optimizer state under ``shard_map`` with no
implicit gradient all-reduce.  The PS server's aggregate is the plan's
Model-visible (replicated) parameter copy.  At a sync step:

    delta_i     = local_i − snapshot_i          (per replica)
    mean_delta  = pmean(delta_i)                (the Σ delta_i/n the
                                                 server would apply)
    global     += mean_delta                    (server state)
    local_i    += mean_delta                    (recv merge — NO reset)
    snapshot_i  = local_i                       (send-side old_param)

With every replica starting from the same global, the FIRST window's
global update equals LocalSGD's average exactly — asserted in
tests/test_geosgd.py — while replicas keep their drift afterwards.

Between syncs no collective appears in the HLO at all (separately
compiled steps, the LocalSGD pattern).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...framework.errors import InvalidArgumentError
from ..collective import shard_map
from .localsgd import LocalSGDPlan

__all__ = ["GeoSgdPlan"]


class GeoSgdPlan(LocalSGDPlan):
    """LocalSGD's state layout and host dispatcher + Geo's delta-merge
    sync rule (only :meth:`_make_step` differs)."""

    _FEATURE = "a_sync (Geo-SGD)"

    def __init__(self, network, optimizer, strategy, mesh=None):
        super().__init__(network, optimizer, strategy, mesh)
        cfg = getattr(strategy, "a_sync_configs", None) or {}
        self.k_steps = int(cfg.get("k_steps", 0))
        if self.k_steps <= 0:
            raise InvalidArgumentError(
                "GeoSgdPlan needs a_sync_configs={'k_steps': N>0} "
                "(N local steps per delta push)")
        self.begin_step = 1  # geo has no dense warmup in the reference
        if getattr(strategy, "localsgd", False) or \
                getattr(strategy, "adaptive_localsgd", False):
            raise InvalidArgumentError(
                "a_sync(geo) and localsgd are mutually exclusive sync "
                "strategies — pick one")

    # -- state ---------------------------------------------------------------
    def init_opt_state(self, optimizer, params, buffers=None):
        """LocalSGD's state plus per-replica ``snapshot`` (the
        GeoCommunicator's send-side old_param copy)."""
        state = super().init_opt_state(optimizer, params, buffers)
        state["local"]["snapshot"] = jax.tree.map(
            jnp.copy, state["local"]["params"])
        return state

    # -- step ----------------------------------------------------------------
    def _make_step(self, train_step):
        mesh, axis = self.mesh, self.axis
        spec_l = P(axis)

        def make(sync: bool, n_batch: int):
            def step(params, opt_state, buffers, key, lr, *batch):
                local = opt_state["local"]

                def body(params, buffers, l_params, l_inner, l_bufs,
                         l_snap, key, lr, *batch):
                    sq = lambda t: jax.tree.map(lambda x: x[0], t)
                    st = lambda t: jax.tree.map(lambda x: x[None], t)
                    key = jax.random.fold_in(key, lax.axis_index(axis))
                    loss, out, new_p, new_inner, new_b = train_step(
                        sq(l_params), sq(l_inner), sq(l_bufs),
                        key, lr, *batch)
                    loss = lax.pmean(loss, axis)
                    snap = sq(l_snap)
                    if sync:
                        # send: delta since the last push; the server-side
                        # aggregate is pmean (= Σ delta/n of communicator.h)
                        mean_delta = jax.tree.map(
                            lambda p, s: lax.pmean(
                                p.astype(jnp.float32)
                                - s.astype(jnp.float32), axis),
                            new_p, snap)
                        g_params = jax.tree.map(
                            lambda g, d: (g.astype(jnp.float32)
                                          + d).astype(g.dtype),
                            params, mean_delta)
                        # recv merge: locals absorb the aggregate drift but
                        # are NOT reset (the geo property)
                        new_p = jax.tree.map(
                            lambda p, d: (p.astype(jnp.float32)
                                          + d).astype(p.dtype),
                            new_p, mean_delta)
                        new_snap = new_p
                        # buffers (BN stats) have no delta semantics in the
                        # reference; average AND re-seed the locals with
                        # the average like LocalSGD (localsgd.py) — unlike
                        # params, drifting per-replica running stats have
                        # no error-feedback story
                        new_b = jax.tree.map(
                            lambda x: lax.pmean(x, axis), new_b)
                        g_bufs = new_b
                    else:
                        g_params, g_bufs = params, buffers
                        new_snap = snap
                    return (loss, out, g_params, st(new_p), st(new_inner),
                            st(new_b), st(new_snap), g_bufs)

                in_specs = (P(), P(), spec_l, spec_l, spec_l, spec_l,
                            P(), P()) + (spec_l,) * n_batch
                out_specs = (P(), spec_l, P(), spec_l, spec_l, spec_l,
                             spec_l, P())
                (loss, out, g_params, nl_p, nl_i, nl_b, nl_s,
                 g_bufs) = shard_map(
                    body, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs,
                )(params, buffers, local["params"], local["inner"],
                  local["buffers"], local["snapshot"], key, lr, *batch)
                new_state = {
                    "count": opt_state["count"] + 1,
                    "local": {"params": nl_p, "inner": nl_i,
                              "buffers": nl_b, "snapshot": nl_s},
                }
                return loss, out, g_params, new_state, g_bufs

            return step

        return make

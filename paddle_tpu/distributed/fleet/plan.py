"""ShardingPlan — lowers a DistributedStrategy onto mesh shardings.

This is the TPU-native replacement for the ENTIRE meta-optimizer program-
rewriting stack (reference: fleet/meta_optimizers/sharding_optimizer.py:33,
graph_execution_optimizer.py, transpiler/collective.py:178 GradAllReduce):
instead of inserting c_broadcast/c_allreduce ops into a Program, we assign a
``NamedSharding`` to every value in the jitted train step and let GSPMD
insert the collectives:

* **DP** — batch split over the ``data`` (+``sharding``) axes, params
  replicated ⇒ XLA emits the gradient all-reduce (the reference's
  AllReduceOpHandle, details/all_reduce_op_handle.cc) on its own.
* **ZeRO (sharding)** — optimizer slots (and f32 master weights) sharded
  over the ``sharding`` axis ⇒ XLA turns the grad all-reduce into
  reduce-scatter + the param update into a per-shard update + all-gather,
  which IS ZeRO-1/2 dataflow (reference's sharding_optimizer broadcast/
  allreduce insertion).
* **TP** — parameters annotated with a ``partition_spec`` (see
  meta_parallel layers) are sharded over ``model``; activations follow.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn.layer_base import Layer
from ..mesh import data_axes, get_mesh

__all__ = ["ShardingPlan"]


def _dim_to_shard(shape, axis_size: int, taken_axes) -> Optional[int]:
    """First dim divisible by axis_size that isn't already sharded."""
    for d, s in enumerate(shape):
        if d in taken_axes:
            continue
        if s % axis_size == 0 and s >= axis_size:
            return d
    return None


class ShardingPlan:
    def __init__(self, network: Layer, optimizer, strategy, mesh=None):
        self.network = network
        self.optimizer = optimizer
        self.strategy = strategy
        self.mesh = mesh or get_mesh()
        self._batch_axes = tuple(data_axes(self.mesh))
        self._zero = self.mesh.shape.get("sharding", 1) > 1

        # parameter specs from layer annotations (TP); default replicated
        self.param_specs: Dict[str, P] = {}
        for name, box in network.named_parameters():
            spec = getattr(box, "partition_spec", None)
            self.param_specs[name] = P(*spec) if spec else P()
        self.buffer_specs = {n: P() for n, _ in network.named_buffers()}
        # host mirror of a step counter for plans with a host-side schedule
        # (LocalSGD sync cadence, DGC sparsity ramp); see on_state_restored
        self._t: Optional[int] = None

    def _require_pure_dp(self, feature: str):
        """Plans that replace GSPMD with per-replica shard_map execution
        only compose with pure data parallelism — same restriction as the
        reference meta-optimizers' _can_apply."""
        from ...framework.errors import InvalidArgumentError

        for ax in ("model", "pipe", "sep", "sharding"):
            if self.mesh.shape.get(ax, 1) > 1:
                raise InvalidArgumentError(
                    f"strategy.{feature} composes only with pure data "
                    f"parallelism (mesh axis {ax!r} has size > 1)")

    def on_state_restored(self):
        """Model.load calls this after replacing the optimizer state —
        schedule-carrying plans re-derive their host step mirror from the
        restored ``opt_state['count']`` on the next step."""
        self._t = None

    # -- shardings -----------------------------------------------------------
    def named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def batch_sharding(self) -> NamedSharding:
        return self.named(P(self._batch_axes))

    @property
    def n_data_shards(self) -> int:
        n = 1
        for a in self._batch_axes:
            n *= self.mesh.shape[a]
        return n

    def _slot_spec(self, pspec: P, shape) -> P:
        """ZeRO: shard optimizer slots over the ``sharding`` axis on top of
        any TP sharding the parameter already has."""
        if not self._zero or not shape:
            return pspec
        axis_size = self.mesh.shape["sharding"]
        taken = {i for i, a in enumerate(pspec) if a is not None}
        d = _dim_to_shard(shape, axis_size, taken)
        if d is None:
            return pspec
        parts = list(pspec) + [None] * (len(shape) - len(pspec))
        parts[d] = "sharding"
        return P(*parts)

    def opt_state_shardings(self, params: Dict[str, jax.Array]):
        """Sharding pytree matching optimizer.init(params) (via eval_shape —
        no allocation)."""
        shapes = jax.eval_shape(self.optimizer.init, params)

        slots = {}
        for pname, pslots in shapes["slots"].items():
            pspec = self.param_specs.get(pname, P())
            slots[pname] = {
                sname: self.named(self._slot_spec(pspec, leaf.shape))
                for sname, leaf in pslots.items()
            }
        return {"count": self.named(P()), "slots": slots}

    def param_shardings(self, params: Dict[str, jax.Array]):
        return {n: self.named(self.param_specs.get(n, P())) for n in params}

    def buffer_shardings(self, buffers: Dict[str, jax.Array]):
        return {n: self.named(P()) for n in buffers}

    def init_opt_state(self, optimizer, params: Dict[str, jax.Array],
                       buffers=None):
        """Init under jit with sharded outputs: ZeRO slots are born sharded —
        the full replicated state never materializes.  (LocalSGDPlan
        overrides this to stack per-replica state; it needs ``buffers``.)"""
        return jax.jit(
            optimizer.init,
            out_shardings=self.opt_state_shardings(params),
        )(params)

    # -- application ---------------------------------------------------------
    def place_network(self):
        """device_put every Parameter/Buffer box with its sharding — the
        one-time "broadcast parameters" step (reference: sharding/prune
        broadcast insertion; dygraph DataParallel init broadcast)."""
        for name, box in self.network.named_parameters():
            box.value = jax.device_put(box.value, self.named(self.param_specs[name]))
        for name, box in self.network.named_buffers():
            box.value = jax.device_put(box.value, self.named(P()))

    def shard_batch(self, batch):
        """Split a global host batch across the data axes."""
        sh = self.batch_sharding()
        n_shards = self.n_data_shards
        out = []
        for b in batch:
            b = jnp.asarray(b)
            if b.ndim == 0 or b.shape[0] % n_shards != 0:
                from ...framework.errors import InvalidArgumentError

                raise InvalidArgumentError(
                    f"batch dim {tuple(b.shape)[:1]} not divisible by the "
                    f"{n_shards} data-parallel shards; use a batch size "
                    f"divisible by {n_shards} and drop_last=True (Model.fit "
                    f"does this automatically for partial final batches)"
                )
            out.append(jax.device_put(b, sh))
        return tuple(out)

    def jit_train_step(self, train_step):
        """Compile with output shardings pinned so params stay in-plan and
        slots stay ZeRO-sharded across steps.  Inputs: params/opt/buffers are
        committed (placed) arrays; batch is placed by shard_batch."""
        plan = self

        def out_shardings_for(params, buffers):
            return (
                plan.named(P()),                       # loss
                None,                                  # model out: let XLA pick
                plan.param_shardings(params),
                plan.opt_state_shardings(params),
                plan.buffer_shardings(buffers),
            )

        compiled_cache = {}

        def wrapped(params, opt_state, buffers, key, lr, *batch):
            k = len(batch)
            if k not in compiled_cache:
                compiled_cache[k] = jax.jit(
                    train_step,
                    donate_argnums=(0, 1, 2),
                    out_shardings=out_shardings_for(params, buffers),
                )
            return compiled_cache[k](params, opt_state, buffers, key, lr, *batch)

        return wrapped

"""fleet.metrics — globally-reduced evaluation metrics.

Parity: python/paddle/distributed/fleet/metrics/metric.py (sum/max/min/
auc/mae/rmse/mse/acc over gloo all_reduce of scope tensors).  TPU-native:
each process evaluates its own data shard and holds host-side numpy
accumulators; aggregation rides ``multihost_utils.process_allgather``
(the jax coordination service) instead of a gloo ring.  Single-process
runs reduce to the identity, so the same training script works from a
laptop to a pod.

The ``scope`` parameter of the reference (static-graph Variable lookup)
is accepted and ignored — there is no scope; pass arrays directly.
"""
from __future__ import annotations

import numpy as np
import jax

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]

_py_sum, _py_max, _py_min = sum, max, min  # the paddle API shadows builtins


def _allgather(arr: np.ndarray) -> np.ndarray:
    """[n_process, *arr.shape] — every process's value."""
    if jax.process_count() == 1:
        return arr[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def _to_np(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float64)


def sum(input, scope=None, util=None):  # noqa: A001 — paddle API name
    """Global elementwise sum of ``input`` across processes."""
    return _allgather(_to_np(input)).sum(axis=0)


def max(input, scope=None, util=None):  # noqa: A001
    return _allgather(_to_np(input)).max(axis=0)


def min(input, scope=None, util=None):  # noqa: A001
    return _allgather(_to_np(input)).min(axis=0)


def auc(stat_pos, stat_neg, scope=None, util=None) -> float:
    """AUC from bucketed score histograms (reference: metric.py:140 —
    same bucket-trapezoid estimate as the distributed auc op).

    ``stat_pos[i]`` / ``stat_neg[i]``: counts of positive / negative
    examples whose predicted score fell into bucket ``i``.
    """
    from ...metric import bucket_auc

    # reference metric.py:214 returns 0.5 when one class is empty (the
    # hapi Auc metric returns 0.0 — both kept, via the shared sweep)
    return bucket_auc(sum(stat_pos), sum(stat_neg), degenerate=0.5)


def mae(abserr, total_ins_num, scope=None, util=None) -> float:
    """Global mean absolute error: sum(abserr) / sum(total_ins_num)."""
    err = float(sum(abserr).sum())
    n = float(sum(_to_np(total_ins_num)).sum())
    return err / _py_max(n, 1.0)


def mse(sqrerr, total_ins_num, scope=None, util=None) -> float:
    err = float(sum(sqrerr).sum())
    n = float(sum(_to_np(total_ins_num)).sum())
    return err / _py_max(n, 1.0)


def rmse(sqrerr, total_ins_num, scope=None, util=None) -> float:
    return float(np.sqrt(mse(sqrerr, total_ins_num)))


def acc(correct, total, scope=None, util=None) -> float:
    c = float(sum(_to_np(correct)).sum())
    t = float(sum(_to_np(total)).sum())
    return c / _py_max(t, 1.0)

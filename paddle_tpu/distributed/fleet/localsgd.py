"""LocalSGD data parallelism — k local steps per replica, then model averaging.

Parity: the reference's LocalSGD program transpiler
(transpiler/collective.py:270 — snapshot vars + c_allreduce of param deltas
every ``k_steps``) and the fleet meta-optimizer
(fleet/meta_optimizers/localsgd_optimizer.py).

TPU-native design: instead of rewriting a Program with snapshot/allreduce
ops, the train step runs under ``shard_map`` over the ``data`` axis with NO
implicit gradient all-reduce — each device advances its own replica.  The
divergent per-replica state (parameters, optimizer slots, buffers) rides
stacked ``[ndp, ...]`` inside the optimizer state, sharded over ``data`` so
each device holds exactly its own copy.  Every ``k_steps`` a *separately
compiled* step adds a ``lax.pmean`` over replicas; between syncs no
collective appears in the HLO at all — the communication saving is
structural, not simulated.

Semantics kept from the reference:
* ``k_steps``: sync period; ``begin_step``: plain per-step averaging (≈DP)
  until this step, LocalSGD after.
* The Model-visible parameters/buffers are the last *synced* values —
  between syncs they lag the replicas (evaluate after a sync boundary,
  as the reference does).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..collective import shard_map
from .plan import ShardingPlan

__all__ = ["LocalSGDPlan", "AdaptiveLocalSGDPlan"]


class LocalSGDPlan(ShardingPlan):
    """ShardingPlan variant where the ``data`` axis holds independent
    replicas between sync points instead of a single GSPMD program."""

    _FEATURE = "localsgd"  # the flag named in mesh-compat errors

    def __init__(self, network, optimizer, strategy, mesh=None):
        super().__init__(network, optimizer, strategy, mesh)
        self._require_pure_dp(self._FEATURE)
        cfg = getattr(strategy, "localsgd_configs", None) or {}
        self.k_steps = max(int(cfg.get("k_steps", 1)), 1)
        self.begin_step = max(int(cfg.get("begin_step", 1)), 1)
        self.axis = "data"
        self.ndp = self.mesh.shape["data"]

    # -- state ---------------------------------------------------------------
    def _local_sharding(self) -> NamedSharding:
        return self.named(P(self.axis))

    def init_opt_state(self, optimizer, params, buffers=None):
        """{"count", "local": {"params", "inner", "buffers"}} — the local
        subtrees are stacked [ndp, ...], one replica per data-axis device."""
        buffers = buffers or {}
        ndp = self.ndp

        def init_fn(params, buffers):
            stack = lambda t: jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (ndp,) + x.shape), t)
            return {
                "count": jnp.zeros((), jnp.int32),
                "local": {
                    "params": stack(params),
                    "inner": stack(optimizer.init(params)),
                    "buffers": stack(buffers),
                },
            }

        shapes = jax.eval_shape(init_fn, params, buffers)
        shardings = {
            "count": self.named(P()),
            "local": jax.tree.map(lambda _: self._local_sharding(),
                                  shapes["local"]),
        }
        return jax.jit(init_fn, out_shardings=shardings)(params, buffers)

    # -- step ----------------------------------------------------------------
    def jit_train_step(self, train_step):
        """Host dispatcher shared by LocalSGD / AdaptiveLocalSGD / GeoSGD
        (subclasses override :meth:`_make_step` for their sync rule)."""
        plan = self
        make = self._make_step(train_step)
        compiled = {}

        def wrapped(params, opt_state, buffers, key, lr, *batch):
            # host mirror of opt_state["count"]: one device read at start
            # and after each Model.load (on_state_restored nulls it)
            t = (plan._t if plan._t is not None
                 else int(opt_state["count"])) + 1
            if plan._last_sync is None:
                # restored mid-window: re-anchor the cadence conservatively
                plan._last_sync = t - 1
            sync = t < plan.begin_step or \
                (t - plan._last_sync) >= plan.k_steps
            kk = (bool(sync), len(batch))
            if kk not in compiled:
                compiled[kk] = jax.jit(make(*kk), donate_argnums=(0, 1, 2))
            out = compiled[kk](params, opt_state, buffers, key, lr, *batch)
            plan._t = t  # advance only after a successful dispatch
            if sync:
                plan._last_sync = t
            plan._after_step(t, bool(sync), out[0], lr)
            return out

        wrapped.compiled = compiled  # introspection (tests count collectives)
        wrapped.make = make
        return wrapped

    def _make_step(self, train_step):
        mesh, axis = self.mesh, self.axis  # the sync period is read from
        spec_l = P(axis)                   # plan.k_steps LIVE (adaptive)

        def make(sync: bool, n_batch: int):
            def step(params, opt_state, buffers, key, lr, *batch):
                local = opt_state["local"]

                def body(params, buffers, l_params, l_inner, l_bufs,
                         key, lr, *batch):
                    # local leaves arrive [1, ...] — this device's replica
                    sq = lambda t: jax.tree.map(lambda x: x[0], t)
                    st = lambda t: jax.tree.map(lambda x: x[None], t)
                    key = jax.random.fold_in(key, lax.axis_index(axis))
                    loss, out, new_p, new_inner, new_b = train_step(
                        sq(l_params), sq(l_inner), sq(l_bufs),
                        key, lr, *batch)
                    loss = lax.pmean(loss, axis)
                    if sync:
                        pm = lambda t: jax.tree.map(
                            lambda x: lax.pmean(x, axis), t)
                        new_p = pm(new_p)
                        new_b = pm(new_b)
                        g_params, g_bufs = new_p, new_b
                    else:  # pass the last synced values through, unchanged
                        g_params, g_bufs = params, buffers
                    return (loss, out, g_params, st(new_p), st(new_inner),
                            st(new_b), g_bufs)

                in_specs = (P(), P(), spec_l, spec_l, spec_l, P(), P()) \
                    + (spec_l,) * n_batch
                out_specs = (P(), spec_l, P(), spec_l, spec_l, spec_l, P())
                loss, out, g_params, nl_p, nl_i, nl_b, g_bufs = shard_map(
                    body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                )(params, buffers, local["params"], local["inner"],
                  local["buffers"], key, lr, *batch)
                new_state = {
                    "count": opt_state["count"] + 1,
                    "local": {"params": nl_p, "inner": nl_i, "buffers": nl_b},
                }
                return loss, out, g_params, new_state, g_bufs

            return step

        return make

    _last_sync: "int | None" = 0

    def _after_step(self, t, synced, loss, lr):
        """Hook for host-side schedule adaptation (AdaptiveLocalSGDPlan)."""

    def on_state_restored(self):
        super().on_state_restored()
        self._last_sync = None


class AdaptiveLocalSGDPlan(LocalSGDPlan):
    """Step-adaptive LocalSGD (ref: fleet/meta_optimizers/
    localsgd_optimizer.py:194 AdaptiveLocalSGDOptimizer): the sync period
    adapts to training progress,

        k = clip(ceil(sqrt(lr0 * loss / (lr * loss0) * init_k)), 1, 16)

    recomputed at every sync point from the replica-averaged loss
    (lr0/loss0 recorded at step 1, :352-433 in the reference) — early
    training (loss near loss0) syncs often; as the loss falls the replicas
    drift longer between syncs.  The host-side cadence makes this a pure
    scheduling change: the compiled sync/local steps are identical to
    LocalSGDPlan's."""

    MAX_K, MIN_K = 16, 1  # the reference's clamp constants (:425-431)

    def __init__(self, network, optimizer, strategy, mesh=None):
        cfg = getattr(strategy, "adaptive_localsgd_configs", None) or {}
        # reuse the parent's config plumbing: adaptive init_k seeds k_steps
        super().__init__(network, optimizer, strategy, mesh)
        self.init_k_steps = max(int(cfg.get("init_k_steps", 1)), 1)
        self.begin_step = max(int(cfg.get("begin_step", 1)), 1)
        self.k_steps = self.init_k_steps
        self._loss0 = None
        self._lr0 = None

    def _after_step(self, t, synced, loss, lr):
        import math

        if self._loss0 is None:
            # the reference's initialize() records (loss0, lr0) at step 1;
            # on a checkpoint resume the fresh plan re-anchors the baseline
            # at the first observed step instead of freezing k forever.  A
            # non-finite first loss must not poison the baseline — wait
            # for a finite one.
            l0, r0 = float(loss), float(lr)
            if math.isfinite(l0) and math.isfinite(r0):
                self._loss0 = max(l0, 1e-12)
                self._lr0 = max(r0, 1e-12)
            return
        if not synced:
            return
        ratio = (self._lr0 * max(float(loss), 0.0)) / \
            (max(float(lr), 1e-12) * self._loss0)
        if not math.isfinite(ratio):  # divergence spike: sync at max period
            self.k_steps = self.MAX_K
            return
        next_k = math.ceil(math.sqrt(ratio * self.init_k_steps))
        self.k_steps = int(min(self.MAX_K, max(self.MIN_K, next_k)))

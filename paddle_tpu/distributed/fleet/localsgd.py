"""LocalSGD data parallelism — k local steps per replica, then model averaging.

Parity: the reference's LocalSGD program transpiler
(transpiler/collective.py:270 — snapshot vars + c_allreduce of param deltas
every ``k_steps``) and the fleet meta-optimizer
(fleet/meta_optimizers/localsgd_optimizer.py).

TPU-native design: instead of rewriting a Program with snapshot/allreduce
ops, the train step runs under ``shard_map`` over the ``data`` axis with NO
implicit gradient all-reduce — each device advances its own replica.  The
divergent per-replica state (parameters, optimizer slots, buffers) rides
stacked ``[ndp, ...]`` inside the optimizer state, sharded over ``data`` so
each device holds exactly its own copy.  Every ``k_steps`` a *separately
compiled* step adds a ``lax.pmean`` over replicas; between syncs no
collective appears in the HLO at all — the communication saving is
structural, not simulated.

Semantics kept from the reference:
* ``k_steps``: sync period; ``begin_step``: plain per-step averaging (≈DP)
  until this step, LocalSGD after.
* The Model-visible parameters/buffers are the last *synced* values —
  between syncs they lag the replicas (evaluate after a sync boundary,
  as the reference does).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..collective import shard_map
from .plan import ShardingPlan

__all__ = ["LocalSGDPlan"]


class LocalSGDPlan(ShardingPlan):
    """ShardingPlan variant where the ``data`` axis holds independent
    replicas between sync points instead of a single GSPMD program."""

    def __init__(self, network, optimizer, strategy, mesh=None):
        super().__init__(network, optimizer, strategy, mesh)
        self._require_pure_dp("localsgd")
        cfg = getattr(strategy, "localsgd_configs", None) or {}
        self.k_steps = max(int(cfg.get("k_steps", 1)), 1)
        self.begin_step = max(int(cfg.get("begin_step", 1)), 1)
        self.axis = "data"
        self.ndp = self.mesh.shape["data"]

    # -- state ---------------------------------------------------------------
    def _local_sharding(self) -> NamedSharding:
        return self.named(P(self.axis))

    def init_opt_state(self, optimizer, params, buffers=None):
        """{"count", "local": {"params", "inner", "buffers"}} — the local
        subtrees are stacked [ndp, ...], one replica per data-axis device."""
        buffers = buffers or {}
        ndp = self.ndp

        def init_fn(params, buffers):
            stack = lambda t: jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (ndp,) + x.shape), t)
            return {
                "count": jnp.zeros((), jnp.int32),
                "local": {
                    "params": stack(params),
                    "inner": stack(optimizer.init(params)),
                    "buffers": stack(buffers),
                },
            }

        shapes = jax.eval_shape(init_fn, params, buffers)
        shardings = {
            "count": self.named(P()),
            "local": jax.tree.map(lambda _: self._local_sharding(),
                                  shapes["local"]),
        }
        return jax.jit(init_fn, out_shardings=shardings)(params, buffers)

    # -- step ----------------------------------------------------------------
    def jit_train_step(self, train_step):
        plan = self
        mesh, axis, k = self.mesh, self.axis, self.k_steps
        spec_l = P(axis)

        def make(sync: bool, n_batch: int):
            def step(params, opt_state, buffers, key, lr, *batch):
                local = opt_state["local"]

                def body(params, buffers, l_params, l_inner, l_bufs,
                         key, lr, *batch):
                    # local leaves arrive [1, ...] — this device's replica
                    sq = lambda t: jax.tree.map(lambda x: x[0], t)
                    st = lambda t: jax.tree.map(lambda x: x[None], t)
                    key = jax.random.fold_in(key, lax.axis_index(axis))
                    loss, out, new_p, new_inner, new_b = train_step(
                        sq(l_params), sq(l_inner), sq(l_bufs),
                        key, lr, *batch)
                    loss = lax.pmean(loss, axis)
                    if sync:
                        pm = lambda t: jax.tree.map(
                            lambda x: lax.pmean(x, axis), t)
                        new_p = pm(new_p)
                        new_b = pm(new_b)
                        g_params, g_bufs = new_p, new_b
                    else:  # pass the last synced values through, unchanged
                        g_params, g_bufs = params, buffers
                    return (loss, out, g_params, st(new_p), st(new_inner),
                            st(new_b), g_bufs)

                in_specs = (P(), P(), spec_l, spec_l, spec_l, P(), P()) \
                    + (spec_l,) * n_batch
                out_specs = (P(), spec_l, P(), spec_l, spec_l, spec_l, P())
                loss, out, g_params, nl_p, nl_i, nl_b, g_bufs = shard_map(
                    body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                )(params, buffers, local["params"], local["inner"],
                  local["buffers"], key, lr, *batch)
                new_state = {
                    "count": opt_state["count"] + 1,
                    "local": {"params": nl_p, "inner": nl_i, "buffers": nl_b},
                }
                return loss, out, g_params, new_state, g_bufs

            return step

        compiled = {}

        def wrapped(params, opt_state, buffers, key, lr, *batch):
            # host mirror of opt_state["count"]: one device read at start
            # and after each Model.load (on_state_restored nulls it)
            t = (plan._t if plan._t is not None
                 else int(opt_state["count"])) + 1
            sync = t < plan.begin_step or t % k == 0
            kk = (bool(sync), len(batch))
            if kk not in compiled:
                compiled[kk] = jax.jit(make(*kk), donate_argnums=(0, 1, 2))
            out = compiled[kk](params, opt_state, buffers, key, lr, *batch)
            plan._t = t  # advance only after a successful dispatch
            return out

        return wrapped

"""fleet.utils — filesystem clients + KV rendezvous server.

Parity: python/paddle/distributed/fleet/utils/{fs.py, http_server.py}.
LocalFS and the KV server are real (stdlib); HDFSClient shells out to a
hadoop binary the TPU image doesn't carry, so it constructs but raises
with the object-store guidance on use.
"""
from __future__ import annotations

import http.server
import os
import shutil
import threading

from ...framework.errors import UnimplementedError

__all__ = ["LocalFS", "HDFSClient", "FS", "KVServer", "KVHandler",
           "KVHTTPServer"]


class FS:
    """Abstract FS interface (ref: fleet/utils/fs.py:25)."""

    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem client (ref: fs.py:116) — the checkpoint/auto-
    checkpoint machinery's default store."""

    def ls_dir(self, fs_path):
        """→ ([dirs], [files]) — the reference's pair convention."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, entry))
             else files).append(entry)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_file(fs_path):
            os.remove(fs_path)
        elif self.is_dir(fs_path):
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not overwrite and self.is_exist(dst_path):
            raise FileExistsError(dst_path)
        os.replace(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [d for d in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, d))]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()


class HDFSClient(FS):
    """Ref: fs.py HDFSClient — drives the ``hadoop fs`` CLI.  No hadoop
    binary ships in the TPU image; every operation raises with the
    replacement (object-store paths via LocalFS-mounted fuse, or orbax's
    cloud-storage checkpointing in incubate.sharded_checkpoint)."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop_home = hadoop_home

    def _no_hadoop(self, op):
        raise UnimplementedError(
            f"HDFSClient.{op}: no hadoop CLI in this environment — mount "
            f"the store (gcsfuse etc.) and use LocalFS, or use "
            f"incubate.sharded_checkpoint (orbax) for cloud checkpoints")

    def ls_dir(self, fs_path):
        self._no_hadoop("ls_dir")

    def is_file(self, fs_path):
        self._no_hadoop("is_file")

    def is_dir(self, fs_path):
        self._no_hadoop("is_dir")

    def is_exist(self, fs_path):
        self._no_hadoop("is_exist")

    def mkdirs(self, fs_path):
        self._no_hadoop("mkdirs")

    def delete(self, fs_path):
        self._no_hadoop("delete")

    def need_upload_download(self):
        return True

    def touch(self, fs_path, exist_ok=True):
        self._no_hadoop("touch")


class KVHandler(http.server.BaseHTTPRequestHandler):
    """GET/PUT/DELETE over an in-memory KV map (ref: http_server.py:47) —
    the file-free rendezvous store RoleMaker variants used."""

    def log_message(self, *args):  # quiet
        pass

    def do_GET(self):
        with self.server.kv_lock:
            value = self.server.kv.get(self.path.strip("/"))
        if value is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        with self.server.kv_lock:
            self.server.kv[self.path.strip("/")] = data
        self.send_response(200)
        self.end_headers()

    do_POST = do_PUT

    def do_DELETE(self):
        with self.server.kv_lock:
            self.server.kv.pop(self.path.strip("/"), None)
            self.server.delete_count += 1
        self.send_response(200)
        self.end_headers()


class KVHTTPServer(http.server.ThreadingHTTPServer):
    """Ref: http_server.py:135."""

    def __init__(self, port, handler):
        super().__init__(("", port), handler)
        self.kv_lock = threading.Lock()
        self.kv = {}
        self.delete_count = 0

    def get_deleted_size(self, key=None):
        with self.kv_lock:
            return self.delete_count


class KVServer:
    """Threaded KV rendezvous server (ref: http_server.py:158):
    ``start()``/``stop()`` around a KVHTTPServer."""

    def __init__(self, port, size=None):
        self.http_server = KVHTTPServer(port, KVHandler)
        self.listen_thread = None
        self.size = size or {}

    def start(self):
        self.listen_thread = threading.Thread(
            target=self.http_server.serve_forever, daemon=True)
        self.listen_thread.start()

    def stop(self):
        self.http_server.shutdown()
        self.listen_thread.join()
        self.http_server.server_close()

    def should_stop(self):
        return self.http_server.get_deleted_size() >= sum(
            self.size.values()) if self.size else False

"""paddle_tpu.distributed.fleet — the distributed-training control plane.

Parity: python/paddle/distributed/fleet/ (Fleet singleton fleet_base.py:62,
init:125, distributed_optimizer:554, minimize:946; meta-optimizer composition
:995-1065).  Usage is the same four lines:

    strategy = fleet.DistributedStrategy(sharding=True)
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(paddle_tpu.optimizer.Adam(...))
    model = paddle_tpu.Model(net); model.prepare(opt, loss); model.fit(...)

but where the reference's fleet rewrites the Program through meta-optimizers,
``init`` here builds the hybrid device Mesh and ``distributed_optimizer``
tags the optimizer with a ShardingPlan that Model.prepare lowers to
pjit shardings (see plan.py).
"""
from __future__ import annotations

from typing import Optional

import jax

from ...framework.errors import InvalidArgumentError
from .. import env as _env
from ..mesh import build_mesh, get_mesh, set_mesh
from . import metrics  # noqa: F401
from . import utils  # noqa: F401
from . import data_generator  # noqa: F401
from .utils import LocalFS, HDFSClient  # noqa: F401  (ref fleet/utils)
from .plan import ShardingPlan
from .strategy import DistributedStrategy

__all__ = [
    "DistributedStrategy",
    "ShardingPlan",
    "metrics",
    "init",
    "distributed_optimizer",
    "distributed_model",
    "worker_num",
    "worker_index",
    "is_first_worker",
    "barrier_worker",
    "stop_worker",
    "get_strategy",
    "is_initialized",
]

_strategy: Optional[DistributedStrategy] = None
_initialized = False


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, devices=None):
    """Build the hybrid mesh from the strategy degrees and mark fleet active.

    ``role_maker`` (the reference's Gloo rendezvous) is accepted for parity
    and ignored — rank wiring comes from init_parallel_env / jax.distributed.
    """
    global _strategy, _initialized
    if not is_collective:
        raise InvalidArgumentError(
            "parameter-server mode is not supported on TPU; capabilities are "
            "covered by sharded arrays (see SURVEY §7 translation table)"
        )
    _env.init_parallel_env()
    strategy = strategy or DistributedStrategy()
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    fixed = (strategy.mp_degree * strategy.pp_degree * strategy.sep_degree
             * strategy.ep_degree)
    sharding_degree = strategy.sharding_degree
    dp = strategy.dp_degree
    if strategy.sharding and sharding_degree in (0, 1):
        # span the devices an explicit dp_degree doesn't claim
        sharding_degree = n // (fixed * (dp or 1))
        if sharding_degree < 1:
            raise InvalidArgumentError(
                f"mp*pp*sep*dp degrees ({fixed * (dp or 1)}) exceed the "
                f"device count {n}; no devices left for the sharding axis"
            )
    if strategy.sharding and dp in (0, None):
        dp = n // (fixed * sharding_degree)
    mesh = build_mesh(
        dp=dp or 0,
        mp=strategy.mp_degree,
        pp=strategy.pp_degree,
        sep=strategy.sep_degree,
        sharding=max(sharding_degree, 1),
        ep=strategy.ep_degree,
        devices=devices,
    )
    set_mesh(mesh)
    strategy.sharding_degree = max(sharding_degree, 1)
    _strategy = strategy
    _initialized = True
    from ...framework.logging import vlog

    vlog(1, "fleet.init: mesh %s over %d devices", dict(mesh.shape), n)
    return mesh


def is_initialized() -> bool:
    return _initialized


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Compose the strategy's optimizer-level features and tag the result
    for distributed execution; Model.prepare builds the ShardingPlan from
    the tag (replaces meta-opt minimize orchestration, fleet_base.py:946,
    and the meta-optimizer composition in strategy_compiler.py:112)."""
    global _strategy
    if not _initialized:
        raise InvalidArgumentError("call fleet.init() before distributed_optimizer")
    if strategy is not None:
        _strategy = strategy
    st = _strategy or DistributedStrategy()

    # honest errors for strategies with no TPU implementation yet — the
    # reference silently composed these as program rewrites; silently
    # ignoring them here would train with a different algorithm than asked
    from ...framework.errors import UnimplementedError

    if (st.localsgd or st.adaptive_localsgd) and st.gradient_merge:
        raise InvalidArgumentError(
            "strategy.localsgd/adaptive_localsgd does not compose with "
            "gradient_merge (the reference meta-optimizers are mutually "
            "exclusive too)")
    if st.localsgd and st.adaptive_localsgd:
        raise InvalidArgumentError(
            "pick ONE of strategy.localsgd / strategy.adaptive_localsgd "
            "(the reference meta-optimizers black-list each other)")
    if st.dgc:
        # reference: DGC meta-optimizer applies only to Momentum
        # (fleet/meta_optimizers/dgc_optimizer.py _can_apply); swap it for
        # DGCMomentum, which compresses inside the DGCPlan shard_map
        from ...optimizer.dgc import DGCMomentum
        from ...optimizer.optimizer import Momentum as _Momentum

        for other in ("localsgd", "adaptive_localsgd", "lamb", "lars",
                      "gradient_merge"):
            if getattr(st, other):
                raise InvalidArgumentError(
                    f"strategy.dgc does not compose with {other} (the "
                    "reference meta-optimizers are mutually exclusive too)")
        if not isinstance(optimizer, (DGCMomentum, _Momentum)):
            raise InvalidArgumentError(
                "strategy.dgc applies to a Momentum optimizer (reference "
                "dgc_optimizer.py _can_apply)")
        if not isinstance(optimizer, DGCMomentum):
            if optimizer._multi_precision:
                raise InvalidArgumentError(
                    "strategy.dgc has no multi_precision support (the u/v "
                    "accumulators are f32 already); construct the Momentum "
                    "with multi_precision=False")
            cfg = st.dgc_configs or {}
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._param_boxes,
                rampup_begin_step=int(cfg.get("rampup_begin_step", 0)),
                rampup_step=int(cfg.get("rampup_step", 1)),
                sparsity=cfg.get("sparsity", [0.999]),
                use_nesterov=optimizer._nesterov,
                # a regularizer object lives in _regularizer with
                # _weight_decay zeroed — forward whichever is active
                weight_decay=(optimizer._regularizer
                              or optimizer._weight_decay),
                grad_clip=optimizer._grad_clip,
            )
    if st.a_sync and int((st.a_sync_configs or {}).get("k_steps", 0)) <= 0:
        raise UnimplementedError(
            "strategy.a_sync with k_steps=0 is PURE parameter-server async "
            "mode (reference: operators/distributed/communicator.h:268); "
            "its stale-tolerance has no counterpart on a synchronous TPU "
            "mesh.  Migrations that carry the capability: "
            "a_sync_configs={'k_steps': N} for Geo-SGD (local steps + "
            "periodic parameter-delta push, geo_sgd_transpiler.py parity), "
            "strategy.localsgd for periodic model averaging, and "
            "paddle.incubate.HostEmbeddingTable for beyond-HBM tables "
            "(the PS role's big-table job)")

    from ...optimizer.optimizer import Lamb, Lars, Momentum

    if st.lamb and not isinstance(optimizer, Lamb):
        # LAMB meta-optimizer replaces an Adam-family inner optimizer
        # (reference: fleet/meta_optimizers/lamb_optimizer.py)
        cfg = st.lamb_configs or {}
        optimizer = Lamb(
            learning_rate=optimizer._learning_rate,
            lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
            parameters=optimizer._param_boxes,
            grad_clip=optimizer._grad_clip,
            multi_precision=optimizer._multi_precision,
            exclude_from_weight_decay_fn=cfg.get("exclude_from_weight_decay_fn"),
        )
    if st.lars and not isinstance(optimizer, Lars):
        # reference: fleet/meta_optimizers/lars_optimizer.py (momentum only)
        cfg = st.lars_configs or {}
        momentum = getattr(optimizer, "_momentum", 0.9)
        if not isinstance(optimizer, Momentum):
            raise InvalidArgumentError(
                "strategy.lars applies to a Momentum optimizer (reference "
                "lars_optimizer.py _can_apply)")
        optimizer = Lars(
            learning_rate=optimizer._learning_rate,
            momentum=momentum,
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            parameters=optimizer._param_boxes,
            grad_clip=optimizer._grad_clip,
            multi_precision=optimizer._multi_precision,
            exclude_from_weight_decay=cfg.get("exclude_from_weight_decay"),
            epsilon=cfg.get("epsilon", 0),
        )
    if st.gradient_merge:
        from ...optimizer.gradient_merge import GradientMergeOptimizer

        cfg = st.gradient_merge_configs or {}
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=int(cfg.get("k_steps", 1)),
            avg=bool(cfg.get("avg", True)))

    optimizer._fleet_strategy = st
    return optimizer


def distributed_model(model):
    """Place a Layer's parameters onto the mesh per the active strategy
    (replicated + TP annotations).  Returns the same object (no wrapper —
    SPMD needs no grad-hook machinery like dygraph DataParallel,
    fluid/dygraph/parallel.py:335)."""
    from ...hapi.model import Model as _HapiModel
    from ...nn.layer_base import Layer

    net = model.network if isinstance(model, _HapiModel) else model
    if not isinstance(net, Layer):
        raise InvalidArgumentError("distributed_model expects a Layer or Model")
    if _strategy is not None and (_strategy.localsgd
                                  or _strategy.adaptive_localsgd):
        raise InvalidArgumentError(
            "strategy.localsgd only runs through Model.prepare/fit (the "
            "per-replica state and sync schedule live in the Model's plan); "
            "manual training loops would silently skip the averaging")
    plan = ShardingPlan(net, optimizer=None, strategy=_strategy, mesh=get_mesh())
    plan.place_network()
    return model


def worker_num() -> int:
    return jax.process_count()


def worker_index() -> int:
    return jax.process_index()


def is_first_worker() -> bool:
    return jax.process_index() == 0


def barrier_worker():
    from .. import collective

    collective.barrier()


def stop_worker():
    """No persistent worker daemons exist (the reference tears down brpc/gloo
    servers here)."""

"""``python -m paddle_tpu.distributed.launch script.py [args...]``

Parity: ``python -m paddle.distributed.launch`` (reference: fleet/launch.py).
One process per HOST (not per device — SPMD drives all local chips); the pod
runtime (or the operator) runs this command on every host with
COORDINATOR_ADDRESS / PADDLE_TRAINER_* env wiring, and init_parallel_env
joins the jax.distributed coordination service.
"""
from .parallel import launch

if __name__ == "__main__":
    raise SystemExit(launch())

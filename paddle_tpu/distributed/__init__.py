"""paddle_tpu.distributed — collectives, mesh, fleet, parallel layers.

Reference surface: python/paddle/distributed/ (§2.9 of SURVEY.md).  The
communication backend is XLA ICI/DCN collectives over a named Mesh (see
mesh.py) instead of NCCL rings + Gloo + gRPC parameter servers.
"""
from .env import (  # noqa: F401
    ParallelEnv,
    init_parallel_env,
    validate_env,
    get_rank,
    get_world_size,
    process_index,
    process_count,
    gang_transport,
)
from .mesh import (  # noqa: F401
    build_mesh,
    get_mesh,
    set_mesh,
    mesh_axis_size,
    Mesh,
    NamedSharding,
    PartitionSpec,
)
from .collective import (  # noqa: F401
    ReduceOp,
    all_reduce,
    all_gather,
    reduce,
    broadcast,
    scatter,
    alltoall,
    barrier,
    psum,
    pmean,
    pmax,
    pmin,
    ppermute,
    all_to_all_single,
)
from .parallel import (  # noqa: F401
    DataParallel,
    spawn,
    shard_batch,
    GANG_RESTART_EXIT_CODE,
    RESTART_STORM_EXIT_CODE,
)
from .gang import (  # noqa: F401
    Gang,
    FileTransport,
    KVStoreTransport,
    default_gang,
    current_gang,
    set_gang,
)


def prepare_context(strategy=None):
    """Legacy dygraph-DP bootstrap (ref: fluid/dygraph/parallel.py:34) —
    the modern entry is init_parallel_env; kept for source compatibility.
    Returns None single-process, else initializes the env like the
    reference (which also returns None when nranks < 2)."""
    env = ParallelEnv()
    if env.world_size < 2:
        return None
    init_parallel_env()
    return strategy
from ..nn.recompute import recompute  # noqa: F401  (fleet.utils.recompute parity)
from . import launch  # noqa: F401  (module: python -m paddle_tpu.distributed.launch)
from . import fleet  # noqa: F401
from . import heartbeat  # noqa: F401
from . import meta_parallel  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    ring_attention,
    ulysses_attention,
    ring_attention_sharded,
    ulysses_attention_sharded,
)

"""Tensor-parallel building blocks (megatron-style sharded layers).

The reference at this version has NO tensor parallelism (verified in
SURVEY §2.9: no megatron/model_parallel hits) — these are the new
first-class capability required of the TPU framework.  Naming follows the
later fleet.meta_parallel API so paddle users find what they expect:
ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
ParallelCrossEntropy.

SPMD design: a layer does NOT call collectives.  It annotates its
parameters with a ``partition_spec`` over the ``model`` mesh axis and
constrains its activation sharding; GSPMD inserts the all-gather /
reduce-scatter exactly where the megatron forward would put explicit
NCCL calls.  Column(out-sharded) → Row(in-sharded) pairs therefore fuse
into one all-reduce at the row output, the classic 2-matmul MLP pattern.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import random as _random
from ..nn import initializer as I
from ..nn.layer_base import Layer, Parameter, current_rng_key
from . import mesh as mesh_mod
from .mesh import get_mesh

__all__ = [
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "constrain",
]

#: weight dtypes the quantized serving path stores (slim.quantize_weights)
_QUANT_DTYPES = ("int8", "float8_e4m3fn")


def _quantized_forward(layer, x):
    """Quantized Linear leg shared by Column/RowParallelLinear: the
    weight arrived int8/fp8 (``slim.quantize_weights`` in place, or a
    quantized tree bound by ``functional_call``), so route through
    ``ops.quantized_matmul`` with the per-channel ``weight_scale``
    buffer and the bias fused into the epilogue.  The dtype branch is
    static under trace — a float weight never pays for this check."""
    from ..ops.quantized_matmul import quantized_linear

    scale = layer._buffers.get("weight_scale")
    if scale is None:
        from ..framework.errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"{type(layer).__name__}: weight is "
            f"{jnp.asarray(layer.weight).dtype} but no weight_scale "
            f"buffer is registered — quantize via slim.quantize_weights "
            f"/ slim.quantize_model_trees, not a bare dtype cast")
    bias = None if layer.bias is None else jnp.asarray(layer.bias)
    return quantized_linear(jnp.asarray(x), jnp.asarray(layer.weight),
                            scale.value, bias)


def _lora_leg(layer, x, y):
    """Batched multi-LoRA delta shared by Column/RowParallelLinear: when
    the layer carries an adapter table (``lora.enable_lora``) AND a
    per-slot id scope is active (the serving step installs one), add the
    ragged grouped delta; rows with id -1 keep the base output bitwise.
    The membership check is the only cost for LoRA-free layers."""
    if "lora_A" not in layer._buffers:
        return y
    from ..lora.batched import apply_lora

    return apply_lora(layer, x, y)


def constrain(x, *spec):
    """Apply a sharding constraint when tracing (no-op eagerly, and a
    no-op inside ``mesh.suppress_constraints`` scopes — fully-manual
    shard_map bodies, where specs naming manual axes are rejected)."""
    if isinstance(x, jax.core.Tracer) and not mesh_mod.constraints_suppressed():
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(get_mesh(), P(*spec)))
    return x


class ColumnParallelLinear(Layer):
    """Linear with the OUTPUT features sharded over the ``model`` axis.

    weight [in, out∥model]; bias [out∥model].  ``gather_output=True``
    replicates the result (ends the TP region)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.partition_spec = (None, "model")
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.partition_spec = ("model",)
        else:
            self.bias = None

    def forward(self, x):
        if str(jnp.asarray(self.weight).dtype) in _QUANT_DTYPES:
            y = _quantized_forward(self, x)
        else:
            y = jnp.matmul(jnp.asarray(x), jnp.asarray(self.weight))
            if self.bias is not None:
                y = y + jnp.asarray(self.bias)
        y = _lora_leg(self, x, y)
        if self.gather_output:
            y = constrain(y, *([None] * y.ndim))
        else:
            y = constrain(y, *([None] * (y.ndim - 1) + ["model"]))
        return y


class RowParallelLinear(Layer):
    """Linear with the INPUT features sharded over ``model``.

    weight [in∥model, out]; bias [out] (replicated, added once).  Feeding it
    a ColumnParallelLinear(gather_output=False) output keeps the hidden
    activations sharded end-to-end; the sum over the sharded contraction
    becomes the single all-reduce of the megatron MLP."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.partition_spec = ("model", None)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        from .collective import get_overlap_schedule

        x = jnp.asarray(x)
        if self.input_is_parallel:
            x = constrain(x, *([None] * (x.ndim - 1) + ["model"]))
        # overlap dial (trace-time): deferring the output-replication
        # constrain slides the model-axis all-reduce to the NEXT
        # annotation point downstream.  GSPMD is semantics-preserving —
        # the value (bias add included) is unchanged; only the reduce's
        # placement, and thus what the latency-hiding scheduler can
        # overlap it with, moves.  See collective.set_overlap_schedule.
        defer = bool(get_overlap_schedule().get("defer_row_reduce"))
        if str(jnp.asarray(self.weight).dtype) in _QUANT_DTYPES:
            y = _quantized_forward(self, x)
            y = _lora_leg(self, x, y)
            return y if defer else constrain(y, *([None] * y.ndim))
        y = jnp.matmul(x, jnp.asarray(self.weight))
        if not defer:
            y = constrain(y, *([None] * y.ndim))
        if self.bias is not None:
            y = y + jnp.asarray(self.bias)
        return _lora_leg(self, x, y)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocabulary dim sharded over ``model``.

    ``sparse=True``: gradients flow as SelectedRows through sparse-aware
    train steps (framework/selected_rows.py) — the lazy optimizer's row
    gather/scatter is itself partitioned by GSPMD over the vocab shards, so
    the PS property (no O(vocab) work per step) holds on the sharded table
    too."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, sparse: bool = False, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.sparse = bool(sparse)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(std=0.02))
        self.weight.partition_spec = ("model", None)
        self.weight.sparse = self.sparse

    def forward(self, ids):
        if self.sparse:
            from ..framework.selected_rows import tap_lookup

            rows = tap_lookup(self.weight, self.weight.value, ids,
                              self.num_embeddings)
            if rows is not None:
                return constrain(rows, *([None] * rows.ndim))
        # gather from a vocab-sharded table: GSPMD partitions the take along
        # the sharded dim and all-reduces the partial lookups
        out = jnp.take(jnp.asarray(self.weight), jnp.asarray(ids), axis=0)
        return constrain(out, *([None] * out.ndim))

"""Worker liveness: heartbeat monitoring and hang detection.

Capability parity: HeartBeatMonitor
(reference: paddle/fluid/operators/distributed/heart_beat_monitor.h:51) —
the chief pserver tracked per-trainer beat timestamps and logged workers
whose beats went stale.  TPU-native shape: there is no RPC plane, so

* :class:`HeartBeatMonitor` is the transport-agnostic chief-side state
  machine — ``update(worker_id)`` records a beat, a daemon thread flags
  workers stale past ``timeout`` and invokes ``on_lost`` exactly once per
  outage (re-arming when the worker resumes);
* :class:`FileHeartbeat` is the single-host transport: each trainer
  touches an mtime file (``PADDLE_TPU_HEARTBEAT_FILE``), which
  :func:`paddle_tpu.distributed.parallel.watch` polls — a HUNG trainer
  (alive but not stepping, e.g. a wedged collective) is killed and
  restarted under the normal restart budget, which plain exit-code
  watching can never detect;
* multi-host pods get liveness from the jax.distributed coordination
  service at init/shutdown barriers; per-step liveness rides the same
  file transport per host, monitored by that host's watchdog.

The training loop emits beats automatically: ``Model.train_batch`` calls
:func:`maybe_beat` (cheap — one ``os.utime`` at most once a second, and a
no-op unless the env var is set).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..framework.errors import InvalidArgumentError
from ..framework.locking import OrderedLock

__all__ = ["HeartBeatMonitor", "FileHeartbeat", "maybe_beat"]

ENV_FILE = "PADDLE_TPU_HEARTBEAT_FILE"
#: the training loop throttles beats to one per this many seconds —
#: hang timeouts must comfortably exceed it (watch() enforces 2x)
BEAT_MIN_INTERVAL = 1.0


class HeartBeatMonitor:
    """Chief-side per-worker liveness tracker.

    ``update(worker_id)`` may be called from any thread (beat transport);
    the monitor thread wakes every ``interval`` seconds and calls
    ``on_lost(worker_id, age_seconds)`` for each worker whose last beat is
    older than ``timeout``.  A worker is reported lost once per outage;
    if it beats again it re-arms.  Workers that never beat are measured
    from ``start()``.
    """

    def __init__(self, workers: int, timeout: float = 60.0,
                 interval: Optional[float] = None,
                 on_lost: Optional[Callable[[int, float], None]] = None):
        if workers <= 0:
            raise InvalidArgumentError("workers must be > 0")
        if timeout <= 0:
            raise InvalidArgumentError("timeout must be > 0")
        self.workers = workers
        self.timeout = float(timeout)
        self.interval = float(interval if interval is not None
                              else max(timeout / 4, 0.05))
        self._on_lost = on_lost
        self._beats: Dict[int, float] = {}
        self._lost: Dict[int, bool] = {i: False for i in range(workers)}
        self._lock = OrderedLock("HeartBeatMonitor._lock")
        self._stop = threading.Event()
        self._stop.set()  # not running until start()
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()  # reset by start()

    def update(self, worker_id: int) -> None:
        if not 0 <= worker_id < self.workers:
            raise InvalidArgumentError(
                f"worker_id {worker_id} out of range [0, {self.workers})")
        with self._lock:
            self._beats[worker_id] = time.monotonic()
            self._lost[worker_id] = False  # re-arm after recovery

    def lost_workers(self):
        with self._lock:
            return sorted(i for i, lost in self._lost.items() if lost)

    def _sweep(self) -> None:
        now = time.monotonic()
        fire = []
        with self._lock:
            for i in range(self.workers):
                last = self._beats.get(i, self._t0)
                age = now - last
                if age > self.timeout and not self._lost[i]:
                    self._lost[i] = True
                    fire.append((i, age))
        for i, age in fire:
            if self._stop.is_set():
                # stop() raced the sweep: the lost state stays latched for
                # lost_workers(), but no callback fires after shutdown
                return
            try:
                from ..framework import monitor as _monitor
                from ..framework.logging import vlog

                _monitor.stat_add("lost_workers")
                vlog(0, "heartbeat: worker %d lost (no beat for %.1fs)",
                     i, age)
            except Exception:  # noqa: BLE001 — reporting must not kill
                pass           # the monitor thread
            if self._on_lost is not None:
                try:
                    self._on_lost(i, age)
                except Exception:  # noqa: BLE001
                    # the lost state stays latched (lost_workers() reports
                    # it); record the callback failure instead of dying
                    import traceback

                    traceback.print_exc()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sweep()
            # Event.wait, not time.sleep: stop() interrupts the pause
            # immediately instead of blocking shutdown for up to a full
            # sweep interval
            self._stop.wait(self.interval)

    def start(self) -> "HeartBeatMonitor":
        self._t0 = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="heartbeat-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None


class FileHeartbeat:
    """Trainer-side beat writer: touches ``path``'s mtime.  The watchdog
    reads the mtime — no content parsing, atomic on every filesystem."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.beat()

    def beat(self) -> None:
        try:
            self._write()
        except OSError:
            # liveness is a side channel: a pruned tempdir or full disk
            # must never abort the training step it monitors
            d = os.path.dirname(self.path)
            try:
                if d:
                    os.makedirs(d, exist_ok=True)
                self._write()
            except OSError:
                # still suppressed, but COUNTED: a dead heartbeat disk
                # otherwise surfaces only as a mystery hang-kill minutes
                # later — the counter names the real failure
                from ..framework import monitor as _monitor

                _monitor.stat_add("heartbeat_write_failures")

    def _write(self) -> None:
        # append a byte so st_size changes too: on filesystems with coarse
        # mtime granularity a beat landing in the same timestamp quantum as
        # the watchdog's initial stamp would otherwise be invisible.  Reset
        # before the file grows meaningfully (truncation is itself a size
        # change, so no beat is ever silent).
        try:
            if os.stat(self.path).st_size > 4096:
                with open(self.path, "w"):
                    pass
                return
        except OSError:
            pass
        with open(self.path, "a") as f:
            f.write(".")
        os.utime(self.path, None)

    def age(self) -> float:
        try:
            return time.time() - os.stat(self.path).st_mtime
        except OSError:
            return float("inf")


_last_beat = 0.0
_writer: Optional[FileHeartbeat] = None
_beat_lock = OrderedLock("heartbeat._beat_lock")


def maybe_beat(min_interval: float = BEAT_MIN_INTERVAL) -> None:
    """Touch the heartbeat file named by ``PADDLE_TPU_HEARTBEAT_FILE`` at
    most once per ``min_interval`` seconds; no-op when unset.  Called from
    the training loop (Model.train_batch) and the serving router's health
    sweep — safe for concurrent callers: writer construction and the
    last-beat stamp mutate under a lock, and a caller that finds another
    thread mid-beat simply skips (that beat covers it) instead of
    blocking its step behind a second disk write."""
    global _last_beat, _writer
    path = os.environ.get(ENV_FILE)
    if not path:
        return
    if time.monotonic() - _last_beat < min_interval:
        return  # unlocked fast path: a stale read only costs one acquire
    if not _beat_lock.acquire(blocking=False):
        return  # another thread is beating right now — its beat covers us
    try:
        now = time.monotonic()
        if now - _last_beat < min_interval:
            return
        if _writer is None or _writer.path != path:
            _writer = FileHeartbeat(path)
        else:
            _writer.beat()
        _last_beat = now
    finally:
        _beat_lock.release()

"""Worker liveness: heartbeat monitoring and hang detection.

Capability parity: HeartBeatMonitor
(reference: paddle/fluid/operators/distributed/heart_beat_monitor.h:51) —
the chief pserver tracked per-trainer beat timestamps and logged workers
whose beats went stale.  TPU-native shape: there is no RPC plane, so

* :class:`HeartBeatMonitor` is the transport-agnostic chief-side state
  machine — ``update(worker_id)`` records a beat, a daemon thread flags
  workers stale past ``timeout`` and invokes ``on_lost`` exactly once per
  outage (re-arming when the worker resumes);
* :class:`FileHeartbeat` is the single-host transport: each trainer
  touches an mtime file (``PADDLE_TPU_HEARTBEAT_FILE``), which
  :func:`paddle_tpu.distributed.parallel.watch` polls — a HUNG trainer
  (alive but not stepping, e.g. a wedged collective) is killed and
  restarted under the normal restart budget, which plain exit-code
  watching can never detect;
* multi-host pods get liveness from the jax.distributed coordination
  service at init/shutdown barriers; per-step liveness rides the same
  file transport per host, monitored by that host's watchdog.

The training loop emits beats automatically: ``Model.train_batch`` calls
:func:`maybe_beat` (cheap — one ``os.utime`` at most once a second, and a
no-op unless the env var is set).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..framework.errors import InvalidArgumentError
from ..framework.locking import OrderedLock

__all__ = ["HeartBeatMonitor", "FileHeartbeat", "PeerHeartbeatMonitor",
           "maybe_beat", "gang_beat_path"]

ENV_FILE = "PADDLE_TPU_HEARTBEAT_FILE"
#: the training loop throttles beats to one per this many seconds —
#: hang timeouts must comfortably exceed it (watch() enforces 2x)
BEAT_MIN_INTERVAL = 1.0


class HeartBeatMonitor:
    """Chief-side per-worker liveness tracker.

    ``update(worker_id)`` may be called from any thread (beat transport);
    the monitor thread wakes every ``interval`` seconds and calls
    ``on_lost(worker_id, age_seconds)`` for each worker whose last beat is
    older than ``timeout``.  A worker is reported lost once per outage;
    if it beats again it re-arms.  Workers that never beat are measured
    from ``start()`` against ``grace`` (default: ``timeout``) — cross-host
    gangs set a generous grace so slow interpreter/backend startup on a
    peer isn't mistaken for a death.

    Clock-skew tolerance: staleness is always measured on THIS host's
    monotonic clock against the moment this host *observed* the worker's
    beat — remote timestamps are never compared against local wall clock.
    Transports that can only see a remote stamp (an mtime written by
    another host) feed :meth:`update_stamp`, which records a local
    observation time whenever the stamp *changes*; a peer whose clock
    runs minutes ahead or behind is still exactly as live as its latest
    beat delta.
    """

    def __init__(self, workers: int, timeout: float = 60.0,
                 interval: Optional[float] = None,
                 on_lost: Optional[Callable[[int, float], None]] = None,
                 grace: Optional[float] = None):
        if workers <= 0:
            raise InvalidArgumentError("workers must be > 0")
        if timeout <= 0:
            raise InvalidArgumentError("timeout must be > 0")
        if grace is not None and grace < 0:
            raise InvalidArgumentError("grace must be >= 0")
        self.workers = workers
        self.timeout = float(timeout)
        self.grace = float(grace) if grace is not None else self.timeout
        self.interval = float(interval if interval is not None
                              else max(timeout / 4, 0.05))
        self._on_lost = on_lost
        self._beats: Dict[int, float] = {}
        self._stamps: Dict[int, object] = {}
        self._lost: Dict[int, bool] = {i: False for i in range(workers)}
        self._lock = OrderedLock("HeartBeatMonitor._lock")
        self._stop = threading.Event()
        self._stop.set()  # not running until start()
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()  # reset by start()

    def update(self, worker_id: int) -> None:
        if not 0 <= worker_id < self.workers:
            raise InvalidArgumentError(
                f"worker_id {worker_id} out of range [0, {self.workers})")
        with self._lock:
            self._beats[worker_id] = time.monotonic()
            self._lost[worker_id] = False  # re-arm after recovery

    def update_stamp(self, worker_id: int, stamp) -> None:
        """Record a beat iff ``stamp`` differs from the worker's previous
        stamp.  ``stamp`` is opaque (an ``(mtime, size)`` pair, a sequence
        number...) and is only ever compared for EQUALITY against the same
        worker's prior value — never against this host's clock — which is
        what makes ``lost_workers()`` immune to cross-host clock skew."""
        if not 0 <= worker_id < self.workers:
            raise InvalidArgumentError(
                f"worker_id {worker_id} out of range [0, {self.workers})")
        with self._lock:
            if self._stamps.get(worker_id) == stamp:
                return  # no new beat observed
            self._stamps[worker_id] = stamp
            self._beats[worker_id] = time.monotonic()
            self._lost[worker_id] = False

    def rearm(self, grace: Optional[float] = None) -> None:
        """Forget all observed beats and re-apply the startup grace —
        called after a gang restart, when every peer is expected to go
        silent while its trainer relaunches and must not be re-flagged
        as lost during the window."""
        with self._lock:
            if grace is not None:
                self.grace = float(grace)
            self._beats.clear()
            self._stamps.clear()
            for i in self._lost:
                self._lost[i] = False
            self._t0 = time.monotonic()

    def lost_workers(self):
        with self._lock:
            return sorted(i for i, lost in self._lost.items() if lost)

    def _sweep(self) -> None:
        now = time.monotonic()
        fire = []
        with self._lock:
            for i in range(self.workers):
                last = self._beats.get(i)
                if last is None:  # never beaten: measured against grace
                    last, limit = self._t0, self.grace
                else:
                    limit = self.timeout
                age = now - last
                if age > limit and not self._lost[i]:
                    self._lost[i] = True
                    fire.append((i, age))
        for i, age in fire:
            if self._stop.is_set():
                # stop() raced the sweep: the lost state stays latched for
                # lost_workers(), but no callback fires after shutdown
                return
            try:
                from ..framework import monitor as _monitor
                from ..framework.logging import vlog

                _monitor.stat_add("lost_workers")
                vlog(0, "heartbeat: worker %d lost (no beat for %.1fs)",
                     i, age)
            except Exception:  # noqa: BLE001 — reporting must not kill
                pass           # the monitor thread
            if self._on_lost is not None:
                try:
                    self._on_lost(i, age)
                except Exception:  # noqa: BLE001
                    # the lost state stays latched (lost_workers() reports
                    # it); record the callback failure instead of dying
                    import traceback

                    traceback.print_exc()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sweep()
            # Event.wait, not time.sleep: stop() interrupts the pause
            # immediately instead of blocking shutdown for up to a full
            # sweep interval
            self._stop.wait(self.interval)

    def start(self) -> "HeartBeatMonitor":
        self._t0 = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="heartbeat-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None


class FileHeartbeat:
    """Trainer-side beat writer: touches ``path``'s mtime.  The watchdog
    reads the mtime — no content parsing, atomic on every filesystem."""

    def __init__(self, path: str, touch: bool = True):
        # touch=False: adopt the path without stamping it — used by the
        # gang watchdog, where ONLY the trainer's own beats may refresh
        # the file (a watchdog stamp would make peers think the trainer
        # is alive while it is still relaunching)
        self.path = path
        d = os.path.dirname(path)
        try:
            if d:
                os.makedirs(d, exist_ok=True)
        except OSError:
            pass  # side channel: beat() retries and counts the failure
        if touch:
            self.beat()

    def beat(self) -> None:
        try:
            self._write()
        except OSError:
            # liveness is a side channel: a pruned tempdir or full disk
            # must never abort the training step it monitors
            d = os.path.dirname(self.path)
            try:
                if d:
                    os.makedirs(d, exist_ok=True)
                self._write()
            except OSError:
                # still suppressed, but COUNTED: a dead heartbeat disk
                # otherwise surfaces only as a mystery hang-kill minutes
                # later — the counter names the real failure
                from ..framework import monitor as _monitor

                _monitor.stat_add("heartbeat_write_failures")

    def _write(self) -> None:
        # append a byte so st_size changes too: on filesystems with coarse
        # mtime granularity a beat landing in the same timestamp quantum as
        # the watchdog's initial stamp would otherwise be invisible.  Reset
        # before the file grows meaningfully (truncation is itself a size
        # change, so no beat is ever silent).
        try:
            if os.stat(self.path).st_size > 4096:
                with open(self.path, "w"):
                    pass
                return
        except OSError:
            pass
        with open(self.path, "a") as f:
            f.write(".")
        os.utime(self.path, None)

    def age(self) -> float:
        try:
            return time.time() - os.stat(self.path).st_mtime
        except OSError:
            return float("inf")


_last_beat = 0.0
_writer: Optional[FileHeartbeat] = None
_beat_lock = OrderedLock("heartbeat._beat_lock")


def maybe_beat(min_interval: float = BEAT_MIN_INTERVAL) -> None:
    """Touch the heartbeat file named by ``PADDLE_TPU_HEARTBEAT_FILE`` at
    most once per ``min_interval`` seconds; no-op when unset.  Called from
    the training loop (Model.train_batch) and the serving router's health
    sweep — safe for concurrent callers: writer construction and the
    last-beat stamp mutate under a lock, and a caller that finds another
    thread mid-beat simply skips (that beat covers it) instead of
    blocking its step behind a second disk write."""
    global _last_beat, _writer
    path = os.environ.get(ENV_FILE)
    if not path:
        return
    if time.monotonic() - _last_beat < min_interval:
        return  # unlocked fast path: a stale read only costs one acquire
    if not _beat_lock.acquire(blocking=False):
        return  # another thread is beating right now — its beat covers us
    try:
        now = time.monotonic()
        if now - _last_beat < min_interval:
            return
        if _writer is None or _writer.path != path:
            _writer = FileHeartbeat(path)
        else:
            _writer.beat()
        _last_beat = now
    finally:
        _beat_lock.release()


def gang_beat_path(gang_dir: str, rank: int) -> str:
    """The per-rank beat file inside a shared gang directory — rank ``r``
    writes ``beat.p<r>``; every peer's watchdog reads all of them."""
    return os.path.join(gang_dir, f"beat.p{int(rank)}")


class PeerHeartbeatMonitor:
    """Cross-host liveness: every rank's watchdog reads every OTHER rank's
    beat file from the shared gang directory and feeds stamp changes into
    a :class:`HeartBeatMonitor`.

    The transport is deliberately dumb — each trainer appends to its own
    ``beat.p<rank>`` (the existing :class:`FileHeartbeat` writer, pointed
    into the gang dir) — and the reader side never interprets remote
    mtimes as times: a beat is "the ``(mtime, size)`` stamp changed since
    I last looked", timed on the local monotonic clock via
    :meth:`HeartBeatMonitor.update_stamp`.  NFS-grade semantics (close-to
    -open consistency, coarse mtime) are enough, and cross-host clock skew
    is irrelevant by construction.

    ``self_rank`` is exempt: this watchdog supervises its own trainer
    through the hang detector; the peer monitor only answers "did someone
    ELSE's host die", so ``lost_workers()`` never contains ``self_rank``.
    """

    def __init__(self, gang_dir: str, world: int, self_rank: int,
                 timeout: float = 10.0, interval: Optional[float] = None,
                 grace: Optional[float] = None,
                 on_lost: Optional[Callable[[int, float], None]] = None):
        if not 0 <= self_rank < world:
            raise InvalidArgumentError(
                f"self_rank {self_rank} out of range [0, {world})")
        self.gang_dir = gang_dir
        self.world = int(world)
        self.self_rank = int(self_rank)
        self._mon = HeartBeatMonitor(
            workers=world, timeout=timeout, interval=interval,
            grace=grace if grace is not None else max(30.0, 3 * timeout),
            on_lost=on_lost)
        self._poll = self._mon.interval
        self._stop = threading.Event()
        self._stop.set()
        self._thread: Optional[threading.Thread] = None

    def _scan(self) -> None:
        self._mon.update(self.self_rank)  # self is alive by definition
        for r in range(self.world):
            if r == self.self_rank:
                continue
            try:
                st = os.stat(gang_beat_path(self.gang_dir, r))
            except OSError:
                continue  # not written yet / mid-replace: no new beat
            self._mon.update_stamp(r, (st.st_mtime, st.st_size))

    def _run(self) -> None:
        while not self._stop.is_set():
            self._scan()
            self._stop.wait(self._poll)

    def start(self) -> "PeerHeartbeatMonitor":
        self._mon.start()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gang-peer-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll + 1)
            self._thread = None
        self._mon.stop()

    def rearm(self, grace: Optional[float] = None) -> None:
        self._mon.rearm(grace)

    def lost_workers(self):
        return [r for r in self._mon.lost_workers() if r != self.self_rank]

"""Host-level gang collectives — the control lane of a multi-host pod.

The *data plane* of a pod (gradient allreduce over chips) rides XLA
collectives through :mod:`paddle_tpu.distributed.collective` over
ICI/DCN.  But a pod also needs a *host lane*: small host-resident values
exchanged between the one-process-per-host gang members — checkpoint
counters to negotiate a gang-consistent resume point, per-host gradient
or parameter trees on backends whose XLA cannot span processes (the CPU
backend joins the coordination service fine but refuses cross-process
computations), barriers around save/restore, membership handshakes after
an elastic gang restart.  That lane is this module.

Two transports:

* :class:`FileTransport` — a directory shared by all ranks
  (``PADDLE_TPU_GANG_DIR``).  Atomic per-rank files (write tmp +
  ``os.replace``), NFS-grade semantics suffice.  This is how the CPU
  pod smoke runs N *real* processes, and works on any pod with a shared
  filesystem.
* :class:`KVStoreTransport` — the JAX coordination-service key-value
  store (available once ``jax.distributed.initialize`` joined); the
  zero-extra-infrastructure production option.

Determinism: gathers return contributions in **rank order** and
reductions fold in rank order, so every rank computes bit-identical
results — and a single-process run folding the same per-shard values in
the same order reproduces them exactly (the pod smoke's bit-identity
gates are built on this).

Failure: every blocking op runs under the ``FLAGS_collective_timeout_s``
watchdog contract — a dead peer raises :class:`TransientDeviceError`
naming the missing ranks instead of hanging the gang, bumping the same
``collective_watchdog_trips`` counters as the XLA-side watchdog.  The
``fault_point("gang.collective")`` seam lets chaos plans wedge or fail
individual ops.

Restart-safety: all op keys are namespaced by a **generation** digest
negotiated at :meth:`Gang.join` from fresh per-incarnation nonces.  After
a gang restart every member rejoins, the generation changes, and stale
files written by the previous incarnation can never satisfy (or corrupt)
a new collective.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import time
from typing import Callable, Dict, List, Optional

from ..framework.errors import InvalidArgumentError, TransientDeviceError

__all__ = ["Gang", "FileTransport", "KVStoreTransport", "default_gang",
           "current_gang", "set_gang"]

_POLL_S = 0.01


class FileTransport:
    """Shared-directory transport: ``put`` is atomic (tmp + rename) so a
    reader never observes a torn value; keys map to flat file names."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace(os.sep, "_"))

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass


class KVStoreTransport:
    """The jax.distributed coordination-service KV store.  Values are
    hex-encoded (the store speaks strings).  Only usable after
    ``init_parallel_env`` joined the coordinator; deletes are no-ops (the
    store dies with the coordinator, and generations already fence stale
    keys)."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed as _jd

            client = getattr(_jd.global_state, "client", None)
        if client is None:
            raise InvalidArgumentError(
                "KVStoreTransport needs a joined jax.distributed client — "
                "call init_parallel_env() first")
        self._client = client

    def put(self, key: str, value: bytes) -> None:
        self._client.key_value_set(key, value.hex())

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            raw = self._client.blocking_key_value_get(key, 1)  # 1 ms
        except Exception:  # noqa: BLE001 — "not there yet" surfaces as
            return None    # a backend-specific error; the caller polls
        return bytes.fromhex(raw)

    def delete(self, key: str) -> None:
        pass


class Gang:
    """A joined set of host processes exchanging small values.

    All collectives are synchronous and deterministic; ``world == 1``
    degenerates to local no-ops (gather returns ``[x]``), so trainer code
    is identical on one host and on a pod.
    """

    def __init__(self, rank: int, world: int, transport=None,
                 name: str = "gang", default_timeout: Optional[float] = None,
                 heartbeat: Optional[Callable[[], None]] = None):
        if world < 1:
            raise InvalidArgumentError("world must be >= 1")
        if not 0 <= rank < world:
            raise InvalidArgumentError(
                f"rank {rank} out of range [0, {world})")
        if world > 1 and transport is None:
            raise InvalidArgumentError("world > 1 needs a transport")
        self.rank = int(rank)
        self.world = int(world)
        self.transport = transport
        self.name = name
        self.default_timeout = default_timeout
        self.generation = "solo" if world == 1 else None
        self._seq = 0
        self._nonces: Dict[int, str] = {}  # joined incarnations, by rank
        self._written: Dict[int, List[str]] = {}
        self._stats = {"ops": 0, "timeouts": 0, "joins": 0}
        if heartbeat is None:
            from .heartbeat import maybe_beat

            heartbeat = maybe_beat
        self._beat = heartbeat

    # -- plumbing ---------------------------------------------------------

    def _timeout(self, timeout: Optional[float]) -> float:
        if timeout is not None:
            return float(timeout)
        from ..framework.flags import flag

        configured = float(flag("collective_timeout_s") or 0.0)
        if configured > 0:
            return configured
        if self.default_timeout is not None:
            return float(self.default_timeout)
        return 600.0

    def _publish(self, extra: Optional[dict] = None) -> None:
        from ..framework import trace_events

        if not trace_events.active():
            return
        info = {"rank": self.rank, "world": self.world,
                "generation": self.generation, **self._stats}
        if extra:
            info.update(extra)
        trace_events.notify(("gang", self.name), info)

    def _trip(self, what: str, timeout: float, missing: List[int]):
        from ..framework import monitor as _monitor
        from ..framework.logging import vlog
        from ..resilience import supervisor as _supervisor

        self._stats["timeouts"] += 1
        _monitor.stat_add("collective_watchdog_trips")
        _supervisor.record("watchdog_trips")
        vlog(0, "gang %s: %s timed out after %.1fs waiting for rank(s) %s",
             self.name, what, timeout, missing)
        self._publish({"last_timeout_op": what})
        raise TransientDeviceError(
            f"gang collective {what!r} timed out after {timeout:g}s "
            f"waiting for rank(s) {missing} — peer dead or wedged "
            "(FLAGS_collective_timeout_s watchdog)")

    def _check_reincarnation(self, what: str) -> None:
        """A peer whose join nonce changed has restarted and abandoned
        this generation — the collective we are blocked in can NEVER
        complete (the new incarnation will only ever speak the next
        generation), so fail fast instead of waiting out the watchdog.
        This is what breaks the fast-restart livelock: a SIGKILLed host
        that relaunches within the peer-heartbeat timeout never looks
        lost to any watchdog, yet its old generation is dead."""
        if not self._nonces:
            return
        for r in range(self.world):
            if r == self.rank:
                continue
            raw = self.transport.try_get(f"join.p{r}")
            if raw is None or raw.decode() == self._nonces.get(r):
                continue
            from ..framework import monitor as _monitor
            from ..framework.logging import vlog

            _monitor.stat_add("gang_reincarnations")
            vlog(0, "gang %s: rank %d reincarnated mid-%s — generation "
                    "%s is abandoned", self.name, r, what, self.generation)
            self._publish({"reincarnated_rank": r})
            raise TransientDeviceError(
                f"gang peer rank {r} restarted while {what!r} was in "
                f"flight — generation {self.generation} is abandoned; "
                f"rejoin the gang (exit GANG_RESTART_EXIT_CODE under a "
                f"watchdog)")

    def _await_keys(self, keys: Dict[int, str], what: str,
                    timeout: float) -> Dict[int, bytes]:
        deadline = time.monotonic() + timeout
        got: Dict[int, bytes] = {}
        polls = 0
        while True:
            for r, key in keys.items():
                if r in got:
                    continue
                val = self.transport.try_get(key)
                if val is not None:
                    got[r] = val
            if len(got) == len(keys):
                return got
            if time.monotonic() > deadline:
                self._trip(what, timeout, sorted(set(keys) - set(got)))
            polls += 1
            if polls % 25 == 0:  # ~4x/s: reincarnation fencing
                self._check_reincarnation(what)
            self._beat()  # blocked-in-collective is alive, not hung
            time.sleep(_POLL_S)

    # -- membership -------------------------------------------------------

    def join(self, timeout: Optional[float] = None) -> str:
        """Handshake a fresh generation with every peer; returns the
        generation id.  Each incarnation contributes a fresh nonce; the
        generation is a digest over all nonces, and members ack the digest
        they computed — convergence means every member saw the same set of
        live incarnations.  A peer restarting mid-join changes its nonce,
        digests diverge, and everyone re-reads until stable: the handshake
        is self-healing across elastic restarts."""
        self._stats["joins"] += 1
        if self.world == 1:
            self.generation = "solo"
            return self.generation
        from ..resilience.faults import fault_point

        fault_point("gang.join")
        timeout = self._timeout(timeout)
        deadline = time.monotonic() + timeout
        nonce = os.urandom(8).hex()
        self.transport.put(f"join.p{self.rank}", nonce.encode())
        digest = None
        while True:
            nonces = {}
            for r in range(self.world):
                raw = self.transport.try_get(f"join.p{r}")
                if raw is not None:
                    nonces[r] = raw.decode()
            if len(nonces) == self.world and nonces[self.rank] == nonce:
                material = ",".join(f"{r}:{nonces[r]}"
                                    for r in range(self.world))
                d = hashlib.sha256(material.encode()).hexdigest()[:16]
                if d != digest:
                    digest = d
                    self.transport.put(f"ack.p{self.rank}", digest.encode())
                acks = [self.transport.try_get(f"ack.p{r}")
                        for r in range(self.world)]
                if all(a is not None and a.decode() == digest
                       for a in acks):
                    break
            if time.monotonic() > deadline:
                missing = sorted(set(range(self.world)) - set(nonces))
                self._trip("join", timeout, missing or
                           list(range(self.world)))
            self._beat()
            time.sleep(_POLL_S)
        self.generation = digest
        self._nonces = dict(nonces)  # the incarnations this gen speaks for
        self._seq = 0
        self._written.clear()
        self._publish({"joined": 1})
        return self.generation

    # -- collectives ------------------------------------------------------

    def all_gather_bytes(self, data: bytes,
                         timeout: Optional[float] = None) -> List[bytes]:
        """Every rank contributes ``data``; returns all contributions in
        rank order on every rank."""
        if self.world == 1:
            return [data]
        if self.generation is None:
            raise InvalidArgumentError("gang not joined — call join()")
        from ..resilience.faults import fault_point

        fault_point("gang.collective")
        timeout = self._timeout(timeout)
        seq = self._seq
        self._seq += 1
        self._stats["ops"] += 1
        key = f"op.{self.generation}.{seq}"
        self.transport.put(f"{key}.p{self.rank}", data)
        self._written.setdefault(seq, []).append(f"{key}.p{self.rank}")
        got = self._await_keys(
            {r: f"{key}.p{r}" for r in range(self.world)},
            f"all_gather[{seq}]", timeout)
        self._gc(seq)
        return [got[r] for r in range(self.world)]

    def _gc(self, seq: int) -> None:
        # every rank observed at seq means every rank finished seq-1 and
        # earlier (ops are issued in order), so our own files a few seqs
        # back can never be read again
        for s in [s for s in self._written if s < seq - 2]:
            for key in self._written.pop(s):
                self.transport.delete(key)

    def all_gather_obj(self, obj, timeout: Optional[float] = None) -> list:
        return [pickle.loads(b) for b in
                self.all_gather_bytes(pickle.dumps(obj), timeout)]

    def barrier(self, timeout: Optional[float] = None) -> None:
        self.all_gather_bytes(b"", timeout)

    def broadcast_obj(self, obj=None, src: int = 0,
                      timeout: Optional[float] = None):
        """Rank ``src``'s object lands on every rank (others pass any
        placeholder)."""
        return self.all_gather_obj(obj, timeout)[src]

    def min_int(self, value: int, timeout: Optional[float] = None) -> int:
        """The gang-wide minimum — the checkpoint-counter negotiation
        primitive for gang-consistent resume."""
        return min(self.all_gather_obj(int(value), timeout))

    def all_reduce_mean_tree(self, tree, timeout: Optional[float] = None):
        """Mean of a pytree of numpy arrays across ranks, folded in rank
        order — bit-identical on every rank, and bit-identical to a
        single process folding the same per-rank trees in the same order
        (see :func:`mean_trees`)."""
        contributions = self.all_gather_obj(tree, timeout)
        return mean_trees(contributions)


def mean_trees(trees: list):
    """Rank-ordered mean of pytrees of numpy arrays — THE reduction both
    the gang and the single-process baseline use, so pod and solo runs
    agree bitwise.  Left-fold in list order; no pairwise reassociation."""
    import jax
    import numpy as np

    def _mean(*leaves):
        acc = np.asarray(leaves[0], dtype=np.float32).copy()
        for leaf in leaves[1:]:
            acc += np.asarray(leaf, dtype=np.float32)
        return acc / np.float32(len(leaves))

    return jax.tree_util.tree_map(_mean, *trees)


_gang: Optional[Gang] = None


def set_gang(gang: Optional[Gang]) -> Optional[Gang]:
    global _gang
    _gang = gang
    return gang


def current_gang() -> Optional[Gang]:
    return _gang


def default_gang(name: str = "gang") -> Gang:
    """Build (and cache) the gang described by the launch environment:
    file transport when ``PADDLE_TPU_GANG_DIR`` is wired, the KV store
    when a jax.distributed coordinator is joined, a solo gang otherwise.
    The returned gang is already :meth:`Gang.join`-ed."""
    global _gang
    if _gang is not None:
        return _gang
    from . import env as _env

    _env.init_parallel_env()
    world = _env.process_count()
    rank = _env.process_index()
    transport = None
    if world > 1:
        gang_dir = os.environ.get(_env.ENV_GANG_DIR)
        if _env.gang_transport() == "file" or (
                gang_dir and _env.gang_transport() != "jax"):
            if not gang_dir:
                raise InvalidArgumentError(
                    f"file gang transport needs {_env.ENV_GANG_DIR}")
            transport = FileTransport(os.path.join(gang_dir, "ops"))
        else:
            transport = KVStoreTransport()
    g = Gang(rank, world, transport, name=name)
    g.join()
    _gang = g
    return g

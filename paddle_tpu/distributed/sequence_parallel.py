"""Long-context sequence/context parallelism: ring attention + Ulysses.

New capability — the reference has NOTHING here (SURVEY §5 verified: no
ring attention / sequence parallel / Ulysses anywhere; its long-sequence
story was recompute + pipeline only).  Built TPU-first:

* **Ring attention** (`ring_attention`): the sequence is sharded over the
  ``sep`` mesh axis; each step every device computes blockwise attention of
  its local Q chunk against the KV chunk it currently holds, then rotates
  KV one neighbor along the ring with ``lax.ppermute`` — KV transfer rides
  ICI neighbor links and overlaps with the chunk matmuls.  Online-softmax
  (logsumexp) merging makes the result exact, not approximate.  Peak memory
  is O(S/p) per device — sequences scale linearly with ring size.
* **Ulysses** (`ulysses_attention`): all-to-all resharding seq→heads, local
  full attention per head group, all-to-all back.  Cheaper than a ring when
  num_heads ≥ ring size (two all-to-alls instead of p permutes).

Both are written for use inside ``shard_map`` (functions taking *local*
chunks + the axis name); ``*_sharded`` wrappers apply the shard_map over
the global mesh for eager/global arrays.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.errors import InvalidArgumentError
from .collective import shard_map
from .mesh import get_mesh

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "ring_attention_sharded",
    "ulysses_attention_sharded",
]


def _merge(o_a, lse_a, o_b, lse_b):
    """Numerically-stable combine of two normalized partial attentions."""
    m = jnp.maximum(lse_a, lse_b)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    wa = jnp.where(jnp.isneginf(lse_a), 0.0, jnp.exp(lse_a - m_safe))
    wb = jnp.where(jnp.isneginf(lse_b), 0.0, jnp.exp(lse_b - m_safe))
    denom = wa + wb
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o_a * wa[..., None] + o_b * wb[..., None]) / denom_safe[..., None]
    lse = m + jnp.log(denom_safe)
    lse = jnp.where(denom == 0.0, -jnp.inf, lse)
    return o, lse


def _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale):
    """Ring forward on the FLASH kernels: every chunk's partial attention
    is a Pallas call (O(block²) VMEM — no [S_local, S_local] score tensor
    anywhere), merged with online-softmax statistics.  Causal chunk
    dispatch (per ring step, per device):

    * step 0 (the device's own chunk): causal self-attention at offset 0 —
      this takes the TRIANGLE grid inside the kernel;
    * src < idx (chunk entirely below the diagonal): full non-causal
      attention — no masking needed at all;
    * src > idx (entirely above): the chunk contributes NOTHING — the
      lax.cond branch returns zeros/-inf without running a kernel, so its
      compute AND its kernel DMA are skipped (the ppermute still moves the
      chunk onward for the devices that do need it).
    """
    from ..ops.flash_attention import flash_attention_fwd_lse

    mesh = get_mesh()
    p = mesh.shape[axis_name]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    idx = lax.axis_index(axis_name)
    out = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    perm = [(j, (j + 1) % p) for j in range(p)]

    def full_chunk(args):
        qq, kk, vv = args
        o, l = flash_attention_fwd_lse(qq, kk, vv, causal=False,
                                       sm_scale=sm_scale)
        return o.astype(jnp.float32), l

    def skip_chunk(args):
        qq = args[0]
        return (jnp.zeros(qq.shape, jnp.float32),
                jnp.full(qq.shape[:3], -jnp.inf, jnp.float32))

    kc, vc = k, v
    for step in range(p):
        if step == 0:
            o_i, lse_i = flash_attention_fwd_lse(
                q, kc, vc, causal=causal, sm_scale=sm_scale)
            o_i = o_i.astype(jnp.float32)
        elif causal:
            src = (idx - step) % p  # the global chunk currently held
            o_i, lse_i = lax.cond(src < idx, full_chunk, skip_chunk,
                                  (q, kc, vc))
        else:
            o_i, lse_i = full_chunk((q, kc, vc))
        out, lse = _merge(out, lse, o_i, lse_i)
        if step + 1 < p:
            # rotate KV around the ring (ICI neighbor transfer)
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, sm_scale):
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, sm_scale):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, sm_scale, res, do):
    """Ring backward, also on the flash kernels: with the GLOBAL merged
    (out, lse) per q row, each (q, kv-chunk) pair's flash-2 backward is an
    exact additive contribution (p = exp(s − lse_global) is linear over
    chunks).  dk/dv accumulators travel the ring WITH their kv chunk; a
    final ppermute delivers them to the chunk's owner."""
    from ..ops.flash_attention import flash_attention_bwd_chunk

    q, k, v, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    mesh = get_mesh()
    p = mesh.shape[axis_name]
    idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p) for j in range(p)]
    do = do.astype(jnp.float32)
    # loop-invariant: computed once, reused by every ring step's kernel
    delta = (do * out.astype(jnp.float32)).sum(-1)

    def full_bwd(args):
        qq, kk, vv = args
        dq_i, dk_i, dv_i = flash_attention_bwd_chunk(
            qq, kk, vv, out, lse, do, causal=False, sm_scale=sm_scale,
            delta=delta)
        return (dq_i.astype(jnp.float32), dk_i.astype(jnp.float32),
                dv_i.astype(jnp.float32))

    def skip_bwd(args):
        qq, kk, vv = args
        return (jnp.zeros(qq.shape, jnp.float32),
                jnp.zeros(kk.shape, jnp.float32),
                jnp.zeros(vv.shape, jnp.float32))

    dq = jnp.zeros(q.shape, jnp.float32)
    kc, vc = k, v
    dkc = jnp.zeros(k.shape, jnp.float32)
    dvc = jnp.zeros(v.shape, jnp.float32)
    for step in range(p):
        if step == 0:
            dq_i, dk_i, dv_i = flash_attention_bwd_chunk(
                q, kc, vc, out, lse, do, causal=causal, sm_scale=sm_scale,
                delta=delta)
            dq_i, dk_i, dv_i = (x.astype(jnp.float32)
                                for x in (dq_i, dk_i, dv_i))
        elif causal:
            src = (idx - step) % p
            dq_i, dk_i, dv_i = lax.cond(src < idx, full_bwd, skip_bwd,
                                        (q, kc, vc))
        else:
            dq_i, dk_i, dv_i = full_bwd((q, kc, vc))
        dq = dq + dq_i
        dkc = dkc + dk_i
        dvc = dvc + dv_i
        if step + 1 < p:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            dkc = lax.ppermute(dkc, axis_name, perm)
            dvc = lax.ppermute(dvc, axis_name, perm)
    # after p-1 rotations device i holds chunk (i+1) % p; one more step
    # forward delivers each dk/dv to its chunk's owner
    dkc = lax.ppermute(dkc, axis_name, perm)
    dvc = lax.ppermute(dvc, axis_name, perm)
    return dq.astype(q.dtype), dkc.astype(k.dtype), dvc.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Exact attention over a sequence sharded on ``axis_name``, every
    chunk computed by the Pallas flash kernel (fwd AND bwd — see
    _ring_fwd_impl/_ring_flash_bwd; no O(S_local²) score tensor exists).

    Call INSIDE shard_map; q/k/v are the local chunks [B, H, S_local, D].
    """
    mesh = get_mesh()
    p = mesh.shape[axis_name]
    if p == 1:
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return _ring_flash(q, k, v, axis_name, causal,
                       None if sm_scale is None else float(sm_scale))


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      sm_scale: Optional[float] = None):
    """Attention via all-to-all head resharding (DeepSpeed-Ulysses style).

    Call INSIDE shard_map; q/k/v local [B, H, S_local, D] with H divisible
    by the axis size.  After the first all-to-all each device holds H/p
    heads × the FULL sequence; local attention is exact; the second
    all-to-all restores seq sharding.
    """
    mesh = get_mesh()
    p = mesh.shape[axis_name]
    if q.shape[1] % p:
        raise InvalidArgumentError(
            f"num_heads {q.shape[1]} not divisible by axis {axis_name!r} "
            f"size {p}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    def reshard_in(x):  # [B, H, S/p, D] → [B, H/p, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    from ..ops.flash_attention import flash_attention

    q2, k2, v2 = reshard_in(q), reshard_in(k), reshard_in(v)
    # local full-sequence attention on the Pallas flash kernel (fwd+bwd):
    # the custom_vjp composes with the surrounding all_to_alls under grad
    o2 = flash_attention(q2, k2, v2, causal=causal, sm_scale=sm_scale)
    return lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _sharded(fn, q, k, v, axis, causal, sm_scale):
    mesh = get_mesh()
    spec = P(None, None, axis, None)

    def local(ql, kl, vl):
        return fn(ql, kl, vl, axis_name=axis, causal=causal, sm_scale=sm_scale)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def ring_attention_sharded(q, k, v, axis: str = "sep", causal: bool = False,
                           sm_scale: Optional[float] = None):
    """Global-array convenience wrapper: q/k/v [B, H, S, D] sharded (or
    shardable) over ``axis`` on dim 2."""
    return _sharded(ring_attention, q, k, v, axis, causal, sm_scale)


def ulysses_attention_sharded(q, k, v, axis: str = "sep", causal: bool = False,
                              sm_scale: Optional[float] = None):
    return _sharded(ulysses_attention, q, k, v, axis, causal, sm_scale)

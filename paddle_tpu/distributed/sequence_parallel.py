"""Long-context sequence/context parallelism: ring attention + Ulysses.

New capability — the reference has NOTHING here (SURVEY §5 verified: no
ring attention / sequence parallel / Ulysses anywhere; its long-sequence
story was recompute + pipeline only).  Built TPU-first:

* **Ring attention** (`ring_attention`): the sequence is sharded over the
  ``sep`` mesh axis; each step every device computes blockwise attention of
  its local Q chunk against the KV chunk it currently holds, then rotates
  KV one neighbor along the ring with ``lax.ppermute`` — KV transfer rides
  ICI neighbor links and overlaps with the chunk matmuls.  Online-softmax
  (logsumexp) merging makes the result exact, not approximate.  Peak memory
  is O(S/p) per device — sequences scale linearly with ring size.
* **Ulysses** (`ulysses_attention`): all-to-all resharding seq→heads, local
  full attention per head group, all-to-all back.  Cheaper than a ring when
  num_heads ≥ ring size (two all-to-alls instead of p permutes).

Both are written for use inside ``shard_map`` (functions taking *local*
chunks + the axis name); ``*_sharded`` wrappers apply the shard_map over
the global mesh for eager/global arrays.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.errors import InvalidArgumentError
from .collective import shard_map
from .mesh import get_mesh

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "ring_attention_sharded",
    "ulysses_attention_sharded",
]


def _chunk_attn_lse(q, k, v, sm_scale, causal, q_offset, k_offset):
    """Local-chunk attention returning (out, lse); fully-masked rows give
    out=0, lse=-inf so the ring merge ignores them."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * sm_scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])
        k_pos = k_offset + jnp.arange(k.shape[2])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, -jnp.inf)
    m = s.max(axis=-1)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    l = p.sum(axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = out / l_safe[..., None]
    lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(l_safe))
    return out, lse


def _merge(o_a, lse_a, o_b, lse_b):
    """Numerically-stable combine of two normalized partial attentions."""
    m = jnp.maximum(lse_a, lse_b)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    wa = jnp.where(jnp.isneginf(lse_a), 0.0, jnp.exp(lse_a - m_safe))
    wb = jnp.where(jnp.isneginf(lse_b), 0.0, jnp.exp(lse_b - m_safe))
    denom = wa + wb
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o_a * wa[..., None] + o_b * wb[..., None]) / denom_safe[..., None]
    lse = m + jnp.log(denom_safe)
    lse = jnp.where(denom == 0.0, -jnp.inf, lse)
    return o, lse


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Exact attention over a sequence sharded on ``axis_name``.

    Call INSIDE shard_map; q/k/v are the local chunks [B, H, S_local, D].
    """
    mesh = get_mesh()
    p = mesh.shape[axis_name]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_offset = idx * s_local

    out = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    perm = [(j, (j + 1) % p) for j in range(p)]

    kc, vc = k, v
    for step in range(p):
        src = (idx - step) % p  # the global chunk currently held
        o_i, lse_i = _chunk_attn_lse(
            q, kc, vc, sm_scale, causal, q_offset, src * k.shape[2])
        out, lse = _merge(out, lse, o_i, lse_i)
        if step + 1 < p:
            # rotate KV around the ring (ICI neighbor transfer)
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      sm_scale: Optional[float] = None):
    """Attention via all-to-all head resharding (DeepSpeed-Ulysses style).

    Call INSIDE shard_map; q/k/v local [B, H, S_local, D] with H divisible
    by the axis size.  After the first all-to-all each device holds H/p
    heads × the FULL sequence; local attention is exact; the second
    all-to-all restores seq sharding.
    """
    mesh = get_mesh()
    p = mesh.shape[axis_name]
    if q.shape[1] % p:
        raise InvalidArgumentError(
            f"num_heads {q.shape[1]} not divisible by axis {axis_name!r} "
            f"size {p}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    def reshard_in(x):  # [B, H, S/p, D] → [B, H/p, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    q2, k2, v2 = reshard_in(q), reshard_in(k), reshard_in(v)
    o2, _ = _chunk_attn_lse(q2, k2, v2, sm_scale, causal, 0, 0)
    o2 = o2.astype(q.dtype)
    return lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _sharded(fn, q, k, v, axis, causal, sm_scale):
    mesh = get_mesh()
    spec = P(None, None, axis, None)

    def local(ql, kl, vl):
        return fn(ql, kl, vl, axis_name=axis, causal=causal, sm_scale=sm_scale)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def ring_attention_sharded(q, k, v, axis: str = "sep", causal: bool = False,
                           sm_scale: Optional[float] = None):
    """Global-array convenience wrapper: q/k/v [B, H, S, D] sharded (or
    shardable) over ``axis`` on dim 2."""
    return _sharded(ring_attention, q, k, v, axis, causal, sm_scale)


def ulysses_attention_sharded(q, k, v, axis: str = "sep", causal: bool = False,
                              sm_scale: Optional[float] = None):
    return _sharded(ulysses_attention, q, k, v, axis, causal, sm_scale)

"""Process/rank environment + rendezvous.

Parity: python/paddle/distributed/parallel.py (init_parallel_env:46,
ParallelEnv:62 in fluid/dygraph/parallel.py) and fleet launch env wiring
(PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS, fleet/launch_utils.py).

TPU-native: rendezvous is JAX's coordination service
(``jax.distributed.initialize``) instead of NCCL-id-over-TCP
(imperative/nccl_context.cc) or Gloo file/HTTP KV stores (role_maker.py:33).
One process per *host* (driving all its local chips), not one per device —
collectives ride ICI/DCN via XLA, so there is no per-GPU process model.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = [
    "ParallelEnv",
    "init_parallel_env",
    "get_rank",
    "get_world_size",
    "is_initialized",
]

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """Initialize multi-host execution.

    Single-host (the common TPU pod-slice dev loop and all tests): no-op
    beyond marking the env initialized — every local device is already
    visible.  Multi-host: wires ``jax.distributed.initialize`` from args or
    the standard env vars (COORDINATOR_ADDRESS / PADDLE_TRAINER_ENDPOINTS,
    PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID — the launch-compatible names).
    """
    global _initialized
    if _initialized:
        return ParallelEnv()

    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
        if eps:
            addr = eps.split(",")[0]
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM", "0") or 0)
    pid = process_id if process_id is not None else int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)

    if addr and nproc > 1:
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=nproc, process_id=pid
        )
    _initialized = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    """Number of participating *devices* across all processes (paddle's
    world_size counts trainers = GPUs; the TPU analogue is chips)."""
    return jax.device_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv (fluid/dygraph/parallel.py:62)."""

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def world_size(self) -> int:
        return jax.device_count()

    @property
    def local_rank(self) -> int:
        return jax.process_index()

    @property
    def nranks(self) -> int:
        return jax.device_count()

    @property
    def device_id(self) -> int:
        devs = jax.local_devices()
        return devs[0].id if devs else 0

    @property
    def local_devices(self):
        return jax.local_devices()

    @property
    def current_endpoint(self) -> str:
        eps = self.trainer_endpoints
        i = jax.process_index()
        return eps[i] if i < len(eps) else ""

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

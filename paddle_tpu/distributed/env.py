"""Process/rank environment + rendezvous.

Parity: python/paddle/distributed/parallel.py (init_parallel_env:46,
ParallelEnv:62 in fluid/dygraph/parallel.py) and fleet launch env wiring
(PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS, fleet/launch_utils.py).

TPU-native: rendezvous is JAX's coordination service
(``jax.distributed.initialize``) instead of NCCL-id-over-TCP
(imperative/nccl_context.cc) or Gloo file/HTTP KV stores (role_maker.py:33).
One process per *host* (driving all its local chips), not one per device —
collectives ride ICI/DCN via XLA, so there is no per-GPU process model.

Env wiring is validated up front (:func:`validate_env`): a bad
``PADDLE_TRAINER_*`` / ``COORDINATOR_ADDRESS`` combination raises a typed
:class:`InvalidArgumentError` naming the offending variable instead of
failing deep inside ``jax.distributed.initialize`` minutes later.  The
coordinator join itself runs under a deadline-aware
:class:`resilience.retry.RetryPolicy` with a ``fault_point`` seam
(``"distributed.init"``) so chaos plans can exercise the flaky-rendezvous
path.

Transports (``PADDLE_TPU_GANG_TRANSPORT``):

* ``jax`` — the coordination service; the production pod mode.  Global
  device view, XLA collectives over ICI/DCN.
* ``file`` — rank/world come from the env vars alone and *host-level*
  gang collectives (:mod:`paddle_tpu.distributed.gang`) ride a shared
  directory (``PADDLE_TPU_GANG_DIR``).  This is the CPU multi-process
  lane: the CPU backend joins the coordination service fine but refuses
  cross-process XLA computations, so the pod smoke runs real processes
  over this transport instead.
* ``auto`` (default) — ``jax`` when a coordinator address is wired,
  ``file`` when only a gang dir is, single-host otherwise.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax

from ..framework.errors import InvalidArgumentError

__all__ = [
    "ParallelEnv",
    "init_parallel_env",
    "validate_env",
    "get_rank",
    "get_world_size",
    "is_initialized",
    "process_index",
    "process_count",
    "gang_transport",
]

ENV_GANG_TRANSPORT = "PADDLE_TPU_GANG_TRANSPORT"
ENV_GANG_DIR = "PADDLE_TPU_GANG_DIR"
ENV_INIT_TIMEOUT = "PADDLE_TPU_INIT_TIMEOUT_S"

_initialized = False
#: resolved transport after init: "single" | "jax" | "file"
_transport = "single"
#: rank/world under the file transport (jax only sees local devices there)
_gang_rank = 0
_gang_world = 1


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        return int(raw)
    except ValueError:
        raise InvalidArgumentError(
            f"{name}={raw!r} is not an integer") from None


def validate_env(coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 ) -> Tuple[Optional[str], int, int]:
    """Parse + cross-check the launch env; returns ``(addr, nproc, pid)``.

    Every inconsistency raises :class:`InvalidArgumentError` naming the
    offending variable — world size vs rank bounds, endpoint-count
    mismatches, duplicate endpoints, malformed addresses — instead of the
    opaque coordination-service failure those produce downstream.
    """
    eps_raw = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    endpoints = [e.strip() for e in eps_raw.split(",") if e.strip()]

    explicit_coord = bool(coordinator_address
                          or os.environ.get("COORDINATOR_ADDRESS"))
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr is None and endpoints:
        addr = endpoints[0]

    nproc = (num_processes if num_processes is not None
             else _env_int("PADDLE_TRAINERS_NUM", 0))
    if nproc is None:
        nproc = 0
    if num_processes is None and os.environ.get("PADDLE_TRAINERS_NUM") \
            and nproc < 1:
        raise InvalidArgumentError(
            f"PADDLE_TRAINERS_NUM={nproc} must be >= 1")
    pid = (process_id if process_id is not None
           else _env_int("PADDLE_TRAINER_ID", 0))

    world = nproc if nproc > 0 else (len(endpoints) or 1)
    if not 0 <= pid < max(world, 1):
        raise InvalidArgumentError(
            f"PADDLE_TRAINER_ID={pid} out of range [0, {world}) — "
            "check PADDLE_TRAINER_ID against PADDLE_TRAINERS_NUM")
    if endpoints and nproc > 0 and len(endpoints) != nproc \
            and not explicit_coord:
        # with an explicit COORDINATOR_ADDRESS the endpoint list is
        # informational; when it IS the rendezvous source, every rank
        # needs exactly one entry
        raise InvalidArgumentError(
            f"PADDLE_TRAINER_ENDPOINTS lists {len(endpoints)} endpoints "
            f"but PADDLE_TRAINERS_NUM={nproc} — every rank needs exactly "
            "one endpoint")
    if len(set(endpoints)) != len(endpoints):
        dups = sorted({e for e in endpoints if endpoints.count(e) > 1})
        raise InvalidArgumentError(
            f"PADDLE_TRAINER_ENDPOINTS contains duplicate endpoint(s) "
            f"{dups} — two ranks cannot share an address")
    if addr is not None:
        host, _, port = addr.partition(":")
        if not host or not port or not port.isdigit():
            name = ("COORDINATOR_ADDRESS"
                    if coordinator_address or os.environ.get(
                        "COORDINATOR_ADDRESS")
                    else "PADDLE_TRAINER_ENDPOINTS")
            raise InvalidArgumentError(
                f"{name}={addr!r} is not host:port")
    transport = os.environ.get(ENV_GANG_TRANSPORT, "auto").lower()
    if transport not in ("auto", "jax", "file"):
        raise InvalidArgumentError(
            f"{ENV_GANG_TRANSPORT}={transport!r} must be one of "
            "auto|jax|file")
    if world > 1 and addr is None and transport != "file" \
            and not os.environ.get(ENV_GANG_DIR):
        raise InvalidArgumentError(
            f"PADDLE_TRAINERS_NUM={world} but neither COORDINATOR_ADDRESS "
            f"nor PADDLE_TRAINER_ENDPOINTS (nor a {ENV_GANG_DIR} for the "
            "file transport) is set — multi-host needs a rendezvous point")
    if transport == "file" and world > 1 \
            and not os.environ.get(ENV_GANG_DIR):
        raise InvalidArgumentError(
            f"{ENV_GANG_TRANSPORT}=file needs {ENV_GANG_DIR} to point at "
            "a directory shared by all ranks")
    return addr, world, pid


def _join_coordinator(addr: str, nproc: int, pid: int) -> None:
    """``jax.distributed.initialize`` under a deadline-aware retry.

    Pod bring-up is racy by design — hosts boot in any order, the
    coordinator may not be listening yet — so the join retries transient
    rendezvous failures with backoff, bounded by a wall-clock deadline
    (``PADDLE_TPU_INIT_TIMEOUT_S``, default 300s).  The
    ``fault_point("distributed.init")`` seam lets chaos plans inject
    exactly this failure mode.
    """
    from ..resilience.faults import fault_point
    from ..resilience.retry import RetryPolicy

    timeout_s = float(os.environ.get(ENV_INIT_TIMEOUT, "300") or 300)
    policy = RetryPolicy(
        max_attempts=8, backoff_ms=500.0, max_backoff_ms=10_000.0,
        deadline_ms=timeout_s * 1e3,
        retry_on=(RuntimeError, OSError, ConnectionError, TimeoutError),
        name="distributed.init")

    def _attempt():
        fault_point("distributed.init")
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=nproc, process_id=pid)

    policy.call(_attempt)


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """Initialize multi-host execution.

    Single-host (the common TPU pod-slice dev loop and all tests): no-op
    beyond marking the env initialized — every local device is already
    visible.  Multi-host: validates the env wiring up front
    (:func:`validate_env`), then either joins the JAX coordination service
    (``jax`` transport — retried, deadline-bounded, fault-injectable) or
    records the env-derived rank/world (``file`` transport — host-level
    gang collectives ride ``PADDLE_TPU_GANG_DIR``; see
    :mod:`paddle_tpu.distributed.gang`).
    """
    global _initialized, _transport, _gang_rank, _gang_world
    if _initialized:
        return ParallelEnv()

    addr, world, pid = validate_env(coordinator_address, num_processes,
                                    process_id)
    transport = os.environ.get(ENV_GANG_TRANSPORT, "auto").lower()
    if transport == "auto":
        if world > 1 and addr:
            transport = "jax"
        elif world > 1 and os.environ.get(ENV_GANG_DIR):
            transport = "file"

    if transport == "jax" and addr and world > 1:
        _join_coordinator(addr, world, pid)
        _transport = "jax"
    elif transport == "file" and world > 1:
        _transport = "file"
        _gang_rank, _gang_world = pid, world
    else:
        _transport = "single"
    _initialized = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized


def gang_transport() -> str:
    """Resolved transport after :func:`init_parallel_env`:
    ``"single"`` | ``"jax"`` | ``"file"``."""
    return _transport


def process_index() -> int:
    """This host's rank in the gang.  Unlike raw ``jax.process_index()``
    this honors the file transport, where jax itself only sees the local
    host."""
    if _transport == "file":
        return _gang_rank
    return jax.process_index()


def process_count() -> int:
    """Number of host processes in the gang (see :func:`process_index`)."""
    if _transport == "file":
        return _gang_world
    return jax.process_count()


def get_rank() -> int:
    return process_index()


def get_world_size() -> int:
    """Number of participating *devices* across all processes (paddle's
    world_size counts trainers = GPUs; the TPU analogue is chips).  Under
    the file transport jax only sees local devices, so the count is
    local x world (hosts are assumed homogeneous — true for pod slices)."""
    if _transport == "file":
        return jax.device_count() * _gang_world
    return jax.device_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv (fluid/dygraph/parallel.py:62)."""

    @property
    def rank(self) -> int:
        return process_index()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return process_index()

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        devs = jax.local_devices()
        return devs[0].id if devs else 0

    @property
    def local_devices(self):
        return jax.local_devices()

    @property
    def current_endpoint(self) -> str:
        eps = self.trainer_endpoints
        i = process_index()
        return eps[i] if i < len(eps) else ""

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

"""Batched multi-LoRA: fixed-capacity adapter tables, ragged grouped apply.

S-LoRA's observation (Sheng et al., MLSys 2024): serving N adapters from
one continuously-batched engine beats N per-adapter replicas when the
per-row adapter gather is a single ragged grouped computation instead of
a per-request branch.  The TPU-native spelling here keeps every shape
static so the serving compile set stays closed:

* each targeted parallel linear carries THREE buffers —
  ``lora_A [cap, in, r]``, ``lora_B [cap, r, out]``, ``lora_scale
  [cap]`` — a fixed-capacity table of ``cap`` adapter slots.  Buffers
  ride ``buffer_pytree()`` into the serving executables as ARGUMENTS, so
  hot add/remove of an adapter edits host-side leaves (the
  ``swap_weights`` machinery) and recompiles nothing;
* per decode step the engine scopes a ``[B]`` id vector
  (``runtime.adapter_scope``); the linear's base matmul is untouched and
  the delta is ``grouped_matmul(scatter(x), A_stack) · B_stack`` over
  the table — the same compacted one-hot/cumsum dispatch as the MoE
  layer, with ``grouped_matmul`` (PR 14) when lane-aligned and the
  masked-einsum reference otherwise;
* slot id ``-1`` = no adapter: the final combine is a ``where`` that
  SELECTS the base output for dead rows, so a base-tenant row is
  bitwise identical to a model without LoRA enabled.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.errors import InvalidArgumentError

__all__ = [
    "DEFAULT_TARGETS", "lora_targets", "enable_lora", "apply_lora",
    "lora_delta", "write_adapter", "clear_slot", "adapter_capacity",
]

#: leaf names of the parallel linears that take adapter deltas — the
#: transformer block projections, NOT the (tied) embedding / LM head
DEFAULT_TARGETS = ("qkv", "out", "fc1", "fc2")


def lora_targets(model, targets: Sequence[str] = DEFAULT_TARGETS
                 ) -> List[Tuple[str, object]]:
    """``(dotted_name, layer)`` for every parallel linear whose leaf name
    is in ``targets`` (``None`` = every parallel linear)."""
    from ..distributed.meta_parallel import (ColumnParallelLinear,
                                             RowParallelLinear)

    out = []
    for name, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, (ColumnParallelLinear, RowParallelLinear)):
            continue
        leaf = name.rsplit(".", 1)[-1]
        if targets is not None and leaf not in tuple(targets):
            continue
        out.append((name, layer))
    return out


def enable_lora(model, capacity: int, rank: int, alpha: float = None,
                targets: Sequence[str] = DEFAULT_TARGETS,
                dtype: str = "float32") -> List[str]:
    """Register zero-initialized adapter tables on every target linear.

    Zero tables mean an enabled-but-empty model computes ``base + 0`` on
    live rows and exactly ``base`` on ``-1`` rows — safe to enable
    eagerly at model construction.  Returns the dotted site names (the
    keys adapters must address)."""
    capacity = int(capacity)
    rank = int(rank)
    if capacity < 1:
        raise InvalidArgumentError(
            f"lora capacity must be >= 1, got {capacity}")
    if rank < 1:
        raise InvalidArgumentError(f"lora rank must be >= 1, got {rank}")
    scale = (float(alpha) if alpha is not None else float(rank)) / float(rank)
    sites = lora_targets(model, targets)
    if not sites:
        raise InvalidArgumentError(
            f"enable_lora: no parallel-linear targets matching "
            f"{tuple(targets)!r} under {type(model).__name__}")
    for name, layer in sites:
        if "lora_A" in layer._buffers:
            raise InvalidArgumentError(
                f"enable_lora: {name} already has an adapter table")
        din, dout = (int(s) for s in layer.weight.value.shape)
        layer.register_buffer(
            "lora_A", jnp.zeros((capacity, din, rank), dtype))
        layer.register_buffer(
            "lora_B", jnp.zeros((capacity, rank, dout), dtype))
        layer.register_buffer(
            "lora_scale", jnp.full((capacity,), scale, jnp.float32))
    return [n for n, _ in sites]


def _grouped(xe, w, counts):
    """[G, C, D] x [G, D, F] with per-group valid-row counts — Pallas
    grouped kernel when lane-aligned, masked-einsum reference otherwise
    (the MoE layer's exact gate; LoRA's inner dim is the rank, which is
    rarely lane-aligned, so the first hop usually takes the einsum)."""
    from ..ops import autotune as _at

    if (_at.fused_epilogues_eligible(int(xe.shape[-1]))
            and _at.fused_epilogues_eligible(int(w.shape[-1]))):
        from ..ops.grouped_matmul import grouped_matmul

        return grouped_matmul(xe, w, counts)
    rows = xe.shape[1]
    mask = (jnp.arange(rows)[None, :] < counts[:, None]).astype(xe.dtype)
    return jnp.einsum("gcd,gdf->gcf", xe * mask[..., None], w)


def lora_delta(A, B, scale, x2, ids_row):
    """Per-row adapter delta over the fixed table.

    ``x2 [N, D]`` rows carry ``ids_row [N]`` adapter ids (−1 = none).
    Compacted dispatch (one-hot + exclusive cumsum = position within
    group, as in ``moe.layer``) scatters live rows group-major into
    ``[cap, N, D]``, runs both low-rank hops grouped, and gathers each
    row's delta back.  Returns ``(delta [N, F], live [N] bool)``; dead
    rows' delta is exact zero but callers should still ``where`` on
    ``live`` for bitwise base output."""
    cap = int(A.shape[0])
    n = x2.shape[0]
    onehot = jax.nn.one_hot(ids_row, cap, dtype=jnp.int32)  # -1 -> zeros
    counts = onehot.sum(axis=0)
    posn = jnp.cumsum(onehot, axis=0) - onehot
    idx = (onehot * posn).sum(axis=-1)
    cid = jnp.clip(ids_row, 0, cap - 1)
    live = ids_row >= 0
    xm = jnp.where(live[:, None], x2, 0).astype(A.dtype)
    xd = jnp.zeros((cap, n) + (x2.shape[-1],), A.dtype).at[cid, idx].add(xm)
    h = _grouped(xd, A, counts)        # [cap, N, r]
    z = _grouped(h, B, counts)         # [cap, N, F]
    d = z[cid, idx] * scale[cid][:, None].astype(z.dtype)
    return d, live


def apply_lora(layer, x, y):
    """Add the scoped batched-LoRA delta to a parallel-linear output.

    Called from ``ColumnParallelLinear.forward`` /
    ``RowParallelLinear.forward`` when the layer carries a ``lora_A``
    buffer.  Outside any ``runtime.adapter_scope`` this returns ``y``
    untouched (training / plain forwards pay one dict lookup)."""
    from . import runtime

    ids = runtime.active_ids()
    if ids is None:
        return y
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    b = int(ids.shape[0])
    lead = x.shape[:-1]
    if not lead or int(lead[0]) != b:
        raise InvalidArgumentError(
            f"apply_lora: input leading dim {lead} does not start with "
            f"the scoped batch {b}")
    A = layer._buffers["lora_A"].value
    B = layer._buffers["lora_B"].value
    scale = layer._buffers["lora_scale"].value
    x2 = x.reshape(-1, x.shape[-1])
    ids_row = jnp.broadcast_to(
        ids.reshape((b,) + (1,) * (len(lead) - 1)), lead).reshape(-1)
    d, live = lora_delta(A, B, scale, x2, ids_row)
    y2 = y.reshape(-1, y.shape[-1])
    # where, not plain add: selects the untouched base row at id -1, so
    # base-tenant output is bitwise the no-LoRA model's
    y2 = jnp.where(live[:, None], y2 + d.astype(y2.dtype), y2)
    return y2.reshape(y.shape)


# -- host-side table edits (the swap_weights-shaped hot path) -----------------

def adapter_capacity(buffers: Dict[str, object]) -> int:
    """Adapter-table capacity from a flat buffer tree (0 = no LoRA)."""
    for k, v in buffers.items():
        if k.endswith(".lora_A") or k == "lora_A":
            return int(np.asarray(v).shape[0])
    return 0


def write_adapter(buffers: Dict[str, object], slot: int, adapter
                  ) -> Dict[str, object]:
    """New flat buffer dict with ``adapter`` written into table ``slot``.

    Pure w.r.t. the input tree (touched leaves are copies) so the engine
    can swap the whole dict atomically between dispatches.  Shapes and
    dtypes are preserved — the edit is invisible to the compile cache.
    Adapters of rank ``r <= table rank`` zero-pad: padded A columns meet
    padded B rows, so the delta is unchanged."""
    out = dict(buffers)
    slot = int(slot)
    touched = 0
    for site, (a_np, b_np) in adapter.sites.items():
        ak, bk, sk = (site + ".lora_A", site + ".lora_B",
                      site + ".lora_scale")
        if ak not in out or bk not in out or sk not in out:
            raise InvalidArgumentError(
                f"adapter {adapter.name!r} addresses unknown site "
                f"{site!r} (no {ak} buffer — was the model built with "
                f"lora_capacity > 0 and matching targets?)")
        at = np.array(out[ak], copy=True)
        bt = np.array(out[bk], copy=True)
        st = np.array(out[sk], copy=True)
        cap, din, r_tab = at.shape
        dout = bt.shape[2]
        if not 0 <= slot < cap:
            raise InvalidArgumentError(
                f"adapter slot {slot} out of range [0, {cap})")
        if adapter.rank > r_tab:
            raise InvalidArgumentError(
                f"adapter {adapter.name!r} rank {adapter.rank} exceeds "
                f"table rank {r_tab} at {site}")
        if a_np.shape != (din, adapter.rank) or \
                b_np.shape != (adapter.rank, dout):
            raise InvalidArgumentError(
                f"adapter {adapter.name!r} site {site}: A{a_np.shape} / "
                f"B{b_np.shape} do not match layer [{din} -> {dout}] at "
                f"rank {adapter.rank}")
        at[slot] = 0
        at[slot, :, :adapter.rank] = a_np.astype(at.dtype)
        bt[slot] = 0
        bt[slot, :adapter.rank, :] = b_np.astype(bt.dtype)
        st[slot] = adapter.scale
        out[ak], out[bk], out[sk] = at, bt, st
        touched += 1
    if not touched:
        raise InvalidArgumentError(
            f"adapter {adapter.name!r} has no sites")
    return out


def clear_slot(buffers: Dict[str, object], slot: int) -> Dict[str, object]:
    """New flat buffer dict with table ``slot`` zeroed at every site —
    id ``slot`` then computes a zero delta (base output on live rows)."""
    out = dict(buffers)
    slot = int(slot)
    touched = 0
    for k in list(out.keys()):
        if not (k.endswith(".lora_A") or k.endswith(".lora_B")):
            continue
        t = np.array(out[k], copy=True)
        if not 0 <= slot < t.shape[0]:
            raise InvalidArgumentError(
                f"adapter slot {slot} out of range [0, {t.shape[0]})")
        t[slot] = 0
        out[k] = t
        touched += 1
    if not touched:
        raise InvalidArgumentError("clear_slot: tree has no adapter tables")
    return out

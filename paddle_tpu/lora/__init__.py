"""paddle_tpu.lora — batched multi-LoRA adapters for multi-tenant serving.

Three pieces:

* :mod:`.batched` — fixed-capacity adapter tables registered as buffers
  on the parallel linears (``enable_lora``), the per-row ragged grouped
  apply (``apply_lora`` / ``lora_delta``), and the pure host-side table
  edits the engine hot-swaps (``write_adapter`` / ``clear_slot``);
* :mod:`.runtime` — the trace-scoped ``[B]`` adapter-id vector
  (``adapter_scope``) the serving step installs around the block stack;
* :mod:`.adapter` — the :class:`LoraAdapter` bundle and its
  sha256-manifested side-file artifact (``export_adapter`` /
  ``load_adapter``, format ``paddle_tpu.lora_adapter.v1``).

Slot id ``-1`` means "no adapter" and is bitwise the base model's
output; every shape is static in the adapter capacity, so a serving
engine's compile set closes at warmup and stays closed across adapter
hot add/remove.
"""
from . import runtime  # noqa: F401
from .adapter import (  # noqa: F401
    ADAPTER_FORMAT,
    LoraAdapter,
    export_adapter,
    load_adapter,
    merge_adapter,
    random_adapter,
)
from .batched import (  # noqa: F401
    DEFAULT_TARGETS,
    adapter_capacity,
    apply_lora,
    clear_slot,
    enable_lora,
    lora_delta,
    lora_targets,
    write_adapter,
)
from .runtime import active_ids, adapter_scope  # noqa: F401

__all__ = [
    "ADAPTER_FORMAT", "LoraAdapter", "export_adapter", "load_adapter",
    "merge_adapter", "random_adapter", "DEFAULT_TARGETS",
    "adapter_capacity", "apply_lora", "clear_slot", "enable_lora",
    "lora_delta", "lora_targets", "write_adapter", "adapter_scope",
    "active_ids", "runtime",
]

"""LoRA adapter representation + sha256-manifested side-file artifacts.

An adapter (Hu et al., ICLR 2022) is a named bundle of per-site low-rank
pairs ``A [in, r] / B [r, out]`` with one (rank, alpha) — site keys are
the dotted parallel-linear paths ``enable_lora`` returned for the model
it targets.  ``export_adapter`` / ``load_adapter`` mirror the quantized
weight artifacts (PR 15): one serialized payload plus a
``.manifest.json`` sidecar carrying the artifact's sha256, format tag
``paddle_tpu.lora_adapter.v1``.
"""
from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np

from ..framework.errors import InvalidArgumentError
from .batched import DEFAULT_TARGETS, lora_targets

__all__ = [
    "LoraAdapter", "random_adapter", "merge_adapter",
    "export_adapter", "load_adapter", "ADAPTER_FORMAT",
]

ADAPTER_FORMAT = "paddle_tpu.lora_adapter.v1"


class LoraAdapter:
    """In-memory adapter: ``sites[dotted] = (A [in, r], B [r, out])``."""

    def __init__(self, name: str, rank: int, alpha: float,
                 sites: Dict[str, Tuple[np.ndarray, np.ndarray]]):
        self.name = str(name)
        self.rank = int(rank)
        self.alpha = float(alpha)
        if self.rank < 1:
            raise InvalidArgumentError(
                f"adapter {self.name!r}: rank must be >= 1, got {rank}")
        if not sites:
            raise InvalidArgumentError(
                f"adapter {self.name!r}: needs >= 1 site")
        checked = {}
        for site, (a, b) in sites.items():
            a = np.asarray(a)
            b = np.asarray(b)
            if a.ndim != 2 or b.ndim != 2 or a.shape[1] != self.rank or \
                    b.shape[0] != self.rank:
                raise InvalidArgumentError(
                    f"adapter {self.name!r} site {site!r}: expected "
                    f"A [in, {self.rank}] / B [{self.rank}, out], got "
                    f"A{a.shape} / B{b.shape}")
            checked[str(site)] = (a, b)
        self.sites = checked

    @property
    def scale(self) -> float:
        return self.alpha / float(self.rank)

    def __repr__(self):
        return (f"LoraAdapter({self.name!r}, rank={self.rank}, "
                f"alpha={self.alpha}, sites={len(self.sites)})")


def random_adapter(model, name: str, *, rank: int = 4, alpha: float = None,
                   targets: Sequence[str] = DEFAULT_TARGETS, seed: int = 0,
                   std: float = 0.02) -> LoraAdapter:
    """Seeded random adapter over the model's LoRA target sites.

    Both A and B are nonzero (unlike training init, where B starts at
    zero) so the delta is observable — the shape tests and the smoke
    gates need adapters that actually move logits."""
    rs = np.random.RandomState(seed)
    sites = {}
    for n, layer in lora_targets(model, targets):
        din, dout = (int(s) for s in layer.weight.value.shape)
        sites[n] = (
            rs.normal(0.0, std, (din, rank)).astype(np.float32),
            rs.normal(0.0, std, (rank, dout)).astype(np.float32),
        )
    if not sites:
        raise InvalidArgumentError(
            f"random_adapter: no LoRA targets matching {tuple(targets)!r}")
    return LoraAdapter(name, rank,
                       float(alpha) if alpha is not None else float(rank),
                       sites)


def merge_adapter(model, adapter: LoraAdapter) -> Dict[str, np.ndarray]:
    """Dense-merged reference: the model's flat param tree with
    ``W + (A @ B) * scale`` folded into each adapter site's weight.

    Binding this tree via ``functional_call`` gives the single-adapter
    dense forward the batched gather path is tested against (allclose,
    not bitwise — ``x@(W + AB)`` vs ``x@W + (x@A)@B`` associate
    differently)."""
    params = {k: np.asarray(v) for k, v in model.param_pytree().items()}
    for site, (a, b) in adapter.sites.items():
        wk = site + ".weight"
        if wk not in params:
            raise InvalidArgumentError(
                f"merge_adapter: model has no weight at site {site!r}")
        w = params[wk]
        delta = (a.astype(np.float64) @ b.astype(np.float64)) * adapter.scale
        params[wk] = (w.astype(np.float64) + delta).astype(w.dtype)
    return params


def export_adapter(adapter: LoraAdapter, path: str) -> str:
    """Write ``<path>.pdlora`` (the serialized adapter payload) plus a
    ``<path>.pdlora.manifest.json`` sidecar with the artifact's sha256 —
    the same integrity convention as quantized-weight exports.  Returns
    the ``.pdlora`` path."""
    import json
    import os

    from ..framework import serialization
    from ..incubate.checkpoint import _sha256

    prefix = path[:-7] if path.endswith(".pdlora") else path
    artifact = prefix + ".pdlora"
    payload = {
        "format": ADAPTER_FORMAT,
        "name": adapter.name,
        "rank": adapter.rank,
        "alpha": adapter.alpha,
        "sites": {s: {"A": np.asarray(a), "B": np.asarray(b)}
                  for s, (a, b) in adapter.sites.items()},
    }
    serialization.save(payload, artifact)
    manifest = {
        "format": ADAPTER_FORMAT,
        "name": adapter.name,
        "rank": adapter.rank,
        "alpha": adapter.alpha,
        "file": os.path.basename(artifact),
        "sha256": _sha256(artifact),
        "num_sites": len(adapter.sites),
    }
    mpath = artifact + ".manifest.json"
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, mpath)
    return artifact


def load_adapter(path: str) -> LoraAdapter:
    """Load an exported adapter, verifying the manifest's sha256 against
    the artifact bytes (a missing or mismatched manifest is an error —
    the side file IS the integrity contract)."""
    import json
    import os

    from ..framework import serialization
    from ..incubate.checkpoint import _sha256

    artifact = path if path.endswith(".pdlora") else path + ".pdlora"
    mpath = artifact + ".manifest.json"
    if not os.path.exists(mpath):
        raise InvalidArgumentError(
            f"load_adapter: no manifest at {mpath} — refusing an "
            f"unverifiable artifact")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("format") != ADAPTER_FORMAT:
        raise InvalidArgumentError(
            f"load_adapter: {mpath} format is "
            f"{manifest.get('format')!r}, expected {ADAPTER_FORMAT!r}")
    digest = _sha256(artifact)
    if digest != manifest.get("sha256"):
        raise InvalidArgumentError(
            f"load_adapter: sha256 mismatch for {artifact}: manifest "
            f"says {manifest.get('sha256')}, file is {digest}")
    payload = serialization.load(artifact)
    if not isinstance(payload, dict) or payload.get("format") != \
            ADAPTER_FORMAT:
        raise InvalidArgumentError(
            f"load_adapter: {artifact} is not a "
            f"{ADAPTER_FORMAT!r} payload")
    sites = {s: (np.asarray(ab["A"]), np.asarray(ab["B"]))
             for s, ab in payload["sites"].items()}
    return LoraAdapter(payload["name"], payload["rank"], payload["alpha"],
                       sites)

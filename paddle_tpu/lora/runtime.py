"""Trace-scoped per-slot adapter ids for the batched multi-LoRA path.

The serving engine decides *per decode step* which adapter each batch
slot uses; the model's linear layers are many call frames below and
their signatures should not grow a LoRA argument apiece.  Same problem
shape as ``moe.stats``: thread-local scope, pushed by the caller that
owns the step, read by whoever happens to run inside it.

``adapter_scope(ids)`` installs a ``[B]`` int32 vector (slot id ``-1``
= no adapter); ``active_ids()`` returns the innermost vector or
``None``.  Under jit the vector is a tracer captured at trace time —
scopes are per-thread, so a serving decode trace and a training trace
on another thread never see each other's ids.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

__all__ = ["adapter_scope", "active_ids", "active"]

_local = threading.local()


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class adapter_scope:
    """Context manager binding a per-slot adapter-id vector ``[B]``.

    Nesting is allowed (innermost wins) so a caller can temporarily
    disable LoRA by pushing an all ``-1`` vector.
    """

    def __init__(self, ids):
        self._ids = jnp.asarray(ids, jnp.int32)
        if self._ids.ndim != 1:
            from ..framework.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"adapter_scope expects a [B] id vector, got shape "
                f"{self._ids.shape}")

    def __enter__(self):
        _stack().append(self._ids)
        return self

    def __exit__(self, exc_type, exc, tb):
        _stack().pop()
        return False


def active_ids():
    """The innermost scoped id vector, or ``None`` outside any scope."""
    st = _stack()
    return st[-1] if st else None


def active() -> bool:
    return bool(_stack())

"""Dtype registry for paddle_tpu.

TPU-native re-design of the reference's VarType/proto dtype system
(reference: paddle/fluid/framework/framework.proto:104 ``VarType.Type``;
python/paddle/fluid/data_feeder.py convert_dtype).  Instead of a protobuf
enum we map paddle-style dtype names directly onto numpy/jax dtypes; the
default float dtype is process-global like
``paddle.set_default_dtype`` (python/paddle/fluid/framework.py).

On TPU the preferred compute dtype is bfloat16 (MXU-native); float32 stays
the default for parity with the reference API, and AMP (paddle_tpu.amp)
switches matmul-heavy ops to bf16.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes  # ships with jax

__all__ = [
    "dtype",
    "float16",
    "float32",
    "float64",
    "bfloat16",
    "int8",
    "int16",
    "int32",
    "int64",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "bool_",
    "complex64",
    "complex128",
    "set_default_dtype",
    "get_default_dtype",
    "convert_dtype",
    "is_floating_point_dtype",
    "is_integer_dtype",
    "iinfo",
    "finfo",
]

# Canonical dtype objects (numpy dtype instances; jax consumes these directly).
dtype = np.dtype

float16 = np.dtype("float16")
float32 = np.dtype("float32")
float64 = np.dtype("float64")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
bool_ = np.dtype("bool")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_ALIASES = {
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int": int32,
    "int64": int64,
    "long": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, float32, float64, bfloat16}
_INTEGER = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}

_default_dtype = float32


def convert_dtype(d) -> np.dtype:
    """Normalize a user-supplied dtype (str / numpy / jax dtype) to np.dtype.

    Mirrors ``paddle.fluid.data_feeder.convert_dtype`` but returns a numpy
    dtype usable by jax instead of a VarType enum.
    """
    if d is None:
        return get_default_dtype()
    if isinstance(d, str):
        key = d.lower()
        if key in _ALIASES:
            return _ALIASES[key]
        raise TypeError(f"Unsupported dtype string: {d!r}")
    try:
        return np.dtype(d)
    except TypeError as e:
        raise TypeError(f"Unsupported dtype: {d!r}") from e


def set_default_dtype(d):
    """Set the process-global default float dtype (float32/float64/bfloat16/float16).

    Parity: ``paddle.set_default_dtype``.
    """
    global _default_dtype
    nd = convert_dtype(d)
    if nd not in _FLOATING:
        raise TypeError(
            f"set_default_dtype only accepts floating dtypes, got {nd}"
        )
    _default_dtype = nd


def get_default_dtype() -> np.dtype:
    """Parity: ``paddle.get_default_dtype``."""
    return _default_dtype


def is_floating_point_dtype(d) -> bool:
    return convert_dtype(d) in _FLOATING


def is_integer_dtype(d) -> bool:
    return convert_dtype(d) in _INTEGER


def iinfo(d):
    """Parity: ``paddle.iinfo``."""
    return jnp.iinfo(convert_dtype(d))


def finfo(d):
    """Parity: ``paddle.finfo``."""
    return jnp.finfo(convert_dtype(d))

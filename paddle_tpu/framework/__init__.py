"""paddle_tpu.framework — core runtime services.

TPU-native equivalents of the reference's L1 platform layer
(paddle/fluid/platform/) and the Python framework glue
(python/paddle/fluid/framework.py).  There is no ProgramDesc/Scope/Executor
here: under XLA the "program" is a traced jaxpr compiled per step function,
so the IR, interpreter, scope tree and garbage collector of the reference
collapse into ``jax.jit``.
"""
from .dtype import (  # noqa: F401
    float16,
    float32,
    float64,
    bfloat16,
    int8,
    int16,
    int32,
    int64,
    uint8,
    uint16,
    uint32,
    uint64,
    bool_,
    complex64,
    complex128,
    set_default_dtype,
    get_default_dtype,
    convert_dtype,
    is_floating_point_dtype,
    is_integer_dtype,
    iinfo,
    finfo,
)
from .device import (  # noqa: F401
    Place,
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    XPUPlace,
    set_device,
    get_device,
    device_count,
    is_compiled_with_tpu,
    is_compiled_with_cuda,
    get_jax_device,
    memory_stats,
)
from .errors import (  # noqa: F401
    EnforceNotMet,
    InvalidArgumentError,
    NotFoundError,
    OutOfRangeError,
    UnimplementedError,
    enforce,
    enforce_eq,
)
from .flags import set_flags, get_flags, define_flag, flag  # noqa: F401
from .selected_rows import SelectedRows, sparse_tape  # noqa: F401
from .random import (  # noqa: F401
    Generator,
    seed,
    get_rng_state,
    set_rng_state,
    default_generator,
    split_key,
)

"""Global flag registry.

TPU-native re-design of the reference's gflags system
(reference: paddle/fluid/platform/flags.cc:33-560 defines ~30 FLAGS_*;
python/paddle/fluid/framework.py:5676 ``set_flags``; flags are overridable
via FLAGS_* environment variables at import time, see
paddle/fluid/platform/init.cc).

Here flags are a typed in-process registry. Environment variables named
``FLAGS_<name>`` seed the initial value (same convention as the reference).
XLA-level knobs (memory fraction etc.) are owned by the XLA runtime; the
flags kept here are the framework-behavior ones.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict

from .errors import NotFoundError, InvalidArgumentError

__all__ = ["define_flag", "set_flags", "get_flags", "flag"]

_REGISTRY: Dict[str, dict] = {}


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default: Any, help_str: str = "", type_: type | None = None):
    """Register a flag. Env var FLAGS_<name> overrides the default."""
    t = type_ or type(default)
    value = default
    env = os.environ.get(f"FLAGS_{name}")
    if env is not None:
        if t is bool:
            value = _parse_bool(env)
        else:
            value = t(env)
    _REGISTRY[name] = {"value": value, "default": default, "type": t, "help": help_str}
    return value


def set_flags(flags: Dict[str, Any]):
    """Parity: ``paddle.set_flags`` (python/paddle/fluid/framework.py:5676)."""
    for name, value in flags.items():
        if name not in _REGISTRY:
            raise NotFoundError(f"Unknown flag {name!r}")
        t = _REGISTRY[name]["type"]
        if t is bool and isinstance(value, str):
            value = _parse_bool(value)
        try:
            _REGISTRY[name]["value"] = t(value)
        except (TypeError, ValueError) as e:
            raise InvalidArgumentError(f"Bad value for flag {name}: {value!r}") from e


def get_flags(names) -> Dict[str, Any]:
    """Parity: ``paddle.get_flags``."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for name in names:
        if name not in _REGISTRY:
            raise NotFoundError(f"Unknown flag {name!r}")
        out[name] = _REGISTRY[name]["value"]
    return out


def flag(name: str) -> Any:
    """Fast single-flag read for internal use."""
    return _REGISTRY[name]["value"]


# ---------------------------------------------------------------------------
# Core flags (subset of platform/flags.cc that still makes sense on TPU).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Sweep op outputs for NaN/Inf during training "
            "(ref: FLAGS_check_nan_inf, platform/flags.cc:44).")
define_flag("sort_sum_gradient", False,
            "Deterministic gradient accumulation order "
            "(ref: FLAGS_sort_sum_gradient, platform/flags.cc:521). "
            "On XLA gradients are already deterministic; flag kept for API parity.")
define_flag("benchmark", False,
            "Synchronous benchmarking mode: block_until_ready after each step "
            "(ref: FLAGS_benchmark).")
define_flag("paddle_num_threads", 1,
            "Host-side worker threads for data feeding "
            "(ref: FLAGS_paddle_num_threads).")
define_flag("use_system_allocator", False,
            "Ignored on TPU: buffers are owned by the XLA runtime "
            "(ref: FLAGS_use_system_allocator).")
define_flag("eager_delete_tensor_gb", 0.0,
            "Ignored on TPU: XLA owns buffer lifetimes; kept for parity "
            "(ref: FLAGS_eager_delete_tensor_gb).")
define_flag("log_level", 0, "Verbosity for paddle_tpu host-side logging.")
define_flag("executor_cache_capacity", 64,
            "LRU capacity of each Executor's compiled-runner cache. Every "
            "distinct (program version, feed signature, fetch set) pins one "
            "XLA executable; unbounded growth is a slow leak, a too-small "
            "cap recompiles every run (surfaced as analysis rule R403).")
define_flag("persistent_compilation_cache", "",
            "Non-empty: enable JAX's persistent compilation cache at this "
            "directory ('1'/'true' picks a default under ~/.cache), so "
            "repeated process launches skip XLA recompiles. See "
            "sysconfig.enable_persistent_compilation_cache().")
define_flag("kernel_autotune", "on",
            "Pallas kernel tile-size tuning mode (ops/autotune.py): 'on' "
            "runs a measured search on TPU and heuristic defaults "
            "elsewhere; 'off' always takes the heuristic defaults; 'force' "
            "measures even off-TPU (interpret mode — CI smoke only, the "
            "timings are meaningless).")
define_flag("kernel_tuning_cache", "",
            "Persistent kernel-tuning cache (JSON). Empty picks the "
            "default ~/.cache/paddle_tpu/kernel_tuning.json; '0'/'off' "
            "disables persistence (winners live for the process only); "
            "any other value is the cache file path. Pre-warm it by "
            "running representative shapes once, then ship the file — "
            "restarts and serving engines pay zero re-tuning.")
define_flag("measured_search", "on",
            "Measured search over sharding plans and serving configs "
            "(tuning/plan_space.py, tuning/serving_space.py): 'on' lets "
            "tune_plan/tune_serving compile+time candidates on the real "
            "backend when a caller asks; 'off' returns the hand-set "
            "defaults untimed. Kernel tile tuning keeps its own "
            "FLAGS_kernel_autotune; all spaces share "
            "FLAGS_kernel_tuning_cache for persisted winners.")
define_flag("fused_epilogues", True,
            "Let the BERT/GPT hot paths call the fused Pallas epilogues "
            "(LayerNorm+residual, softmax-cross-entropy) on TPU. Off "
            "falls back to the plain XLA ops everywhere.")
define_flag("paged_flash", True,
            "Let the paged serving decode path dispatch to the Pallas "
            "paged-flash-decode kernel (ops/paged_attention.py) on TPU. "
            "Off keeps the gather-then-attend reference path everywhere "
            "(always the CPU path — it is the bit-identical fallback).")
define_flag("fault_plan", "",
            "Deterministic fault injection plan (resilience/faults.py). "
            "Semicolon-separated rules of comma-separated key=value "
            "fields, e.g. 'site=checkpoint.write,nth=3,error="
            "TransientDeviceError;site=serving.runner,p=0.1,seed=7'. "
            "Keys: site (required — a named fault_point), nth (fire on "
            "exactly the Nth call), every (fire on every Nth call), p + "
            "seed (seeded per-call probability), times (max fires), "
            "error (class from framework.errors or builtins; default "
            "TransientDeviceError), latency_ms (inject latency instead "
            "of raising). Empty (default): every fault_point is a no-op "
            "falsy check — zero hot-path cost, bit-identical runs.")
define_flag("collective_timeout_s", 0.0,
            "Collective/straggler watchdog deadline in seconds "
            "(distributed/collective.py): non-zero, every host-level "
            "collective (all_reduce, all_gather, barrier, ...) runs under "
            "a deadline and a wedged call raises TransientDeviceError "
            "into the retry/restart path instead of hanging the rank "
            "forever.  0.0 (default): disabled — the hook is a single "
            "falsy flag check, zero hot-path cost.  Set it well above "
            "the slowest legitimate collective (including the compile "
            "on first call).")
define_flag("transient_max_retries", 3,
            "Max attempts (1 = no retry) for operations retried on "
            "transient device errors (errors.is_transient): Executor.run "
            "dispatch, the async checkpoint writer, and serving batch "
            "execution. See resilience.RetryPolicy.from_flags().")
define_flag("retry_backoff_ms", 100.0,
            "Base delay of the exponential backoff between transient-"
            "error retries (doubles per attempt, +/-25% seeded jitter, "
            "capped at 20x the base).")
define_flag("circuit_failure_threshold", 0.5,
            "Serving circuit breaker (resilience/circuit.py): open a "
            "bucket's circuit when its failure rate over the last "
            "FLAGS_circuit_window batches reaches this fraction.")
define_flag("circuit_window", 8,
            "Number of most-recent batch outcomes per bucket the circuit "
            "breaker evaluates the failure rate over (it never opens "
            "before observing a full window).")
define_flag("circuit_cooldown_ms", 1000.0,
            "How long an open circuit sheds before letting half-open "
            "probe batches through to test recovery.")
define_flag("circuit_half_open_probes", 1,
            "Probe batches admitted in the half-open state; all must "
            "succeed to close the circuit, any failure re-opens it.")
define_flag("continuous_batching", True,
            "GenerationEngine decode scheduling (serving/generation.py): "
            "on (default), requests are admitted into and evicted from "
            "individual decode slots at decode-step granularity against "
            "the preallocated ring KV cache (Orca-style iteration-level "
            "scheduling — a stalled long request holds one slot, never "
            "the batch). Off falls back to the legacy run-batch-to-"
            "completion path. Per-engine override: "
            "GenerationEngine(continuous=...).")
define_flag("paged_kv", False,
            "GenerationEngine KV-cache layout (serving/generation.py): on, "
            "the continuous-batching decode loop stores KV in fixed-size "
            "pages behind a slot→page-table indirection (vLLM-style "
            "PagedAttention) instead of one dense ring region per slot — "
            "pages are allocated on demand, shared copy-on-write across "
            "slots with a common prefix, and returned to a free list at "
            "eviction, so the same HBM budget holds strictly more "
            "resident slots. Tokens stay bit-identical to the dense "
            "path. Requires continuous batching. Per-engine override: "
            "GenerationEngine(paged=...).")
define_flag("kv_page_size", 16,
            "Tokens per KV page in paged mode. Smaller pages waste less "
            "memory on the last partial page per sequence but grow the "
            "page table; must divide the engine's max_len.")
define_flag("speculative_k", 4,
            "Speculative decoding draft length in paged mode: an n-gram "
            "proposer (prompt-lookup) drafts up to k tokens per slot and "
            "one batched verify step accepts the longest matching prefix "
            "— token-identical to plain greedy, up to k+1 tokens per "
            "step when drafts hit. 0 disables speculation.")
define_flag("metrics_port", 0,
            "Prometheus text-exposition endpoint for the observability "
            "registry (observability/exporters.py): 0 disables (default), "
            "-1 binds an ephemeral port (read it back from "
            "observability.status()), any other value is the TCP port. "
            "Picked up by the first Executor via "
            "observability.maybe_enable_from_flags().")
define_flag("metrics_jsonl", "",
            "Base path of the periodic JSONL metrics sink; written as "
            "<base>.p<process_index>.jsonl (one file per host process — "
            "observability.merge_jsonl collates them). Empty (default) "
            "disables the sink. bench.py also emits its per-config "
            "results through this lane when set.")
define_flag("metrics_jsonl_interval_s", 10.0,
            "Seconds between JSONL metric snapshots (plus one final "
            "snapshot at close).")
define_flag("hbm_high_water_frac", 0.9,
            "Analysis rule M902 fires when the HBM high-water mark "
            "(peak_bytes_in_use) reaches this fraction of the device's "
            "bytes_limit — the early warning before a real OOM.")
define_flag("trace_requests", False,
            "End-to-end request tracing (observability/tracing.py): on, "
            "Router.submit opens a root span per accepted request and "
            "the replica-dispatch / batcher-queue / decode-slot layers "
            "record child spans into a bounded per-process ring buffer "
            "(merged into profiler.export_chrome_tracing output). Off "
            "(default), every hook is a single falsy check. Picked up "
            "by observability.maybe_enable_from_flags().")
define_flag("trace_buffer_cap", 65536,
            "Capacity of the request-tracing span ring buffer; the "
            "oldest spans are dropped first past the cap (drops are "
            "counted in Tracer.stats()).")
define_flag("lock_sanitizer", False,
            "Runtime lock-order sanitizer (framework/locking.py): on, "
            "every OrderedLock/OrderedRLock/OrderedCondition acquire "
            "checks the cumulative cross-thread acquisition-order graph "
            "and records a C1004 violation on a would-be cycle (instead "
            "of deadlocking), and every release checks the hold time "
            "against FLAGS_lock_hold_warn_ms (C1005). Off (default), "
            "acquire/release adds a single falsy check. Static "
            "companion: python -m paddle_tpu.analysis --concurrency.")
define_flag("lock_hold_warn_ms", 500.0,
            "Lock-hold duration (milliseconds) past which the lock "
            "sanitizer records a C1005 long-hold violation on release. "
            "Condition.wait time does not count (the wait releases the "
            "lock). <= 0 disables the hold check.")

"""RNG seed management.

TPU-native re-design of the reference's Generator
(reference: paddle/fluid/framework/generator.cc — global + per-device
generators seeded by ``paddle.seed``; python/paddle/framework/random.py).

JAX RNG is functional (explicit keys), which is what XLA needs for
reproducible, parallelizable randomness.  We keep paddle's ``seed()``
ergonomics with a process-global Generator that *splits* a fresh subkey for
every eager random op.  Inside jit-traced functions, random ops must receive
keys explicitly (the layer system plumbs them via ``rngs=`` in
``paddle_tpu.nn.functional_call``) — a global mutable generator inside a
traced function would bake one key into the compiled executable.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Generator", "seed", "get_rng_state", "set_rng_state", "default_generator", "split_key"]


class Generator:
    """Counter-based key source. Thread-safe; each ``next_key`` is unique."""

    def __init__(self, seed_: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed_)

    def manual_seed(self, seed_: int):
        with self._lock:
            self._seed = int(seed_)
            self._count = 0
        return self

    def next_key(self) -> jax.Array:
        with self._lock:
            c = self._count
            self._count += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), c)

    def get_state(self):
        with self._lock:
            return {"seed": self._seed, "count": self._count}

    def set_state(self, state):
        with self._lock:
            self._seed = int(state["seed"])
            self._count = int(state["count"])


_default = Generator(0)


def default_generator() -> Generator:
    return _default


def seed(seed_: int) -> Generator:
    """Parity: ``paddle.seed`` — reseeds the global generator."""
    return _default.manual_seed(seed_)


def split_key(key: Optional[jax.Array] = None) -> jax.Array:
    """Fresh key: from ``key`` if given (pure) else from the global generator."""
    if key is not None:
        return key
    return _default.next_key()


def get_rng_state():
    """Parity: ``paddle.get_rng_state`` (opaque state blob)."""
    return _default.get_state()


def set_rng_state(state):
    """Parity: ``paddle.set_rng_state``."""
    _default.set_state(state)

"""VLOG-style host logging gated by FLAGS_log_level.

Parity: the reference's glog VLOG(level) usage throughout the runtime,
with verbosity from GLOG_v; here the knob is the framework flag
``log_level`` (settable via FLAGS_log_level env or paddle.set_flags).
"""
from __future__ import annotations

import sys

from .flags import flag

__all__ = ["vlog"]


def vlog(level: int, msg: str, *args):
    """Print ``msg % args`` when FLAGS_log_level >= level."""
    if int(flag("log_level")) >= level:
        print(f"[paddle_tpu:v{level}] " + (msg % args if args else msg),
              file=sys.stderr, flush=True)

"""Monitor — named global stat counters.

Parity: paddle/fluid/platform/monitor.h:44-145 (StatRegistry + the
STAT_ADD/STAT_SUB/STAT_RESET macros; e.g. STAT_gpu0_mem_size:174) and its
python accessor.  Framework subsystems bump counters here (train steps,
checkpoint saves, host→device staging bytes), and operators read them for
observability — the no-Prometheus, in-process flavor the reference has.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["stat_add", "stat_sub", "stat_set", "get_stat", "reset_stat",
           "all_stats"]

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> int:
    """STAT_ADD (monitor.h:131): bump and return the counter."""
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_sub(name: str, value: int = 1) -> int:
    return stat_add(name, -int(value))


def stat_set(name: str, value: int) -> int:
    with _lock:
        _stats[name] = int(value)
        return _stats[name]


def get_stat(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def reset_stat(name: str = None):
    """Reset one counter, or all (STAT_RESET)."""
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def all_stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)

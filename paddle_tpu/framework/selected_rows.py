"""SelectedRows: sparse embedding gradients with O(touched-rows) updates.

Reference capability: ``paddle/fluid/framework/selected_rows.h:41`` — the
(rows, value) pair a sparse ``lookup_table`` backward emits so that a step
touching k rows of an N-row table costs O(k), not O(N) — plus the lazy-mode
optimizers that consume it (``python/paddle/fluid/optimizer.py:2026``
``Adam(lazy_mode=True)``) and, at PS scale, the distributed lookup tables
(``paddle/fluid/operators/distributed/large_scale_kv.h:773``).

TPU-native design — the reference cannot be translated here, because
``jax.grad`` of a gather **materializes a dense table-shaped cotangent**:
differentiating ``table[ids]`` w.r.t. ``table`` scatter-adds into an O(N)
zeros buffer, and a dense Adam step then rewrites all N rows of the
moments.  Instead the sparse path restructures the differentiation itself:

1. the embedding forward taps a **gradient tape**: it gathers rows from the
   (non-differentiated) table and adds a zeros ``delta`` of row shape that
   IS a differentiated argument of the train step — so ``d loss / d delta``
   is exactly the per-row gradient, computed without any O(N) buffer;
2. the tape returns the traced ``ids`` alongside, and the train step wraps
   ``(ids, d_delta)`` into a :class:`SelectedRows`;
3. ``Optimizer.update`` recognizes ``SelectedRows`` leaves: with
   ``lazy_mode=True`` the rule gathers the k touched moment rows, updates
   them, and scatters back — per-step cost O(k·D) independent of vocab N.
   Duplicate ids are segment-summed first (:meth:`SelectedRows.merged`);
   padding uses the out-of-range sentinel ``height``, which XLA's default
   FILL_OR_DROP scatter mode drops silently.

Everything stays inside one jitted train step: ``SelectedRows`` is a plain
Python carrier of traced arrays and never crosses a jit boundary, so it
needs no pytree registration (and generic ``tree_map``s therefore cannot
accidentally scale its integer ids).

For tables that exceed HBM, see ``paddle_tpu.incubate.host_embedding`` —
the host-RAM pull/push table that mirrors the reference's parameter-server
role (``large_scale_kv.h``) with the same O(k) per-step cost.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .errors import InvalidArgumentError

__all__ = ["SelectedRows", "sparse_tape", "current_tape", "sparse_param_names"]


class SelectedRows:
    """A sparse slice of a ``[height, D]`` table: ``values[i]`` is the row
    at ``ids[i]``.  Duplicate ids are allowed (they mean "sum"); ids equal
    to ``height`` are padding and are dropped by scatter.

    Mirrors ``paddle/fluid/framework/selected_rows.h:41`` (rows_, value_,
    height_)."""

    __slots__ = ("ids", "values", "height", "_is_merged")

    def __init__(self, ids, values, height: int, _merged: bool = False):
        self.ids = jnp.asarray(ids).reshape(-1)
        values = jnp.asarray(values)
        k = self.ids.shape[0]
        if k:
            self.values = values.reshape(k, -1)
        else:  # reshape(0, -1) cannot infer the row dim
            d = values.shape[-1] if values.ndim >= 2 else 0
            self.values = values.reshape(0, d)
        self.height = int(height)
        self._is_merged = _merged

    @property
    def dim(self) -> int:
        return self.values.shape[1]

    # -- algebra used by the optimizer plumbing ------------------------------
    def __mul__(self, other):  # grad clip / loss-scale: scales values
        return SelectedRows(self.ids, self.values * other, self.height,
                            self._is_merged)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return SelectedRows(self.ids, self.values / other, self.height,
                            self._is_merged)

    def astype(self, dtype):
        return SelectedRows(self.ids, self.values.astype(dtype), self.height,
                            self._is_merged)

    @property
    def dtype(self):
        return self.values.dtype

    def concat(self, other: "SelectedRows") -> "SelectedRows":
        if other.height != self.height:
            raise InvalidArgumentError(
                f"SelectedRows height mismatch {self.height} vs {other.height}")
        return SelectedRows(jnp.concatenate([self.ids, other.ids]),
                            jnp.concatenate([self.values, other.values]),
                            self.height)

    def merged(self) -> "SelectedRows":
        """Segment-sum duplicate ids (ref: math/selected_rows_functor.cc
        MergeAdd).  Returns fixed-size (jit-static) output: k slots, the
        tail padded with the drop sentinel ``height``."""
        if self._is_merged or self.ids.shape[0] == 0:
            return self
        ids, values = self.ids, self.values
        k = ids.shape[0]
        order = jnp.argsort(ids)
        sid = ids[order]
        sval = values[order]
        first = jnp.concatenate(
            [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
        seg = jnp.cumsum(first) - 1  # segment index per sorted element
        summed = jax.ops.segment_sum(sval, seg, num_segments=k)
        uniq = jnp.full((k,), self.height, dtype=sid.dtype)
        uniq = uniq.at[seg].set(sid, mode="drop")
        # drop padding rows' garbage: slots >= n_unique keep the sentinel id,
        # and their summed value is 0 already (segment_sum of nothing)
        return SelectedRows(uniq, summed, self.height, _merged=True)

    def to_dense(self) -> jax.Array:
        """Materialize the dense [height, D] gradient (O(N) — used by
        non-lazy optimizers, matching the reference's dense fallback)."""
        z = jnp.zeros((self.height, self.values.shape[1]), self.values.dtype)
        return z.at[self.ids].add(self.values, mode="drop")

    def l2_norm_sq(self) -> jax.Array:
        """Sum of squares — exact for merged rows; for unmerged duplicates
        this is the norm of the unmerged stack (callers wanting the exact
        gradient norm should call ``.merged()`` first)."""
        return jnp.sum(jnp.square(self.values.astype(jnp.float32)))

    def __repr__(self):
        return (f"SelectedRows(k={self.ids.shape[0]}, dim={self.dim}, "
                f"height={self.height})")


# ---------------------------------------------------------------------------
# The gradient tape
# ---------------------------------------------------------------------------
_state = threading.local()


def current_tape() -> Optional["_Tape"]:
    return getattr(_state, "tape", None)


class _Tape:
    """Collects sparse-embedding taps during one traced forward.

    Two modes:
      * record (``deltas is None``): each tap records (box, ids-shape,
        rows-shape/dtype) and returns plain gathered rows — used under
        ``jax.eval_shape`` to discover delta shapes before differentiation.
      * consume: each tap adds ``deltas[i]`` to its gathered rows (the
        differentiable zeros) and records the traced ids for the caller.
    """

    def __init__(self, deltas: Optional[Sequence[jax.Array]] = None):
        self.deltas = list(deltas) if deltas is not None else None
        self.taps: List[Tuple[Any, jax.Array]] = []  # (box, traced ids)
        self.specs: List[Tuple[Any, Tuple[int, ...], Any]] = []
        self._i = 0

    def tap(self, box, table: jax.Array, ids: jax.Array,
            rows: jax.Array, valid: Optional[jax.Array] = None) -> jax.Array:
        """Called from a sparse layer's forward with the gathered ``rows``
        (= ``table[ids]``, already padding-masked).  Returns the rows the
        layer should use downstream.  ``valid`` (bool, ids-shaped) masks the
        differentiable delta at padding positions so their cotangent is
        exactly zero — the dense path (F.embedding zeroing padding output)
        blocks that gradient too, and clip-by-norm must see the same norm."""
        if self.deltas is None:  # record mode
            self.specs.append((box, rows.shape, rows.dtype))
            return rows
        if self._i >= len(self.deltas):
            raise InvalidArgumentError(
                "sparse tape: more embedding taps than recorded deltas — "
                "the forward is not shape-deterministic across traces")
        d = self.deltas[self._i]
        self._i += 1
        self.taps.append((box, ids))
        d = d.astype(rows.dtype)
        if valid is not None:
            d = jnp.where(valid[..., None], d, 0)
        return rows + d


class sparse_tape:
    """Context manager installing a tape for the duration of a forward."""

    def __init__(self, deltas: Optional[Sequence[jax.Array]] = None):
        self._tape = _Tape(deltas)

    def __enter__(self) -> _Tape:
        if current_tape() is not None:
            raise InvalidArgumentError("sparse_tape does not nest")
        _state.tape = self._tape
        return self._tape

    def __exit__(self, *exc):
        _state.tape = None
        return False


def tap_lookup(box, table, ids, num_embeddings: int,
               padding_idx: Optional[int] = None):
    """The sparse layer forward: gather rows from the non-differentiated
    table and route them through the active tape (shared by nn.Embedding
    and VocabParallelEmbedding so the tap protocol has one definition).
    Returns the rows, or None when no tape is active (caller falls back to
    the dense path)."""
    tape = current_tape()
    if tape is None:
        return None
    table = jnp.asarray(table)
    ids = jnp.asarray(ids)
    valid = None
    if padding_idx is not None:
        # padded positions map to the drop sentinel: they gather fill-zeros
        # here, and their delta-grad scatter is discarded by FILL_OR_DROP;
        # ``valid`` additionally zeroes the delta so phantom rows never
        # inflate merged() gradient norms (clip parity with the dense path)
        ids = jnp.where(ids == padding_idx, num_embeddings, ids)
        valid = ids != num_embeddings
    rows = jnp.take(jax.lax.stop_gradient(table), ids, axis=0,
                    mode="fill", fill_value=0)
    return tape.tap(box, table, ids, rows, valid)


def all_gather_rows(sr: "SelectedRows", axis_name: str, scale=1.0,
                    wire_dtype=None) -> "SelectedRows":
    """Cross-replica SelectedRows reduction inside a ``shard_map`` body:
    ``all_gather`` each replica's (ids, values) and concatenate — the
    reference's sparse allreduce (details/sparse_all_reduce_op_handle.cc:1),
    which gathers rows instead of densifying.  Duplicate ids across
    replicas merge by scatter-add downstream, so ``scale=1/n`` yields mean
    semantics matching the dense pmean.  ``wire_dtype`` sends values in a
    reduced precision (the fp16_allreduce composition; ids stay int)."""
    from jax import lax

    vals = sr.values * scale
    if wire_dtype is not None:
        wire = vals.astype(wire_dtype)
    else:
        wire = vals
    ids = lax.all_gather(sr.ids, axis_name)          # [ndp, k]
    wire = lax.all_gather(wire, axis_name)           # [ndp, k, D]
    return SelectedRows(ids.reshape(-1),
                        wire.reshape((-1,) + wire.shape[2:]).astype(
                            sr.values.dtype),
                        sr.height)


def sparse_param_names(layer) -> Dict[int, str]:
    """Map ``id(Parameter box) -> dotted param name`` for every parameter
    flagged ``sparse`` on ``layer`` (set by ``nn.Embedding(sparse=True)``)."""
    out = {}
    for name, box in layer.named_parameters():
        if getattr(box, "sparse", False):
            out[id(box)] = name
    return out


def build_sparse_step(forward_loss: Callable, sparse_names: Dict[int, str],
                      table_shapes: Dict[str, Tuple[int, int]]):
    """Build the two-phase differentiation used by train steps with sparse
    embeddings.  ``forward_loss(params) -> (loss, aux)`` closes over batch /
    buffers / key; ``sparse_names`` maps box id -> param name.

    Returns ``grad_fn(params) -> ((loss, aux), grads)`` where ``grads`` has
    dense leaves for dense params and :class:`SelectedRows` leaves for the
    sparse tables — and, critically, no O(N) cotangent is ever built for a
    table.

    CONTRACT: sparse tables are excluded from the differentiated arguments,
    so they receive gradients ONLY through tape taps (embedding lookups).
    A forward that reads a sparse table any other way — tied heads,
    explicit weight regularization — trains that use against a constant,
    silently.  Such tables must stay ``sparse=False`` (see the
    nn.Embedding docstring)."""
    names = set(table_shapes)

    def grad_fn(params):
        dense_p = {k: v for k, v in params.items() if k not in names}
        tables = {k: v for k, v in params.items() if k in names}

        # phase 1: abstract probe to learn each tap's delta shape (trace-time
        # only — eval_shape runs no FLOPs)
        probe_tape = _Tape()

        def probe():
            _state.tape = probe_tape
            try:
                return forward_loss({**dense_p, **tables})
            finally:
                _state.tape = None

        jax.eval_shape(probe)
        deltas = [jnp.zeros(shape, dtype) for _, shape, dtype
                  in probe_tape.specs]

        # phase 2: differentiate w.r.t. (dense params, deltas).  The tap
        # order is trace-deterministic, so the probe's box sequence aligns
        # with this trace's ids (boxes are Python objects and cannot ride
        # through has_aux).
        boxes = [box for box, _, _ in probe_tape.specs]

        def inner(dp, ds):
            with sparse_tape(ds) as tape:
                loss, aux = forward_loss({**dp, **tables})
            ids_list = [ids for _, ids in tape.taps]
            return loss, (aux, ids_list)

        (loss, (aux, ids_list)), (dg, d_deltas) = jax.value_and_grad(
            inner, argnums=(0, 1), has_aux=True)(dense_p, deltas)

        grads: Dict[str, Any] = dict(dg)
        for box, ids, gd in zip(boxes, ids_list, d_deltas):
            name = sparse_names.get(id(box))
            if name is None or name not in table_shapes:
                continue  # tapped box not in this params dict (frozen)
            sr = SelectedRows(ids, gd, table_shapes[name][0])
            grads[name] = (grads[name].concat(sr)
                           if isinstance(grads.get(name), SelectedRows) else sr)
        return (loss, aux), grads

    return grad_fn

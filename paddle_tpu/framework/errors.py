"""Enforce-style error helpers.

TPU-native equivalent of ``PADDLE_ENFORCE*`` and ``platform::errors``
(reference: paddle/fluid/platform/enforce.h; errors typed as
InvalidArgument/NotFound/OutOfRange/... in paddle/fluid/platform/errors.h).
We keep the typed-error taxonomy (it surfaces in user-visible messages and in
tests) but implement it as plain Python exceptions — the XLA runtime already
produces rich device-side errors, so no status-decoding layer is needed.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PermissionDeniedError",
    "UnimplementedError",
    "UnavailableError",
    "PreconditionNotMetError",
    "ExecutionTimeoutError",
    "TransientDeviceError",
    "DivergenceError",
    "is_transient",
    "wrap_transient",
    "enforce",
    "enforce_eq",
    "enforce_gt",
    "enforce_shape_rank",
]


class EnforceNotMet(RuntimeError):
    """Base error, parity with paddle's EnforceNotMet."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class TransientDeviceError(UnavailableError):
    """A device/runtime failure that is expected to clear on retry —
    preempted donated buffer, transient ICI/DCN link error, runtime
    RESOURCE_EXHAUSTED from a concurrent burst.  ``resilience.RetryPolicy``
    retries these; anything else is fatal and propagates immediately."""


class DivergenceError(EnforceNotMet):
    """Training diverged beyond what rollback can fix: the supervisor
    (``resilience.TrainingSupervisor``) exhausted its rollback budget or
    kept tripping at the same restored step — restarting from the same
    checkpoint would loop forever, so the run must stop with the
    diagnostic instead."""


#: lowercase substrings of XLA / jax runtime error messages that indicate a
#: transient condition worth retrying (the runtime has no typed taxonomy —
#: status strings are the stable surface, same approach as gRPC clients)
_TRANSIENT_PATTERNS = (
    "resource_exhausted",
    "resource exhausted",
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "connection reset",
    "broken pipe",
    "socket closed",
    "too many pings",
    "transient",
)

#: exception type names (by class name, so jaxlib need not be imported
#: here) whose messages are eligible for pattern classification
_RUNTIME_ERROR_TYPES = ("XlaRuntimeError", "JaxRuntimeError", "RpcError")


def is_transient(exc: BaseException) -> bool:
    """True when ``exc`` should be retried: either already typed transient
    (:class:`TransientDeviceError` / :class:`UnavailableError`) or a raw
    XLA/jax runtime error whose status message matches a known-transient
    pattern.  Typed framework errors other than Unavailable are *never*
    transient — an InvalidArgumentError does not fix itself."""
    if isinstance(exc, TransientDeviceError):
        return True
    if isinstance(exc, UnavailableError):
        return True
    if isinstance(exc, EnforceNotMet):
        return False  # typed taxonomy: everything else is deterministic
    name = type(exc).__name__
    if name in _RUNTIME_ERROR_TYPES or isinstance(exc, (RuntimeError, OSError)):
        msg = str(exc).lower()
        return any(p in msg for p in _TRANSIENT_PATTERNS)
    return False


def wrap_transient(exc: BaseException) -> BaseException:
    """Classify ``exc``: a recognizable transient runtime error comes back
    wrapped as :class:`TransientDeviceError` (chained, so the original
    stack survives); anything else is returned unchanged."""
    if isinstance(exc, TransientDeviceError) or not is_transient(exc):
        return exc
    wrapped = TransientDeviceError(
        f"transient device error ({type(exc).__name__}): {exc}")
    wrapped.__cause__ = exc
    return wrapped


def enforce(cond, msg="", error_cls=InvalidArgumentError):
    """PADDLE_ENFORCE equivalent: raise ``error_cls`` when ``cond`` is falsy."""
    if not cond:
        raise error_cls(msg)


def enforce_eq(a, b, msg="", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg="", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(f"expected {a!r} > {b!r}. {msg}")


def enforce_shape_rank(shape, rank, name="input"):
    if len(shape) != rank:
        raise InvalidArgumentError(
            f"{name} expected rank {rank}, got shape {tuple(shape)}"
        )

"""Enforce-style error helpers.

TPU-native equivalent of ``PADDLE_ENFORCE*`` and ``platform::errors``
(reference: paddle/fluid/platform/enforce.h; errors typed as
InvalidArgument/NotFound/OutOfRange/... in paddle/fluid/platform/errors.h).
We keep the typed-error taxonomy (it surfaces in user-visible messages and in
tests) but implement it as plain Python exceptions — the XLA runtime already
produces rich device-side errors, so no status-decoding layer is needed.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PermissionDeniedError",
    "UnimplementedError",
    "UnavailableError",
    "PreconditionNotMetError",
    "ExecutionTimeoutError",
    "enforce",
    "enforce_eq",
    "enforce_gt",
    "enforce_shape_rank",
]


class EnforceNotMet(RuntimeError):
    """Base error, parity with paddle's EnforceNotMet."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond, msg="", error_cls=InvalidArgumentError):
    """PADDLE_ENFORCE equivalent: raise ``error_cls`` when ``cond`` is falsy."""
    if not cond:
        raise error_cls(msg)


def enforce_eq(a, b, msg="", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg="", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(f"expected {a!r} > {b!r}. {msg}")


def enforce_shape_rank(shape, rank, name="input"):
    if len(shape) != rank:
        raise InvalidArgumentError(
            f"{name} expected rank {rank}, got shape {tuple(shape)}"
        )

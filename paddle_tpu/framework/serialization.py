"""paddle.save / paddle.load — checkpoint serialization.

Parity: python/paddle/framework/io.py (paddle.save:~227, paddle.load:~730 in
the reference) which pickles state-dict-like nested containers, and the C++
fast path framework/save_load_util.cc (version-tagged tensor binary).

TPU-native notes: values are materialized to host numpy before writing
(device buffers are XLA-owned and never memory-mapped); a sharded
``jax.Array`` is fully gathered — per-shard/distributed checkpointing lives
in ``paddle_tpu.incubate.checkpoint`` (orbax-style async) and is layered on
top of this same format.

Format: a zip-free single file — pickle protocol 2+ of nested python
containers whose leaves are numpy arrays / scalars, prefixed by a magic +
version header.  load() rejects non-magic files with a clear error, with
ONE exception: headerless pickles from the reference's ``paddle.save`` are
accepted when (and only when) the filename uses the reference checkpoint
extensions ``.pdparams``/``.pdopt`` (migration path; note that unpickling
any file implies trusting its origin).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import numpy as np

from .errors import InvalidArgumentError, NotFoundError

__all__ = ["save", "load"]

_MAGIC = b"PTPU0001"


def _to_host(obj: Any) -> Any:
    """Recursively materialize jax arrays / Parameter boxes to numpy."""
    from ..nn.layer_base import Parameter

    if isinstance(obj, Parameter):
        return np.asarray(obj.value)
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_to_host(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    if isinstance(obj, (np.ndarray, np.generic, int, float, complex, bool, str, bytes, type(None))):
        return obj
    # LRScheduler / optimizer aux state etc. — plain picklable objects pass
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    """Serialize ``obj`` (state dicts, nested containers, tensors) to
    ``path``.  Parent directories are created (reference behavior)."""
    if not isinstance(path, (str, os.PathLike)):
        raise InvalidArgumentError(f"save path must be str, got {type(path)}")
    path = os.fspath(path)
    if os.path.isdir(path):
        raise InvalidArgumentError(f"save path {path!r} is a directory")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = _to_host(obj)
    from ..resilience.faults import fault_point  # lazy: no import cycle

    fault_point("serialization.save")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:  # stream: no in-memory copy of the pickle
        f.write(_MAGIC)
        pickle.dump(payload, f, protocol=protocol)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crashed save never corrupts a checkpoint


def load(path: str, **configs) -> Any:
    """Load an object saved by :func:`save`. Leaves come back as numpy
    arrays; feed them to ``Layer.set_state_dict`` / ``Optimizer.set_state_dict``
    (which cast onto the right device/dtype lazily).

    Compat: files written by the reference's ``paddle.save`` (plain pickle,
    no magic header — python/paddle/framework/io.py) also load, so
    checkpoints migrate without conversion.  Anything else is rejected with
    a clear error."""
    path = os.fspath(path)
    if not os.path.exists(path):
        raise NotFoundError(f"checkpoint file {path!r} does not exist")
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic == _MAGIC:
            try:
                return pickle.load(f)
            except Exception as e:
                # a magic-headed file that fails to unpickle is a DAMAGED
                # checkpoint (truncated write, bit flip), not a format
                # mismatch — raise the typed error the checkpoint-fallback
                # path (incubate.checkpoint.resume) keys off
                raise InvalidArgumentError(
                    f"{path!r} is a paddle_tpu checkpoint but its payload "
                    f"is corrupt ({type(e).__name__}: {e}) — truncated or "
                    f"bit-flipped write") from e
        # compat fallback ONLY for the reference's own checkpoint
        # extensions: a stray non-checkpoint pickle (or malicious file)
        # under another name is still rejected before unpickling
        if not path.endswith((".pdparams", ".pdopt")):
            raise InvalidArgumentError(
                f"{path!r} is not a paddle_tpu checkpoint (bad magic "
                f"{magic!r}); reference paddle pickles load only from "
                f".pdparams/.pdopt files")
        f.seek(0)
        try:
            return pickle.load(f)  # reference paddle.save: headerless pickle
        except Exception:
            raise InvalidArgumentError(
                f"{path!r} is neither a paddle_tpu checkpoint (magic "
                f"{_MAGIC!r}) nor a reference paddle pickle"
            )

"""Writer for reference-PaddlePaddle binary checkpoint formats.

The inverse of :mod:`paddle_import` — emits artifacts the REFERENCE can
read (and that round-trip through our own importer):

* Tensor / LoDTensor streams (``tensor_util.cc TensorToStream``,
  ``lod_tensor.cc:243 SerializeToStream``): ``u32 version(0)`` ·
  ``u64 lod_level(0)`` · ``u32 version(0)`` · ``i32 desc_size`` ·
  ``VarType.TensorDesc`` protobuf · raw bytes (row-major).
* ``save_params``/``save_persistables`` layouts (``fluid/io.py:598``):
  one file per variable named by the variable, or — with ``filename`` —
  ONE stream of LoDTensors concatenated in SORTED variable-name order
  (``fluid/io.py:344``).
* ``save_inference_model``'s ``__model__`` (``fluid/io.py:1164``): a
  serialized ``ProgramDesc`` (``framework.proto:198``) whose block 0
  declares the persistable LoDTensor variables (name/dtype/shape), the
  feed/fetch plumbing vars, and feed/fetch ops — enough for
  ``protoc --decode`` against the reference's ``framework.proto`` and
  for name recovery by any reader of the format (including ours).

Like the importer, the protobuf wire format is emitted directly (varints
+ length-delimited fields with the framework.proto field numbers) — no
protobuf runtime needed for the handful of messages involved.
"""
from __future__ import annotations

import os
import struct
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .errors import InvalidArgumentError

__all__ = ["write_lod_tensor_stream", "build_program_desc",
           "save_reference_state", "save_reference_inference_model"]

# inverse of paddle_import._DTYPES (framework.proto:105 VarType.Type)
_DTYPE_CODES = {
    np.dtype(np.bool_): 0, np.dtype(np.int16): 1, np.dtype(np.int32): 2,
    np.dtype(np.int64): 3, np.dtype(np.float16): 4,
    np.dtype(np.float32): 5, np.dtype(np.float64): 6,
    np.dtype(np.uint64): 19, np.dtype(np.uint8): 20, np.dtype(np.int8): 21,
}
_LOD_TENSOR = 7
_FEED_MINIBATCH = 9
_FETCH_LIST = 10


def _dtype_code(dt: np.dtype) -> int:
    dt = np.dtype(dt)
    code = _DTYPE_CODES.get(dt)
    if code is None:
        try:
            import ml_dtypes

            if dt == np.dtype(ml_dtypes.bfloat16):
                return 22  # BF16
        except ImportError:
            pass
        raise InvalidArgumentError(
            f"dtype {dt} has no VarType.Type code in the reference format")
    return code


# ---------------------------------------------------------------------------
# protobuf wire encoding (proto2; only what the format needs)
# ---------------------------------------------------------------------------
def _varint(v: int) -> bytes:
    if v < 0:  # two's complement int64/int32, sign-extended (10 bytes)
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(fno: int, v: int) -> bytes:
    return _varint(fno << 3) + _varint(v)


def _field_bytes(fno: int, payload: bytes) -> bytes:
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def _tensor_desc(dtype, shape) -> bytes:
    # TensorDesc: data_type=1 (enum), dims=2 (repeated int64, unpacked —
    # proto2 default, and what the reference's C++ emits)
    out = _field_varint(1, _dtype_code(dtype))
    for d in shape:
        out += _field_varint(2, int(d))
    return out


def _var_type(kind: int, dtype=None, shape=None) -> bytes:
    # VarType: type=1; lod_tensor=3 {tensor=1 TensorDesc} for LOD_TENSOR
    out = _field_varint(1, kind)
    if kind == _LOD_TENSOR:
        out += _field_bytes(3, _field_bytes(1, _tensor_desc(dtype, shape)))
    return out


def _var_desc(name: str, kind: int, dtype=None, shape=None,
              persistable: bool = False) -> bytes:
    out = _field_bytes(1, name.encode())
    out += _field_bytes(2, _var_type(kind, dtype, shape))
    if persistable:
        out += _field_varint(3, 1)
    return out


def _op_var(parameter: str, arguments: Sequence[str]) -> bytes:
    out = _field_bytes(1, parameter.encode())
    for a in arguments:
        out += _field_bytes(2, a.encode())
    return out


def _op_attr_int(name: str, value: int) -> bytes:
    # Attr: name=1, type=2 (INT=0), i=3
    return (_field_bytes(1, name.encode()) + _field_varint(2, 0)
            + _field_varint(3, value))


def _op_desc(op_type: str, inputs, outputs, attrs=()) -> bytes:
    out = b""
    for param, args in inputs:
        out += _field_bytes(1, _op_var(param, args))
    for param, args in outputs:
        out += _field_bytes(2, _op_var(param, args))
    out += _field_bytes(3, op_type.encode())
    for a in attrs:
        out += _field_bytes(4, a)
    return out


def build_program_desc(var_specs: Sequence[dict],
                       feed_names: Sequence[str] = (),
                       fetch_names: Sequence[str] = ()) -> bytes:
    """Serialize a ProgramDesc declaring ``var_specs``
    (``[{"name", "shape", "dtype", "persistable"?}]``) plus the standard
    feed/fetch plumbing (``fluid/io.py:1164 prepend_feed_ops /
    append_fetch_ops``).  Decodes cleanly with
    ``protoc --decode paddle.framework.proto.ProgramDesc framework.proto``.
    """
    # root block: idx=0, parent_idx=kNoneBlockIndex=-1 (proto_desc.h:23)
    block = _field_varint(1, 0) + _field_varint(2, -1)
    for spec in var_specs:
        block += _field_bytes(3, _var_desc(
            spec["name"], _LOD_TENSOR, spec["dtype"], spec["shape"],
            persistable=bool(spec.get("persistable", True))))
    ops = b""
    if feed_names or fetch_names:
        block += _field_bytes(3, _var_desc("feed", _FEED_MINIBATCH,
                                           persistable=True))
        block += _field_bytes(3, _var_desc("fetch", _FETCH_LIST,
                                           persistable=True))
        for i, name in enumerate(feed_names):
            ops += _field_bytes(4, _op_desc(
                "feed", [("X", ["feed"])], [("Out", [name])],
                [_op_attr_int("col", i)]))
        for i, name in enumerate(fetch_names):
            ops += _field_bytes(4, _op_desc(
                "fetch", [("X", [name])], [("Out", ["fetch"])],
                [_op_attr_int("col", i)]))
    block += ops
    # ProgramDesc: blocks=1, version=4 {version=1}
    return (_field_bytes(1, block)
            + _field_bytes(4, _field_varint(1, 0)))


# ---------------------------------------------------------------------------
# tensor streams
# ---------------------------------------------------------------------------
def write_lod_tensor_stream(f, arr) -> None:
    """One LoDTensor stream (format at module top; LoD level 0 — dense
    padding replaces LoD in this framework)."""
    arr = np.ascontiguousarray(np.asarray(arr))
    f.write(struct.pack("<I", 0))           # LoDTensor version
    f.write(struct.pack("<Q", 0))           # lod_level = 0
    f.write(struct.pack("<I", 0))           # Tensor version
    desc = _tensor_desc(arr.dtype, arr.shape)
    f.write(struct.pack("<i", len(desc)))
    f.write(desc)
    f.write(arr.tobytes())


def _state_specs(state: Dict[str, np.ndarray]):
    return [{"name": n, "shape": tuple(np.shape(v)),
             "dtype": np.asarray(v).dtype, "persistable": True}
            for n, v in state.items()]


def save_reference_state(state: Dict[str, np.ndarray], dirname: str,
                         filename: Optional[str] = None,
                         model_filename: str = "__model__",
                         write_model: bool = True) -> None:
    """``save_params``/``save_persistables`` layout: per-variable files,
    or one combined file (sorted-name order) when ``filename`` is given.
    A ``__model__`` ProgramDesc is written alongside so the directory is
    self-describing (the reference reads names from the program; readers
    of the combined file need it)."""
    os.makedirs(dirname, exist_ok=True)
    state = {n: np.asarray(v) for n, v in state.items()}
    if write_model:
        with open(os.path.join(dirname, model_filename), "wb") as f:
            f.write(build_program_desc(_state_specs(state)))
    if filename is None:
        for name, arr in state.items():
            if os.sep in name or (os.altsep and os.altsep in name):
                raise InvalidArgumentError(
                    f"variable name {name!r} is not a valid filename for "
                    "per-variable save; pass filename= for a combined file")
            with open(os.path.join(dirname, name), "wb") as f:
                write_lod_tensor_stream(f, arr)
    else:
        with open(os.path.join(dirname, filename), "wb") as f:
            for name in sorted(state):  # fluid/io.py:344 sorted-name order
                write_lod_tensor_stream(f, state[name])


def save_reference_inference_model(
        dirname: str, feed_names: Sequence[str],
        fetch_names: Sequence[str], state: Dict[str, np.ndarray],
        model_filename: str = "__model__",
        params_filename: Optional[str] = None) -> None:
    """``save_inference_model`` layout (``fluid/io.py:1164``): ``__model__``
    with feed/fetch plumbing + persistables, params per-variable or
    combined (``params_filename``)."""
    os.makedirs(dirname, exist_ok=True)
    state = {n: np.asarray(v) for n, v in state.items()}
    with open(os.path.join(dirname, model_filename), "wb") as f:
        f.write(build_program_desc(_state_specs(state),
                                   feed_names=feed_names,
                                   fetch_names=fetch_names))
    save_reference_state(state, dirname, filename=params_filename,
                         write_model=False)

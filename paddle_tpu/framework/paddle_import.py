"""Importer for reference-PaddlePaddle saved models (binary persistables).

Reference format (implemented from the in-tree spec, not by linking any
reference code):

* Tensor stream (``paddle/fluid/framework/tensor_util.cc TensorToStream``):
  ``u32 version(0)`` · ``i32 desc_size`` · ``VarType.TensorDesc`` protobuf
  (``framework.proto:139`` — field 1 ``data_type`` enum, field 2 repeated
  ``int64 dims``) · raw tensor bytes.
* LoDTensor stream (``lod_tensor.cc:243 SerializeToStream``): ``u32
  version(0)`` · ``u64 lod_level`` · per level ``u64 nbytes`` + raw
  ``size_t`` offsets · the Tensor stream.
* ``save_params``/``save_persistables`` without ``filename``: one file per
  variable, named by the variable (names come from filenames).
* With ``filename`` (and ``save_inference_model``'s params file): ONE
  stream of LoDTensors concatenated in SORTED variable-name order
  (``python/paddle/fluid/io.py:344``); the names live in the ``__model__``
  ProgramDesc (``framework.proto:198`` blocks=1 → :174 vars=3 → :165
  name=1/type=2/persistable=3).
* 2.x ``paddle.save`` state dicts: a pickle of {name: ndarray} — handled
  for completeness.

The ProgramDesc is read with a ~40-line protobuf WIRE parser (varint +
length-delimited walking with the field numbers above) — no protobuf
runtime or generated code needed for the handful of fields involved.

``load_program_state``-style entry: :func:`load_reference_state_dict`.
Mapping onto a paddle_tpu Layer: :func:`adapt_state_dict` (exact names
first — the 2.0 zoo names match this framework's — then unique-shape
matching for renamed 1.x builder params, erroring on ambiguity).
"""
from __future__ import annotations

import os
import re
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .errors import InvalidArgumentError

__all__ = ["load_reference_state_dict", "read_lod_tensor_stream",
           "parse_program_persistables", "adapt_state_dict"]

# framework.proto:105 VarType.Type → numpy dtype (tensor-bearing entries)
_DTYPES = {
    0: np.dtype(np.bool_), 1: np.dtype(np.int16), 2: np.dtype(np.int32),
    3: np.dtype(np.int64), 4: np.dtype(np.float16), 5: np.dtype(np.float32),
    6: np.dtype(np.float64), 19: np.dtype(np.uint64),
    20: np.dtype(np.uint8), 21: np.dtype(np.int8),
    22: None,  # BF16 — resolved to ml_dtypes.bfloat16 in _tensor_desc
}
_LOD_TENSOR_TYPE = 7  # VarType.Type.LOD_TENSOR


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# ---------------------------------------------------------------------------
# protobuf wire-format walking (proto2; only what the format needs)
# ---------------------------------------------------------------------------
def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes) -> Dict[int, list]:
    """Walk one serialized message: {field_number: [raw values]} where a
    raw value is an int (varint/fixed) or bytes (length-delimited)."""
    out: Dict[int, list] = {}
    i = 0
    while i < len(buf):
        key, i = _varint(buf, i)
        fno, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _varint(buf, i)
        elif wire == 1:
            v = struct.unpack_from("<q", buf, i)[0]
            i += 8
        elif wire == 2:
            n, i = _varint(buf, i)
            v = buf[i:i + n]
            i += n
        elif wire == 5:
            v = struct.unpack_from("<i", buf, i)[0]
            i += 4
        else:
            raise InvalidArgumentError(f"unsupported wire type {wire}")
        out.setdefault(fno, []).append(v)
    return out


def _repeated_int64(raw_list) -> List[int]:
    """proto2 repeated int64: unpacked (one varint per entry) or packed
    (one length-delimited blob)."""
    dims: List[int] = []
    for v in raw_list:
        if isinstance(v, bytes):  # packed
            i = 0
            while i < len(v):
                d, i = _varint(v, i)
                dims.append(d)
        else:
            dims.append(v)
    # dims are int64 two's complement via varint (−1 = UNK batch)
    return [d - (1 << 64) if d >= (1 << 63) else d for d in dims]


def _tensor_desc(desc_bytes: bytes) -> Tuple[np.dtype, Tuple[int, ...]]:
    f = _fields(desc_bytes)
    dt_code = f[1][0]
    np_dt = _DTYPES.get(dt_code)
    if dt_code == 22:
        np_dt = _bf16()
    if np_dt is None:
        raise InvalidArgumentError(f"unsupported tensor dtype code {dt_code}")
    dims = tuple(_repeated_int64(f.get(2, [])))
    return np_dt, dims


def parse_program_persistables(model_bytes: bytes) -> List[dict]:
    """Block-0 persistable LoDTensor variables of a serialized ProgramDesc,
    in program order: [{"name", "shape", "dtype"}].  Feed/fetch plumbing
    is excluded (their VarType is not LOD_TENSOR)."""
    prog = _fields(model_bytes)
    if 1 not in prog:
        raise InvalidArgumentError(
            "not a ProgramDesc: no blocks field (is this really a "
            "__model__ / .pdmodel file?)")
    block0 = _fields(prog[1][0])
    out = []
    for raw_var in block0.get(3, []):
        var = _fields(raw_var)
        name = var[1][0].decode()
        persistable = bool(var.get(3, [0])[0])
        vtype = _fields(var[2][0])
        type_code = vtype.get(1, [None])[0]
        if not persistable or type_code != _LOD_TENSOR_TYPE:
            continue
        lod_desc = _fields(vtype[3][0])      # LoDTensorDesc
        np_dt, dims = _tensor_desc(lod_desc[1][0])
        out.append({"name": name, "shape": dims, "dtype": np_dt})
    return out


# ---------------------------------------------------------------------------
# tensor streams
# ---------------------------------------------------------------------------
def read_lod_tensor_stream(f) -> np.ndarray:
    """One LoDTensor from a binary stream (format at module top)."""
    ver = struct.unpack("<I", f.read(4))[0]
    if ver != 0:
        raise InvalidArgumentError(f"unsupported LoDTensor version {ver}")
    lod_levels = struct.unpack("<Q", f.read(8))[0]
    for _ in range(lod_levels):
        nbytes = struct.unpack("<Q", f.read(8))[0]
        f.read(nbytes)  # LoD offsets — dense padding replaces LoD here
    ver = struct.unpack("<I", f.read(4))[0]
    if ver != 0:
        raise InvalidArgumentError(f"unsupported Tensor version {ver}")
    desc_size = struct.unpack("<i", f.read(4))[0]
    np_dt, dims = _tensor_desc(f.read(desc_size))
    numel = int(np.prod(dims)) if dims else 1
    data = f.read(numel * np_dt.itemsize)
    if len(data) != numel * np_dt.itemsize:
        raise InvalidArgumentError("truncated tensor data")
    return np.frombuffer(data, np_dt).reshape(dims).copy()


def load_reference_state_dict(
        path: str, params_filename: Optional[str] = None,
        model_filename: str = "__model__") -> Dict[str, np.ndarray]:
    """Load a reference-Paddle checkpoint into {name: ndarray}.

    ``path`` may be:
    * a directory of per-variable files (``save_params`` default mode) —
      optionally containing ``__model__``/``*.pdmodel``, used (when
      present) to restrict to that program's persistables;
    * a directory with a COMBINED params file (pass ``params_filename``,
      e.g. ``save_inference_model(..., params_filename="params")``);
    * a single combined file — needs its ``__model__``/``.pdmodel``
      sibling for names;
    * a 2.x pickled ``.pdparams`` state dict.
    """
    # 2.x pickled state dict?
    if os.path.isfile(path):
        with open(path, "rb") as f:
            head = f.read(2)
        if head[:1] == b"\x80":  # pickle protocol marker
            import pickle

            with open(path, "rb") as f:
                sd = pickle.load(f)
            # drop the reference's metadata tables (e.g.
            # 'StructuredToParameterName@@', framework/io.py:48) — anything
            # that isn't array-like is bookkeeping, not a parameter
            return {k: np.asarray(v) for k, v in sd.items()
                    if not str(k).endswith("@@")
                    and not isinstance(v, (dict, str))}
        model = None
        for cand in (os.path.join(os.path.dirname(path), model_filename),
                     os.path.splitext(path)[0] + ".pdmodel"):
            if os.path.exists(cand):
                model = cand
                break
        if model is None:
            raise InvalidArgumentError(
                "combined params file needs its __model__/.pdmodel sibling "
                "for variable names (fluid/io.py:344 sorted-name order)")
        return _load_combined(path, model)

    if not os.path.isdir(path):
        raise InvalidArgumentError(f"no such checkpoint path: {path}")

    if params_filename is not None:
        return _load_combined(os.path.join(path, params_filename),
                              os.path.join(path, model_filename))

    # per-variable files: every regular file that parses as a LoDTensor.
    # With a __model__, iterate in PROGRAM (creation) order — structural
    # matching in adapt_state_dict relies on it (the reference's builder
    # names encode creation order the same way)
    out: Dict[str, np.ndarray] = {}
    order = None
    model_path = os.path.join(path, model_filename)
    if os.path.exists(model_path):
        with open(model_path, "rb") as f:
            order = [v["name"] for v in parse_program_persistables(f.read())]
    fnames = (order if order is not None
              else sorted(os.listdir(path)))
    for fname in fnames:
        fpath = os.path.join(path, fname)
        if order is not None and not os.path.isfile(fpath):
            raise InvalidArgumentError(
                f"__model__ lists variable {fname!r} but the file is "
                f"missing from {path} — truncated/partial checkpoint")
        if not os.path.isfile(fpath) or fname == model_filename \
                or fname.endswith((".pdmodel", ".py")):
            continue
        try:
            with open(fpath, "rb") as f:
                out[fname] = read_lod_tensor_stream(f)
        except (InvalidArgumentError, struct.error, KeyError, IndexError,
                ValueError):
            if order is not None:  # the program said it should parse
                raise
            continue  # directory stray, skip
    if not out:
        raise InvalidArgumentError(
            f"no persistable tensors found under {path}")
    return out


def _load_combined(params_path: str, model_path: str) -> Dict[str, np.ndarray]:
    with open(model_path, "rb") as f:
        varinfo = parse_program_persistables(f.read())
    order = [v["name"] for v in varinfo]
    names = sorted(order)  # file layout: fluid/io.py:344,873 sorted order
    out = {}
    with open(params_path, "rb") as f:
        for name in names:
            out[name] = read_lod_tensor_stream(f)
        tail = f.read(1)
    if tail:
        raise InvalidArgumentError(
            "combined params file has trailing bytes — the __model__ "
            "variable list does not match the file")
    # expose PROGRAM (creation) order to structural matching
    return {name: out[name] for name in order}


# ---------------------------------------------------------------------------
# mapping onto a paddle_tpu Layer
# ---------------------------------------------------------------------------
# the 1.x builder role suffixes (``conv2d_0.w_0`` …): w_0=weight/scale,
# b_0=bias, w_1/w_2=BN moving mean/variance (fluid/layers/nn.py batch_norm
# default names) ↔ this framework's 2.0 attribute names
_ROLE_BY_ATTR = {"weight": "w_0", "bias": "b_0",
                 "_mean": "w_1", "_variance": "w_2"}
_1X_ROLE = re.compile(r"\.([wb]_\d+)$")


def _natural_key(name: str):
    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", name)]


def adapt_state_dict(sd: Dict[str, np.ndarray], layer) -> Dict[str, np.ndarray]:
    """Map imported names onto ``layer.state_dict()`` names.

    1. Exact name matches (the 2.0 zoo's dotted names match this
       framework's layers).
    2. STRUCTURAL matching for renamed 1.x builder params
       (``conv2d_0.w_0``, …): leftovers are grouped by
       ``(shape, role)`` — role parsed from the 1.x suffix on the source
       side and from the attribute name on the target side — and each
       group is zipped in ORDER: target order is the layer's traversal
       order, source order is the checkpoint's PROGRAM (creation) order
       when a ``__model__`` provided it (load_reference_state_dict
       preserves it), else natural-sorted names (``conv2d_2`` before
       ``conv2d_10``).  Repeated same-shape params (ResNet's 3×3 stacks,
       BERT's identical blocks) disambiguate by this order — the two
       sides walk the same architecture.
    3. Raises when a group's sizes differ or targets stay unmatched.
    """
    target = layer.state_dict()
    remaining = dict(sd)
    out: Dict[str, np.ndarray] = {}
    unmatched = []
    for name, val in target.items():
        if name in remaining:
            out[name] = remaining.pop(name)
        else:
            unmatched.append(name)
    if not unmatched:
        return out

    use_roles = all(_1X_ROLE.search(n) for n in remaining)

    def src_key(name):
        shape = tuple(remaining[name].shape)
        if not use_roles:
            return (shape,)
        return (shape, _1X_ROLE.search(name).group(1))

    def tgt_key(name):
        shape = tuple(np.shape(target[name]))
        if not use_roles:
            return (shape,)
        attr = name.rsplit(".", 1)[-1]
        role = _ROLE_BY_ATTR.get(attr)
        if role is None:
            # unknown attribute (e.g. a custom buffer): its own bucket —
            # matched only by an exactly-equal source role never produced
            # by the map, so it surfaces as unmatched with a clear error
            role = f"?{attr}"
        return (shape, role)

    src_names = list(remaining)
    if not _is_program_ordered(sd):
        src_names.sort(key=_natural_key)

    def run_pass(skey, tkey):
        problems = []
        src_groups: Dict[tuple, list] = {}
        for n in src_names:
            if n in remaining:
                src_groups.setdefault(skey(n), []).append(n)
        tgt_groups: Dict[tuple, list] = {}
        for n in unmatched:  # state_dict traversal order
            tgt_groups.setdefault(tkey(n), []).append(n)
        for key, tnames in tgt_groups.items():
            snames = src_groups.get(key, [])
            if len(snames) != len(tnames):
                problems.append(
                    f"{key}: {len(tnames)} targets vs {len(snames)} imports")
                continue
            for tn, sn in zip(tnames, snames):
                out[tn] = remaining.pop(sn)
                unmatched.remove(tn)
        return problems

    shape_skey = lambda n: (tuple(remaining[n].shape),)  # noqa: E731
    shape_tkey = lambda n: (tuple(np.shape(target[n])),)  # noqa: E731
    problems = run_pass(src_key if use_roles else shape_skey,
                        tgt_key if use_roles else shape_tkey)
    if unmatched and use_roles:
        # roles that don't line up (hand-renamed checkpoints) retry on
        # shape alone — the pre-r5 behavior, generalized to ordered groups
        problems = run_pass(shape_skey, shape_tkey)
    if unmatched:
        raise InvalidArgumentError(
            f"could not map imported params onto {unmatched[:5]}… "
            f"({len(unmatched)} unmatched; {len(remaining)} unused imports "
            f"{list(remaining)[:5]}…; group mismatches: {problems[:4]})")
    return out


def _is_program_ordered(sd) -> bool:
    """Heuristic: load_reference_state_dict preserves program order when a
    __model__ described the checkpoint; a dict in sorted-name order was
    more likely assembled without one (alphabetical ≠ creation order for
    two-digit indices — conv2d_10 sorts before conv2d_2)."""
    names = list(sd)
    return names != sorted(names)

"""Device ("Place") management.

TPU-native re-design of the reference's Place / DeviceContext machinery
(reference: paddle/fluid/platform/place.h:26-103 CPUPlace/CUDAPlace/...,
paddle/fluid/platform/device_context.h:61 DeviceContextPool,
python/paddle/device ``set_device``/``get_device``).

On TPU there are no per-device streams/handles to manage — the XLA runtime
owns contexts and buffers — so a Place reduces to a (kind, index) pair that
maps to a ``jax.Device``.  ``set_device`` installs the jax default device;
jit-compiled functions place outputs by sharding, not by Place.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from .errors import InvalidArgumentError, UnavailableError

__all__ = [
    "Place",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "set_device",
    "get_device",
    "device_count",
    "is_compiled_with_tpu",
    "is_compiled_with_cuda",
    "get_jax_device",
    "memory_stats",
    "XPUPlace",
]


@dataclasses.dataclass(frozen=True)
class Place:
    """Device identity: kind ('cpu'|'tpu'|'gpu') + index.

    Parity: platform::Place (place.h:26); unlike the reference this is not a
    boost::variant — one dataclass covers all kinds.
    """

    kind: str
    index: int = 0

    def __str__(self):
        return f"{self.kind}:{self.index}"

    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # fall back to cpu backend (always present)
            if self.kind == "cpu":
                devs = jax.devices("cpu")
            else:
                raise UnavailableError(
                    f"No {self.kind} devices available; jax.devices()={jax.devices()}"
                )
        if self.index >= len(devs):
            raise InvalidArgumentError(
                f"Device index {self.index} out of range for {self.kind} "
                f"({len(devs)} available)"
            )
        return devs[self.index]


def CPUPlace(index: int = 0) -> Place:
    return Place("cpu", index)


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def CUDAPlace(index: int = 0) -> Place:
    """Parity alias: maps to 'gpu' backend if jax has one."""
    return Place("gpu", index)


def XPUPlace(index: int = 0) -> Place:
    """Parity with the reference's Kunlun XPUPlace (place.h:62): on this
    framework every accelerator is reached through XLA, so XPU maps to the
    default accelerator kind."""
    return Place(_default_accel_kind(), index)


def _kind_of(d: jax.Device) -> str:
    plat = d.platform.lower()
    if plat in ("tpu", "axon"):
        return "tpu"
    if plat in ("gpu", "cuda", "rocm"):
        return "gpu"
    return "cpu"


def _default_accel_kind() -> str:
    for d in jax.devices():
        k = _kind_of(d)
        if k != "cpu":
            return k
    return "cpu"


_current_place: Optional[Place] = None


def set_device(device) -> Place:
    """Parity: ``paddle.set_device('tpu')`` / ``paddle.set_device('cpu')``.

    Accepts 'tpu', 'tpu:0', 'cpu', 'gpu:1' or a Place. Installs the matching
    jax default device so eager ops land there.
    """
    global _current_place
    if isinstance(device, Place):
        place = device
    else:
        s = str(device).lower()
        if ":" in s:
            kind, idx = s.split(":", 1)
            place = Place(kind, int(idx))
        else:
            place = Place(s, 0)
    jdev = place.jax_device()
    jax.config.update("jax_default_device", jdev)
    _current_place = place
    return place


def get_device() -> str:
    """Parity: ``paddle.get_device`` — returns e.g. 'tpu:0'."""
    global _current_place
    if _current_place is None:
        d = jax.devices()[0]
        _current_place = Place(_kind_of(d), 0)
    return str(_current_place)


def get_jax_device() -> jax.Device:
    """The jax.Device eager ops currently target."""
    global _current_place
    if _current_place is None:
        get_device()
    return _current_place.jax_device()


def device_count(kind: Optional[str] = None) -> int:
    """Number of visible devices of ``kind`` (default: current kind)."""
    kind = kind or (_current_place.kind if _current_place else _default_accel_kind())
    return len([d for d in jax.devices() if _kind_of(d) == kind]) or (
        len(jax.devices("cpu")) if kind == "cpu" else 0
    )


def is_compiled_with_tpu() -> bool:
    """True when a TPU backend is visible (parity shape: is_compiled_with_cuda)."""
    return any(_kind_of(d) == "tpu" for d in jax.devices())


def is_compiled_with_cuda() -> bool:
    return any(_kind_of(d) == "gpu" for d in jax.devices())


def memory_stats(place=None) -> dict:
    """Allocator statistics of one device (``peak_bytes_in_use``,
    ``bytes_in_use``, ``bytes_limit``, ...) as reported by the backend.

    ``place`` is a :class:`Place`, a ``jax.Device``, or None (the current
    device).  Backends without allocator introspection (the CPU backend
    returns None from ``Device.memory_stats()``) yield ``{}`` — callers
    treat missing keys as "unreported", so the observability HBM gauges
    simply read 0 off-TPU."""
    if place is None:
        dev = get_jax_device()
    elif isinstance(place, Place):
        dev = place.jax_device()
    else:
        dev = place
    try:
        stats = dev.memory_stats()
    except Exception:
        return {}
    return dict(stats) if stats else {}

"""Runtime lock-order sanitizer (C1004/C1005) — the live companion to
``paddle_tpu.analysis.concurrency``.

The static pass proves properties about lock ACQUISITIONS IT CAN SEE;
this module checks the ones it can't — order edges that only materialize
two call levels deep, through callbacks, or across subsystems — on the
real running threads.  The serving/resilience stack's locks are created
through three drop-in wrappers:

* :class:`OrderedLock` / :class:`OrderedRLock` / :class:`OrderedCondition`
  — same API as the ``threading`` primitives, plus a stable ``name``
  (``"Router._lock"``) shared by every instance playing that role.

With ``FLAGS_lock_sanitizer`` off (default) each wrapper method is the
real primitive behind ONE falsy check — nothing is recorded.  On
(env ``FLAGS_lock_sanitizer=1`` or :func:`enable`), every thread keeps a
held-lock stack and the process accumulates a global name-level edge set
``held -> acquired``.  At acquire time a would-be cycle in that graph is
recorded as a **C1004** violation (with the path) instead of ever
deadlocking — the edge is checked BEFORE blocking on the primitive, so
an ABBA pair is caught the first time the second order appears, even if
the threads never actually collide.  At release time a hold longer than
``FLAGS_lock_hold_warn_ms`` is recorded as **C1005** (``Condition.wait``
time is excluded: the wait releases the lock).  Locks constructed with
``warn=False`` opt out of the hold check only — intentionally coarse
gates (e.g. the router's ``_probe_gate``, held across warmup compiles by
design) stay cycle-checked without drowning the hold signal.

Violations surface three ways: :func:`stats` / :func:`violations` for
gates and tests, ``("concurrency", <lock>)`` trace events consumed by
``analysis.RetraceMonitor.concurrency_stats()`` (which re-emits them as
C1004/C1005 diagnostics), and a "lock sanitizer" profiler summary
section.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from . import trace_events
from .flags import flag

__all__ = [
    "OrderedLock", "OrderedRLock", "OrderedCondition",
    "enable", "disable", "active", "reset", "stats", "violations",
]

_MAX_VIOLATIONS = 256

# THE off-switch: module-global None.  Every wrapper method is
# ``if _active is None: <real primitive op>`` — one falsy check.
_active: Optional["_Sanitizer"] = None
_section_registered = False


class _Sanitizer:
    """Process-wide order/hold checker.  Internal lock ``_glock`` is a
    leaf: never held across user code, so it cannot join a user cycle."""

    def __init__(self, clock=None):
        self._clock = clock if clock is not None else time.monotonic
        self._tls = threading.local()
        self._glock = threading.Lock()
        self._edges: Dict[str, set] = {}        # held name -> {acquired}
        self._violations: List[dict] = []
        self.cycles = 0
        self.long_holds = 0
        self.acquires = 0                        # approximate (unlocked)

    # -- per-thread state ----------------------------------------------------
    def _state(self):
        st = getattr(self._tls, "st", None)
        if st is None:
            st = self._tls.st = {"stack": [], "depth": {}}
        return st

    # -- violation plumbing --------------------------------------------------
    def _record(self, rule: str, lock: str, message: str) -> None:
        with self._glock:
            if rule == "C1004":
                self.cycles += 1
            else:
                self.long_holds += 1
            if len(self._violations) < _MAX_VIOLATIONS:
                self._violations.append({
                    "rule": rule, "lock": lock,
                    "thread": threading.current_thread().name,
                    "message": message,
                })
        if trace_events.active():
            trace_events.notify(("concurrency", lock), dict(
                self.snapshot(), last_rule=rule, last_message=message))

    def snapshot(self) -> dict:
        with self._glock:
            return {
                "enabled": True,
                "acquires": self.acquires,
                "edges": sum(len(v) for v in self._edges.values()),
                "cycles": self.cycles,
                "long_holds": self.long_holds,
            }

    def reset(self) -> None:
        with self._glock:
            self._edges.clear()
            self._violations.clear()
            self.cycles = self.long_holds = self.acquires = 0

    # -- order check ---------------------------------------------------------
    def _check_and_add_edges(self, name: str, held: List[str]) -> None:
        for h in held:
            if h == name:
                continue
            with self._glock:
                outs = self._edges.setdefault(h, set())
                if name in outs:
                    continue
                path = self._find_path(name, h)
                outs.add(name)
            if path is not None:
                chain = " -> ".join([name] + path)
                self._record(
                    "C1004", name,
                    f"acquiring {name} while holding {h} closes the "
                    f"lock-order cycle {chain} -> {name}")

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS ``src -> … -> dst`` in the edge graph (caller holds
        ``_glock``); returns the node path after ``src`` or None."""
        stack = [(src, [])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt in seen:
                    continue
                if nxt == dst:
                    return path + [nxt]
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
        return None

    # -- wrapper entry points ------------------------------------------------
    def acquire(self, wrapper, blocking: bool, timeout) -> bool:
        name = wrapper._name
        st = self._state()
        depth = st["depth"].get(name, 0)
        if depth == 0 and blocking:
            # check BEFORE blocking: a would-be deadlock is recorded,
            # not experienced
            self._check_and_add_edges(name, [e[0] for e in st["stack"]])
        ok = wrapper._inner_acquire(blocking, timeout)
        if ok:
            self.acquires += 1
            st["depth"][name] = depth + 1
            if depth == 0:
                st["stack"].append((name, self._clock(), wrapper._warn))
        return ok

    def release(self, wrapper) -> None:
        name = wrapper._name
        st = self._state()
        depth = st["depth"].get(name, 0)
        if depth == 1:
            st["depth"].pop(name, None)
            self._end_hold(st, name)
        elif depth > 1:
            st["depth"][name] = depth - 1
        wrapper._inner_release()

    def _end_hold(self, st, name: str) -> None:
        for i in range(len(st["stack"]) - 1, -1, -1):
            if st["stack"][i][0] == name:
                _n, t0, warn = st["stack"].pop(i)
                if warn:
                    limit = flag("lock_hold_warn_ms")
                    if limit and limit > 0:
                        held_ms = (self._clock() - t0) * 1e3
                        if held_ms > limit:
                            self._record(
                                "C1005", name,
                                f"{name} held {held_ms:.1f}ms "
                                f"(> FLAGS_lock_hold_warn_ms={limit:g})")
                return

    def wait(self, wrapper, timeout) -> bool:
        """Condition.wait: the inner wait releases the lock, so the
        held-stack entry is popped around it and hold timing restarts
        on wakeup."""
        name = wrapper._name
        st = self._state()
        depth = st["depth"].pop(name, 0)
        if depth:
            self._end_hold(st, name)
        try:
            return wrapper._cond.wait(timeout)
        finally:
            if depth:
                st["depth"][name] = depth
                st["stack"].append((name, self._clock(), wrapper._warn))


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------

_anon_counter = [0]


def _auto_name(kind: str) -> str:
    _anon_counter[0] += 1
    return f"{kind}#{_anon_counter[0]}"


class OrderedLock:
    """``threading.Lock`` with a role name; sanitizer-aware."""

    __slots__ = ("_lock", "_name", "_warn")
    _reentrant = False

    def __init__(self, name: Optional[str] = None, *, warn: bool = True):
        self._lock = threading.Lock()
        self._name = name or _auto_name("OrderedLock")
        self._warn = warn

    @property
    def name(self) -> str:
        return self._name

    def _inner_acquire(self, blocking, timeout):
        return self._lock.acquire(blocking, timeout)

    def _inner_release(self):
        self._lock.release()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _active is None:
            return self._lock.acquire(blocking, timeout)
        return _active.acquire(self, blocking, timeout)

    def release(self) -> None:
        if _active is None:
            self._lock.release()
            return
        _active.release(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<{type(self).__name__} {self._name}>"


class OrderedRLock(OrderedLock):
    """``threading.RLock`` with a role name; reentry adds no edges."""

    __slots__ = ()
    _reentrant = True

    def __init__(self, name: Optional[str] = None, *, warn: bool = True):
        self._lock = threading.RLock()
        self._name = name or _auto_name("OrderedRLock")
        self._warn = warn


class OrderedCondition:
    """``threading.Condition`` with a role name; the condition's own
    lock IS the named lock (pass an Ordered* wrapper to share one)."""

    __slots__ = ("_cond", "_name", "_warn")
    _reentrant = True  # backed by an RLock unless an explicit Lock given

    def __init__(self, lock=None, name: Optional[str] = None, *,
                 warn: bool = True):
        if lock is None:
            self._cond = threading.Condition()
        elif isinstance(lock, OrderedLock):
            self._cond = threading.Condition(lock._lock)
            name = name or lock._name
        else:
            self._cond = threading.Condition(lock)
        self._name = name or _auto_name("OrderedCondition")
        self._warn = warn

    @property
    def name(self) -> str:
        return self._name

    def _inner_acquire(self, blocking, timeout):
        return self._cond.acquire(blocking, timeout)

    def _inner_release(self):
        self._cond.release()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _active is None:
            return self._cond.acquire(blocking, timeout)
        return _active.acquire(self, blocking, timeout)

    def release(self) -> None:
        if _active is None:
            self._cond.release()
            return
        _active.release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if _active is None:
            return self._cond.wait(timeout)
        return _active.wait(self, timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        """Stdlib semantics, routed through the sanitized :meth:`wait`."""
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self):
        return f"<OrderedCondition {self._name}>"


# ---------------------------------------------------------------------------
# module controls
# ---------------------------------------------------------------------------

def enable(clock=None) -> None:
    """Turn the sanitizer on (idempotent; a custom ``clock`` — for tests
    — replaces ``time.monotonic`` in hold timing)."""
    global _active, _section_registered
    if _active is not None and clock is None:
        return
    _active = _Sanitizer(clock=clock)
    if not _section_registered:
        _section_registered = True
        try:
            from .. import profiler
            profiler.register_summary_section(_render_summary,
                                              on_reset=None)
        except Exception:  # pragma: no cover — profiler optional here
            pass


def disable() -> None:
    global _active
    _active = None


def active() -> bool:
    return _active is not None


def reset() -> None:
    if _active is not None:
        _active.reset()


def stats() -> dict:
    if _active is None:
        return {"enabled": False, "acquires": 0, "edges": 0,
                "cycles": 0, "long_holds": 0}
    return _active.snapshot()


def violations() -> List[dict]:
    if _active is None:
        return []
    with _active._glock:
        return list(_active._violations)


def _render_summary() -> str:
    if _active is None:
        return ""
    s = _active.snapshot()
    lines = ["== lock sanitizer ==",
             f"acquires: {s['acquires']}  order edges: {s['edges']}  "
             f"cycles (C1004): {s['cycles']}  "
             f"long holds (C1005): {s['long_holds']}"]
    for v in violations()[:8]:
        lines.append(f"  [{v['rule']}] {v['message']} "
                     f"(thread {v['thread']})")
    return "\n".join(lines)


if flag("lock_sanitizer"):
    enable()

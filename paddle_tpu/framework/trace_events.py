"""Lightweight trace/compile event bus.

``jit.StaticFunction`` and ``static.graph.Executor`` publish one event per
compiled signature here; subscribers (the retrace hazard detector,
paddle_tpu/analysis/retrace.py) diff the signature stream to name the
argument whose shape/dtype churn is causing a signature explosion.  With no
subscribers registered the publish sites are a single falsy check — zero
cost on the hot path.

Three event families share the bus, distinguished by ``site[0]``:

* ``("jit"|"executor", name)`` — one event per compiled signature, ``info``
  holds hashable signature components (diffed by the retrace detector);
* ``("executor_cache", name)`` — compile-cache counter snapshots
  (hits/misses/evictions/size/dispatches), published on every
  ``Executor.run``/``run_steps``; latest value wins (cache-churn rule
  R403), so these must NOT be deduped like signature events;
* ``("serving", name)`` — serving-engine metric snapshots (queue depth,
  batch occupancy, p50/p99 latency, tokens/s, bucket misses…), published
  by ``serving.ServingMetrics`` after every batch/shed/expiry; latest
  value wins (bucket-miss rule S601), same non-dedup semantics as
  ``executor_cache``.
"""
from __future__ import annotations

import threading
from typing import Callable, List

__all__ = ["register", "unregister", "active", "notify",
           "dropped_notifications"]

_lock = threading.Lock()
_observers: List[Callable] = []
_dropped = 0


def register(fn: Callable) -> Callable:
    """Subscribe ``fn(site, info)``: ``site`` is a ("jit"|"executor", name)
    pair, ``info`` a dict of hashable signature components."""
    with _lock:
        if fn not in _observers:
            _observers.append(fn)
    return fn


def unregister(fn: Callable) -> None:
    with _lock:
        try:
            _observers.remove(fn)
        except ValueError:
            pass


def active() -> bool:
    return bool(_observers)


def notify(site, info) -> None:
    """Fan ``(site, info)`` out to every observer.  A raising observer is
    ISOLATED — publish sites sit inside ``Executor.run`` and the serving
    worker loop, and a broken dashboard must not fail a training step —
    and counted (``dropped_notifications()`` + the
    ``trace_events_dropped_notifications`` monitor stat)."""
    global _dropped
    for fn in list(_observers):
        try:
            fn(site, info)
        except Exception:
            with _lock:
                _dropped += 1
            from . import monitor

            monitor.stat_add("trace_events_dropped_notifications")


def dropped_notifications() -> int:
    """Observer exceptions swallowed by :func:`notify` so far."""
    with _lock:
        return _dropped

/* C inference API for paddle_tpu (see capi.cc).
 *
 * Counterpart of the reference C prediction ABI
 * (paddle/fluid/inference/capi/c_api.h); Go programs wrap this header via
 * cgo exactly as the reference's go/paddle/predictor.go wrapped theirs.
 *
 * All functions are thread-compatible (one embedded CPython runtime per
 * process; calls serialize on the GIL).
 */
#ifndef PADDLE_TPU_C_H_
#define PADDLE_TPU_C_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Create a predictor from an exported model (paddle_tpu.inference
 * save_inference_model artifacts: prefix.pdmodel / prefix.pdiparams).
 * Returns NULL on failure — see pd_last_error(). */
void* pd_predictor_create(const char* model_path, const char* params_path);

void pd_predictor_destroy(void* predictor);

/* Run inference: n_inputs float32 row-major buffers with the given
 * shapes.  On success (return 0) the FIRST output is malloc'd into
 * *out_data (free with pd_free), its shape written to out_shape
 * (capacity out_shape_cap) and rank to *out_ndim. */
int pd_predictor_run(void* predictor, const float** inputs,
                     const int64_t* const* shapes, const int* ndims,
                     int n_inputs, float** out_data, int64_t* out_shape,
                     int out_shape_cap, int* out_ndim);

const char* pd_last_error(void);
void pd_free(void* p);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_C_H_ */

// C inference API — the TPU framework's counterpart of the reference's
// C prediction ABI (paddle/fluid/inference/capi/pd_predictor.cc,
// pd_config.cc; the Go client go/paddle/predictor.go wraps that ABI via
// cgo, and wraps this one the same way).
//
// Design: the inference runtime IS the Python package (StableHLO AOT
// modules executed by jax) — so the C ABI embeds a CPython interpreter
// and drives paddle_tpu.inference through it.  That keeps ONE predictor
// implementation (no drift between language frontends) at the cost of an
// embedded interpreter per process, which is how the reference's
// capi ultimately carries its C++ AnalysisPredictor too: a thin ABI over
// the real runtime.
//
// Contract (single-precision MVP):
//   pd_predictor_create(model, params)     -> handle or NULL
//   pd_predictor_run(h, ins, shapes, ndims, n, &out, out_shape, &out_nd)
//       inputs are f32 row-major; ONE f32 output is malloc'd into *out
//       (caller frees with pd_free); returns 0 on success
//   pd_last_error()                        -> per-thread error copy
//
// Set PADDLE_TPU_C_PLATFORM=cpu to pin the embedded runtime's backend
// (tests do; servers on TPU hosts leave it unset).
//
// Build:  g++ -O2 -std=c++17 -shared -fPIC capi.cc \
//             $(python3-config --includes) $(python3-config --ldflags --embed)

#include <Python.h>

#include "paddle_tpu_c.h"  // the public ABI — signatures must match

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::mutex g_mu;
std::string g_error;
std::mutex g_init_mu;
bool g_py_inited = false;

void set_error(const std::string& e) {
  std::lock_guard<std::mutex> g(g_mu);
  g_error = e;
}

// Fetch and format the pending Python exception into g_error.
void capture_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = std::string(where) + ": ";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* u = PyUnicode_AsUTF8(s);
      msg += u ? u : "<unprintable exception>";
      Py_DECREF(s);
    }
  } else {
    msg += "unknown Python error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

struct Predictor {
  PyObject* predictor = nullptr;  // paddle_tpu.inference.Predictor
  PyObject* np = nullptr;         // numpy module
};

bool ensure_python() {
  std::lock_guard<std::mutex> g(g_init_mu);
  if (g_py_inited) return true;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Release the GIL the initializing thread holds, or every other
    // thread's PyGILState_Ensure would deadlock (the header promises
    // thread-compatibility).  Done BEFORE the pin step so every exit
    // path below leaves the GIL released.
    PyEval_SaveThread();
  }
  // Pin the backend before jax loads when asked (tests use cpu: the
  // site-customized default may be a remote TPU plugin).  Not under
  // g_py_inited: a failed pin retries on the next call.
  const char* plat = std::getenv("PADDLE_TPU_C_PLATFORM");
  if (plat) {
    PyGILState_STATE gil = PyGILState_Ensure();
    std::string code = "import jax\n"
                       "jax.config.update('jax_platforms', '" +
                       std::string(plat) + "')\n";
    int rc = PyRun_SimpleString(code.c_str());
    PyGILState_Release(gil);
    if (rc != 0) {
      set_error("failed to pin jax platform");
      return false;
    }
  }
  g_py_inited = true;
  return true;
}

}  // namespace

extern "C" {

const char* pd_last_error() {
  // per-thread copy: the shared buffer may be reallocated by a concurrent
  // set_error while the caller still reads the returned pointer
  static thread_local std::string local;
  {
    std::lock_guard<std::mutex> g(g_mu);
    local = g_error;
  }
  return local.c_str();
}

void pd_free(void* p) { std::free(p); }

void* pd_predictor_create(const char* model_path, const char* params_path) {
  if (!ensure_python()) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  Predictor* h = nullptr;
  PyObject *mod = nullptr, *cfg_cls = nullptr, *cfg = nullptr,
           *create = nullptr, *pred = nullptr, *np = nullptr;
  do {
    mod = PyImport_ImportModule("paddle_tpu.inference");
    if (!mod) { capture_py_error("import paddle_tpu.inference"); break; }
    np = PyImport_ImportModule("numpy");
    if (!np) { capture_py_error("import numpy"); break; }
    cfg_cls = PyObject_GetAttrString(mod, "Config");
    create = PyObject_GetAttrString(mod, "create_predictor");
    if (!cfg_cls || !create) { capture_py_error("inference API"); break; }
    cfg = PyObject_CallFunction(cfg_cls, "ss", model_path, params_path);
    if (!cfg) { capture_py_error("Config"); break; }
    pred = PyObject_CallFunctionObjArgs(create, cfg, nullptr);
    if (!pred) { capture_py_error("create_predictor"); break; }
    h = new Predictor();
    h->predictor = pred;
    h->np = np;
    pred = nullptr;
    np = nullptr;
  } while (false);
  Py_XDECREF(mod);
  Py_XDECREF(cfg_cls);
  Py_XDECREF(cfg);
  Py_XDECREF(create);
  Py_XDECREF(pred);
  Py_XDECREF(np);
  PyGILState_Release(gil);
  return h;
}

void pd_predictor_destroy(void* handle) {
  if (!handle) return;
  Predictor* h = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->predictor);
  Py_XDECREF(h->np);
  PyGILState_Release(gil);
  delete h;
}

// inputs: n_inputs f32 row-major buffers with shapes[i][0..ndims[i]).
// On success: *out_data = malloc'd f32 of the FIRST output, out_shape
// gets its dims (caller provides space for out_shape_cap), *out_ndim set.
int pd_predictor_run(void* handle, const float** inputs,
                     const int64_t* const* shapes, const int* ndims,
                     int n_inputs, float** out_data, int64_t* out_shape,
                     int out_shape_cap, int* out_ndim) {
  if (!handle) {
    set_error("null predictor");
    return 1;
  }
  Predictor* h = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject *arg_list = nullptr, *result = nullptr;
  do {
    arg_list = PyList_New(n_inputs);
    if (!arg_list) { capture_py_error("alloc args"); break; }
    bool ok = true;
    for (int i = 0; i < n_inputs; ++i) {
      int64_t numel = 1;
      for (int d = 0; d < ndims[i]; ++d) numel *= shapes[i][d];
      PyObject* mv = PyMemoryView_FromMemory(
          reinterpret_cast<char*>(const_cast<float*>(inputs[i])),
          numel * sizeof(float), PyBUF_READ);
      if (!mv) { capture_py_error("memoryview"); ok = false; break; }
      PyObject* shape_t = PyTuple_New(ndims[i]);
      if (!shape_t) { capture_py_error("alloc shape"); Py_DECREF(mv);
                      ok = false; break; }
      for (int d = 0; d < ndims[i]; ++d)
        PyTuple_SET_ITEM(shape_t, d, PyLong_FromLongLong(shapes[i][d]));
      // np.frombuffer(mv, dtype=float32).reshape(shape) — the view
      // aliases caller memory only for the synchronous run call
      PyObject* arr = PyObject_CallMethod(h->np, "frombuffer", "Os", mv,
                                          "float32");
      Py_DECREF(mv);
      if (!arr) { capture_py_error("frombuffer"); Py_DECREF(shape_t);
                  ok = false; break; }
      PyObject* shaped = PyObject_CallMethod(arr, "reshape", "O", shape_t);
      Py_DECREF(arr);
      Py_DECREF(shape_t);
      if (!shaped) { capture_py_error("reshape"); ok = false; break; }
      PyList_SET_ITEM(arg_list, i, shaped);  // steals
    }
    if (!ok) break;
    result = PyObject_CallMethod(h->predictor, "run", "(O)", arg_list);
    if (!result) { capture_py_error("run"); break; }
    // Predictor.run returns a list of np arrays; take output 0 as f32
    PyObject* out0 = PySequence_GetItem(result, 0);
    if (!out0) { capture_py_error("output 0"); break; }
    PyObject* out_f32 = PyObject_CallMethod(h->np, "ascontiguousarray",
                                            "Os", out0, "float32");
    Py_DECREF(out0);
    if (!out_f32) { capture_py_error("cast output"); break; }
    PyObject* shape = PyObject_GetAttrString(out_f32, "shape");
    Py_ssize_t nd = shape ? PyTuple_Size(shape) : -1;
    if (nd < 0 || nd > out_shape_cap) {
      set_error(nd < 0 ? "reading output shape failed"
                       : "output rank exceeds out_shape_cap");
      // a failed GetAttr/Size leaves a pending CPython exception; clear it
      // so the next API call on this thread starts from a clean slate
      PyErr_Clear();
      Py_XDECREF(shape);
      Py_DECREF(out_f32);
      break;
    }
    int64_t numel = 1;
    for (Py_ssize_t d = 0; d < nd; ++d) {
      out_shape[d] = PyLong_AsLongLong(PyTuple_GET_ITEM(shape, d));
      numel *= out_shape[d];
    }
    if (PyErr_Occurred()) {  // non-int shape entry: PyLong_AsLongLong == -1
      capture_py_error("output shape entry");
      Py_DECREF(shape);
      Py_DECREF(out_f32);
      break;
    }
    *out_ndim = static_cast<int>(nd);
    Py_DECREF(shape);
    PyObject* bytes = PyObject_CallMethod(out_f32, "tobytes", nullptr);
    Py_DECREF(out_f32);
    if (!bytes) { capture_py_error("tobytes"); break; }
    char* src = nullptr;
    Py_ssize_t blen = 0;
    if (PyBytes_AsStringAndSize(bytes, &src, &blen) != 0) {
      capture_py_error("output bytes");
      Py_DECREF(bytes);
      break;
    }
    *out_data = static_cast<float*>(std::malloc(blen));
    if (*out_data == nullptr) {
      set_error("output allocation failed");
      Py_DECREF(bytes);
      break;
    }
    std::memcpy(*out_data, src, blen);
    Py_DECREF(bytes);
    rc = 0;
  } while (false);
  Py_XDECREF(arg_list);
  Py_XDECREF(result);
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"

// Native ingest engine — the TPU framework's counterpart of the reference's
// C++ Dataset/DataFeed stack (paddle/fluid/framework/data_set.h:157
// InMemoryDataset, data_feed.h:302 InMemoryDataFeed, MultiSlotDataFeed):
// multithreaded file-sharded parsing into an in-memory sample store, global
// shuffle, and dense minibatch assembly — all off the Python interpreter.
//
// Format: numeric text, one sample per line, fields separated by spaces,
// tabs or commas; every line must have exactly the configured column count
// (fixed-width dense — the reference's ragged LoD slots map to padding/
// bucketing on TPU, SURVEY §7g).  Values are stored as float64 so integer
// ids up to 2^53 round-trip exactly.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

// Slot value type tags (C ABI): 0 = float32, 1 = int64.
enum SlotType : int64_t { kFloat32 = 0, kInt64 = 1 };

// One typed slot's storage: padded fixed-stride values + true lengths —
// the dense TPU mapping of the reference's ragged MultiSlot LoD columns
// (data_feed.h:302 MultiSlotDataFeed / MultiSlotType).
struct SlotStore {
  SlotType type = kFloat32;
  int64_t max_len = 1;
  std::vector<float> f32;      // nsamples * max_len when type == kFloat32
  std::vector<int64_t> i64;    // nsamples * max_len when type == kInt64
  std::vector<int64_t> lens;   // nsamples true lengths
};

struct Store {
  int64_t ncols = 0;                // dense mode: fixed column count
  std::vector<double> arena;        // dense mode: nsamples * ncols
  std::vector<SlotStore> slots;     // multislot mode (empty in dense mode)
  std::vector<int64_t> order;       // shuffle permutation
  std::string error;                // first error, if any
  bool multislot() const { return !slots.empty(); }
};

// Parse one MultiSlot-format line into per-thread slot parts:
//   <count> v... <count> v... ...   (one group per declared slot; the
// reference DataGenerator emits exactly this).  Returns false on error.
bool ParseMultiSlotLine(const char* p, const std::vector<SlotStore>& schema,
                        std::vector<SlotStore>* parts, const std::string& file,
                        int64_t lineno, std::mutex* err_mu, std::string* err,
                        std::atomic<bool>* failed) {
  auto fail = [&](const std::string& what) {
    std::lock_guard<std::mutex> g(*err_mu);
    if (err->empty())
      *err = file + ":" + std::to_string(lineno) + ": " + what;
    failed->store(true);
    return false;
  };
  auto skip_ws = [&]() {
    while (*p == ' ' || *p == '\t' || *p == ',' || *p == '\r') ++p;
  };
  // hand-rolled base-10 i64: ~3x strtoll at this call density (no locale,
  // no errno); falls back to "unparsable" via the ok flag
  auto parse_i64 = [&](long long* out) -> bool {
    const char* q = p;
    bool neg = false;
    if (*q == '-' || *q == '+') { neg = (*q == '-'); ++q; }
    if (*q < '0' || *q > '9') return false;
    unsigned long long v = 0;
    const unsigned long long lim =
        neg ? 9223372036854775808ULL : 9223372036854775807ULL;
    while (*q >= '0' && *q <= '9') {
      unsigned d = static_cast<unsigned>(*q - '0');
      if (v > (lim - d) / 10) return false;  // would overflow int64: reject
      v = v * 10 + d;
      ++q;
    }
    *out = neg ? -static_cast<long long>(v) : static_cast<long long>(v);
    p = q;
    return true;
  };
  skip_ws();
  if (*p == '\0' || *p == '\n') return true;  // blank line: skip
  for (size_t si = 0; si < schema.size(); ++si) {
    const SlotStore& sc = schema[si];
    SlotStore& out = (*parts)[si];
    skip_ws();
    long long cnt = 0;
    if (!parse_i64(&cnt))
      return fail("expected slot " + std::to_string(si) + " count near '" +
                  std::string(p).substr(0, 16) + "'");
    if (cnt < 0 || cnt > sc.max_len)
      return fail("slot " + std::to_string(si) + " count " +
                  std::to_string(cnt) + " outside [0, " +
                  std::to_string(sc.max_len) + "] (raise max_len or bucket "
                  "upstream)");
    out.lens.push_back(cnt);
    size_t base_f = out.f32.size();
    size_t base_i = out.i64.size();
    if (sc.type == kFloat32)
      out.f32.resize(base_f + sc.max_len, 0.0f);
    else
      out.i64.resize(base_i + sc.max_len, 0);
    for (long long k = 0; k < cnt; ++k) {
      skip_ws();
      if (sc.type == kFloat32) {
        char* fend = nullptr;
        double v = std::strtod(p, &fend);
        if (fend == p)
          return fail("slot " + std::to_string(si) + " value " +
                      std::to_string(k) + " unparsable near '" +
                      std::string(p).substr(0, 16) + "'");
        out.f32[base_f + k] = static_cast<float>(v);
        p = fend;
      } else {
        long long v = 0;
        if (!parse_i64(&v))
          return fail("slot " + std::to_string(si) + " value " +
                      std::to_string(k) + " unparsable near '" +
                      std::string(p).substr(0, 16) + "'");
        out.i64[base_i + k] = static_cast<int64_t>(v);
      }
    }
  }
  skip_ws();
  if (*p != '\0' && *p != '\n')
    return fail(std::string("trailing fields near '") +
                std::string(p).substr(0, 16) + "'");
  return true;
}

// One reader thread over its file share, MultiSlot format.  The whole
// file is read with one fread and parsed by pointer in place — the
// per-line fgets/std::string path costs ~2x in libc overhead at this
// parse density (measured on the micro-bench).
void ParseFilesMultiSlot(const std::vector<std::string>* files, size_t begin,
                         size_t stride, const std::vector<SlotStore>* schema,
                         std::vector<SlotStore>* parts,
                         std::atomic<bool>* failed, std::mutex* err_mu,
                         std::string* err) {
  std::vector<char> buf;
  for (size_t fi = begin; fi < files->size(); fi += stride) {
    if (failed->load(std::memory_order_relaxed)) return;
    FILE* f = std::fopen((*files)[fi].c_str(), "rb");
    if (!f) {
      std::lock_guard<std::mutex> g(*err_mu);
      if (err->empty())
        *err = "cannot open " + (*files)[fi] + ": " + std::strerror(errno);
      failed->store(true);
      return;
    }
    std::fseek(f, 0, SEEK_END);
    long fsz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (fsz < 0) {
      std::fclose(f);
      std::lock_guard<std::mutex> g(*err_mu);
      if (err->empty())
        *err = "cannot size " + (*files)[fi] + ": " + std::strerror(errno);
      failed->store(true);
      return;
    }
    buf.resize(static_cast<size_t>(fsz) + 1);
    size_t got = std::fread(buf.data(), 1, static_cast<size_t>(fsz), f);
    bool short_read = got != static_cast<size_t>(fsz) && std::ferror(f);
    std::fclose(f);
    if (short_read) {
      // a silent truncation here would be silent training-data loss
      std::lock_guard<std::mutex> g(*err_mu);
      if (err->empty())
        *err = "short read on " + (*files)[fi] + ": " + std::strerror(errno);
      failed->store(true);
      return;
    }
    buf[got] = '\0';

    int64_t lineno = 0;
    char* p = buf.data();
    char* end = buf.data() + got;
    bool aborted = false;
    while (p < end) {
      char* nl = static_cast<char*>(std::memchr(p, '\n', end - p));
      if (nl) *nl = '\0';
      ++lineno;
      if (!ParseMultiSlotLine(p, *schema, parts, (*files)[fi], lineno,
                              err_mu, err, failed)) {
        aborted = true;
        break;
      }
      p = nl ? nl + 1 : end;
    }
    if (aborted) return;
  }
}

// One reader thread: parse its share of files into a private arena.
void ParseFiles(const std::vector<std::string>* files, size_t begin,
                size_t stride, int64_t ncols, std::vector<double>* out,
                std::atomic<bool>* failed, std::mutex* err_mu,
                std::string* err) {
  for (size_t fi = begin; fi < files->size(); fi += stride) {
    if (failed->load(std::memory_order_relaxed)) return;
    FILE* f = std::fopen((*files)[fi].c_str(), "r");
    if (!f) {
      std::lock_guard<std::mutex> g(*err_mu);
      if (err->empty())
        *err = "cannot open " + (*files)[fi] + ": " + std::strerror(errno);
      failed->store(true);
      return;
    }

    int64_t lineno = 0;
    auto parse_line = [&](const char* p) -> bool {  // false = abort file
      ++lineno;
      int64_t got = 0;
      bool blank = true;
      while (*p) {
        while (*p == ' ' || *p == '\t' || *p == ',' || *p == '\r') ++p;
        if (*p == '\0' || *p == '\n') break;
        blank = false;
        char* end = nullptr;
        double v = std::strtod(p, &end);
        if (end == p) {
          std::lock_guard<std::mutex> g(*err_mu);
          if (err->empty())
            *err = (*files)[fi] + ":" + std::to_string(lineno) +
                   ": unparsable field near '" +
                   std::string(p).substr(0, 16) + "'";
          failed->store(true);
          return false;
        }
        out->push_back(v);
        ++got;
        p = end;
      }
      if (blank) return true;  // skip empty lines
      if (got != ncols) {
        std::lock_guard<std::mutex> g(*err_mu);
        if (err->empty())
          *err = (*files)[fi] + ":" + std::to_string(lineno) + ": expected " +
                 std::to_string(ncols) + " columns, got " +
                 std::to_string(got);
        failed->store(true);
        return false;
      }
      return true;
    };

    char buf[1 << 16];
    std::string pending;
    bool aborted = false;
    while (std::fgets(buf, sizeof(buf), f)) {
      size_t blen = std::strlen(buf);
      const char* p = buf;
      if (!pending.empty() || (blen + 1 == sizeof(buf) &&
                               buf[blen - 1] != '\n' && !std::feof(f))) {
        // rare path: a line longer than the read buffer
        pending += buf;
        if (pending.back() != '\n' && !std::feof(f)) continue;
        p = pending.c_str();
      }
      if (!parse_line(p)) {
        aborted = true;
        break;
      }
      pending.clear();
    }
    // a final unterminated line can be left in `pending` when its length
    // is an exact multiple of the read buffer (fgets fills the buffer
    // without seeing EOF) — parse it, don't drop it
    if (!aborted && !pending.empty() && !parse_line(pending.c_str()))
      aborted = true;
    std::fclose(f);
    if (aborted) return;
  }
}

}  // namespace

extern "C" {

// Returns an opaque store handle, or 0 on allocation failure.
void* ingest_create(int64_t ncols) {
  if (ncols <= 0) return nullptr;
  Store* s = new (std::nothrow) Store();
  if (!s) return nullptr;
  s->ncols = ncols;
  return s;
}

// Typed multi-slot store (reference MultiSlotDataFeed, data_feed.h:302):
// `types[i]` ∈ {0: float32, 1: int64}; `max_lens[i]` the padded width of
// slot i (variable-length slots pad with zeros; true lengths are kept).
void* ingest_create_multislot(int64_t nslots, const int64_t* types,
                              const int64_t* max_lens) {
  if (nslots <= 0) return nullptr;
  Store* s = new (std::nothrow) Store();
  if (!s) return nullptr;
  s->slots.resize(nslots);
  for (int64_t i = 0; i < nslots; ++i) {
    if ((types[i] != kFloat32 && types[i] != kInt64) || max_lens[i] <= 0) {
      delete s;
      return nullptr;
    }
    s->slots[i].type = static_cast<SlotType>(types[i]);
    s->slots[i].max_len = max_lens[i];
  }
  return s;
}

void ingest_destroy(void* h) { delete static_cast<Store*>(h); }

// Parse `nfiles` paths with `nthreads` workers.  Thread k takes files
// k, k+n, k+2n... (file-sharded, like the reference's per-thread channel
// split, data_set.h filelist distribution).  Appends to the store.
// Returns number of samples loaded, or -1 (check ingest_error).
int64_t ingest_load(void* h, const char** paths, int64_t nfiles,
                    int64_t nthreads) {
  Store* s = static_cast<Store*>(h);
  if (!s) return -1;
  s->error.clear();  // a previous failed load's message must not shadow ours
  std::vector<std::string> files(paths, paths + nfiles);
  if (nthreads < 1) nthreads = 1;
  if (nthreads > nfiles) nthreads = nfiles;
  std::vector<std::thread> workers;
  std::atomic<bool> failed(false);
  std::mutex err_mu;

  if (s->multislot()) {
    int64_t nslots = static_cast<int64_t>(s->slots.size());
    std::vector<std::vector<SlotStore>> parts(
        nthreads, std::vector<SlotStore>(nslots));
    for (int64_t t = 0; t < nthreads; ++t) {
      workers.emplace_back(ParseFilesMultiSlot, &files, t, nthreads,
                           &s->slots, &parts[t], &failed, &err_mu, &s->error);
    }
    for (auto& w : workers) w.join();
    if (failed.load()) return -1;
    int64_t before = static_cast<int64_t>(s->order.size());
    for (int64_t t = 0; t < nthreads; ++t) {
      for (int64_t si = 0; si < nslots; ++si) {
        SlotStore& dst = s->slots[si];
        SlotStore& src = parts[t][si];
        dst.f32.insert(dst.f32.end(), src.f32.begin(), src.f32.end());
        dst.i64.insert(dst.i64.end(), src.i64.begin(), src.i64.end());
        dst.lens.insert(dst.lens.end(), src.lens.begin(), src.lens.end());
        src = SlotStore();
      }
    }
    int64_t n = static_cast<int64_t>(s->slots[0].lens.size());
    s->order.resize(n);
    for (int64_t i = 0; i < n; ++i) s->order[i] = i;
    return n - before;
  }

  std::vector<std::vector<double>> parts(nthreads);
  for (int64_t t = 0; t < nthreads; ++t) {
    workers.emplace_back(ParseFiles, &files, t, nthreads, s->ncols, &parts[t],
                         &failed, &err_mu, &s->error);
  }
  for (auto& w : workers) w.join();
  if (failed.load()) return -1;
  int64_t before = static_cast<int64_t>(s->arena.size()) / s->ncols;
  size_t total = s->arena.size();
  for (auto& p : parts) total += p.size();
  s->arena.reserve(total);
  for (auto& p : parts) {
    s->arena.insert(s->arena.end(), p.begin(), p.end());
    p.clear();
    p.shrink_to_fit();
  }
  int64_t n = static_cast<int64_t>(s->arena.size()) / s->ncols;
  s->order.resize(n);
  for (int64_t i = 0; i < n; ++i) s->order[i] = i;
  return n - before;
}

// Copy up to `count` samples of one slot (shuffle-permuted, like
// ingest_copy_rows) into caller-allocated buffers: `out_values` is
// count*max_len of the slot's dtype (f32 or i64), `out_lens` (optional)
// count int64 true lengths.  Returns rows written.
int64_t ingest_copy_slot(void* h, int64_t slot, int64_t start,
                         int64_t count, void* out_values,
                         int64_t* out_lens) {
  Store* s = static_cast<Store*>(h);
  if (!s || !s->multislot() || slot < 0 ||
      slot >= static_cast<int64_t>(s->slots.size()) || count <= 0 ||
      start < 0)
    return 0;
  const SlotStore& sc = s->slots[slot];
  int64_t n = static_cast<int64_t>(s->order.size());
  int64_t take = n - start;
  if (take <= 0) return 0;
  if (take > count) take = count;
  for (int64_t r = 0; r < take; ++r) {
    int64_t src_row = s->order[start + r];
    if (sc.type == kFloat32) {
      std::memcpy(static_cast<float*>(out_values) + r * sc.max_len,
                  sc.f32.data() + src_row * sc.max_len,
                  sizeof(float) * static_cast<size_t>(sc.max_len));
    } else {
      std::memcpy(static_cast<int64_t*>(out_values) + r * sc.max_len,
                  sc.i64.data() + src_row * sc.max_len,
                  sizeof(int64_t) * static_cast<size_t>(sc.max_len));
    }
    if (out_lens) out_lens[r] = sc.lens[src_row];
  }
  return take;
}

int64_t ingest_size(void* h) {
  Store* s = static_cast<Store*>(h);
  return s ? static_cast<int64_t>(s->order.size()) : -1;
}

const char* ingest_error(void* h) {
  Store* s = static_cast<Store*>(h);
  return s ? s->error.c_str() : "null store";
}

// Fisher–Yates over the sample permutation (the data never moves — the
// reference's global_shuffle also permutes channel order, data_set.h:
// global shuffle path).  The permutation restarts from identity, so a
// given seed yields the same order regardless of prior shuffles.
void ingest_shuffle(void* h, uint64_t seed) {
  Store* s = static_cast<Store*>(h);
  if (!s) return;
  std::mt19937_64 rng(seed);
  int64_t n = static_cast<int64_t>(s->order.size());
  for (int64_t i = 0; i < n; ++i) s->order[i] = i;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
    std::swap(s->order[i], s->order[j]);
  }
}

// Copy up to `count` samples starting at permutation position `start`
// into `out` (count*ncols f64, caller-allocated).  Returns rows written;
// 0 = past the end.  The CALLER owns the cursor — concurrent iterators
// over one store each keep their own position.
int64_t ingest_copy_rows(void* h, double* out, int64_t start, int64_t count) {
  Store* s = static_cast<Store*>(h);
  if (!s || count <= 0 || start < 0) return 0;
  int64_t n = static_cast<int64_t>(s->order.size());
  int64_t take = n - start;
  if (take <= 0) return 0;
  if (take > count) take = count;
  for (int64_t r = 0; r < take; ++r) {
    const double* src = s->arena.data() + s->order[start + r] * s->ncols;
    std::memcpy(out + r * s->ncols, src,
                sizeof(double) * static_cast<size_t>(s->ncols));
  }
  return take;
}

void ingest_clear(void* h) {
  Store* s = static_cast<Store*>(h);
  if (!s) return;
  s->arena.clear();
  s->arena.shrink_to_fit();
  for (auto& sl : s->slots) {
    sl.f32.clear();
    sl.f32.shrink_to_fit();
    sl.i64.clear();
    sl.i64.shrink_to_fit();
    sl.lens.clear();
    sl.lens.shrink_to_fit();
  }
  s->order.clear();
  s->error.clear();
}

}  // extern "C"

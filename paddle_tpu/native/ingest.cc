// Native ingest engine — the TPU framework's counterpart of the reference's
// C++ Dataset/DataFeed stack (paddle/fluid/framework/data_set.h:157
// InMemoryDataset, data_feed.h:302 InMemoryDataFeed, MultiSlotDataFeed):
// multithreaded file-sharded parsing into an in-memory sample store, global
// shuffle, and dense minibatch assembly — all off the Python interpreter.
//
// Format: numeric text, one sample per line, fields separated by spaces,
// tabs or commas; every line must have exactly the configured column count
// (fixed-width dense — the reference's ragged LoD slots map to padding/
// bucketing on TPU, SURVEY §7g).  Values are stored as float64 so integer
// ids up to 2^53 round-trip exactly.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  int64_t ncols = 0;
  std::vector<double> arena;        // nsamples * ncols, row-major
  std::vector<int64_t> order;       // shuffle permutation
  std::string error;                // first error, if any
};

// One reader thread: parse its share of files into a private arena.
void ParseFiles(const std::vector<std::string>* files, size_t begin,
                size_t stride, int64_t ncols, std::vector<double>* out,
                std::atomic<bool>* failed, std::mutex* err_mu,
                std::string* err) {
  for (size_t fi = begin; fi < files->size(); fi += stride) {
    if (failed->load(std::memory_order_relaxed)) return;
    FILE* f = std::fopen((*files)[fi].c_str(), "r");
    if (!f) {
      std::lock_guard<std::mutex> g(*err_mu);
      if (err->empty())
        *err = "cannot open " + (*files)[fi] + ": " + std::strerror(errno);
      failed->store(true);
      return;
    }

    int64_t lineno = 0;
    auto parse_line = [&](const char* p) -> bool {  // false = abort file
      ++lineno;
      int64_t got = 0;
      bool blank = true;
      while (*p) {
        while (*p == ' ' || *p == '\t' || *p == ',' || *p == '\r') ++p;
        if (*p == '\0' || *p == '\n') break;
        blank = false;
        char* end = nullptr;
        double v = std::strtod(p, &end);
        if (end == p) {
          std::lock_guard<std::mutex> g(*err_mu);
          if (err->empty())
            *err = (*files)[fi] + ":" + std::to_string(lineno) +
                   ": unparsable field near '" +
                   std::string(p).substr(0, 16) + "'";
          failed->store(true);
          return false;
        }
        out->push_back(v);
        ++got;
        p = end;
      }
      if (blank) return true;  // skip empty lines
      if (got != ncols) {
        std::lock_guard<std::mutex> g(*err_mu);
        if (err->empty())
          *err = (*files)[fi] + ":" + std::to_string(lineno) + ": expected " +
                 std::to_string(ncols) + " columns, got " +
                 std::to_string(got);
        failed->store(true);
        return false;
      }
      return true;
    };

    char buf[1 << 16];
    std::string pending;
    bool aborted = false;
    while (std::fgets(buf, sizeof(buf), f)) {
      size_t blen = std::strlen(buf);
      const char* p = buf;
      if (!pending.empty() || (blen + 1 == sizeof(buf) &&
                               buf[blen - 1] != '\n' && !std::feof(f))) {
        // rare path: a line longer than the read buffer
        pending += buf;
        if (pending.back() != '\n' && !std::feof(f)) continue;
        p = pending.c_str();
      }
      if (!parse_line(p)) {
        aborted = true;
        break;
      }
      pending.clear();
    }
    // a final unterminated line can be left in `pending` when its length
    // is an exact multiple of the read buffer (fgets fills the buffer
    // without seeing EOF) — parse it, don't drop it
    if (!aborted && !pending.empty() && !parse_line(pending.c_str()))
      aborted = true;
    std::fclose(f);
    if (aborted) return;
  }
}

}  // namespace

extern "C" {

// Returns an opaque store handle, or 0 on allocation failure.
void* ingest_create(int64_t ncols) {
  if (ncols <= 0) return nullptr;
  Store* s = new (std::nothrow) Store();
  if (!s) return nullptr;
  s->ncols = ncols;
  return s;
}

void ingest_destroy(void* h) { delete static_cast<Store*>(h); }

// Parse `nfiles` paths with `nthreads` workers.  Thread k takes files
// k, k+n, k+2n... (file-sharded, like the reference's per-thread channel
// split, data_set.h filelist distribution).  Appends to the store.
// Returns number of samples loaded, or -1 (check ingest_error).
int64_t ingest_load(void* h, const char** paths, int64_t nfiles,
                    int64_t nthreads) {
  Store* s = static_cast<Store*>(h);
  if (!s) return -1;
  s->error.clear();  // a previous failed load's message must not shadow ours
  std::vector<std::string> files(paths, paths + nfiles);
  if (nthreads < 1) nthreads = 1;
  if (nthreads > nfiles) nthreads = nfiles;
  std::vector<std::vector<double>> parts(nthreads);
  std::vector<std::thread> workers;
  std::atomic<bool> failed(false);
  std::mutex err_mu;
  for (int64_t t = 0; t < nthreads; ++t) {
    workers.emplace_back(ParseFiles, &files, t, nthreads, s->ncols, &parts[t],
                         &failed, &err_mu, &s->error);
  }
  for (auto& w : workers) w.join();
  if (failed.load()) return -1;
  int64_t before = static_cast<int64_t>(s->arena.size()) / s->ncols;
  size_t total = s->arena.size();
  for (auto& p : parts) total += p.size();
  s->arena.reserve(total);
  for (auto& p : parts) {
    s->arena.insert(s->arena.end(), p.begin(), p.end());
    p.clear();
    p.shrink_to_fit();
  }
  int64_t n = static_cast<int64_t>(s->arena.size()) / s->ncols;
  s->order.resize(n);
  for (int64_t i = 0; i < n; ++i) s->order[i] = i;
  return n - before;
}

int64_t ingest_size(void* h) {
  Store* s = static_cast<Store*>(h);
  return s ? static_cast<int64_t>(s->order.size()) : -1;
}

const char* ingest_error(void* h) {
  Store* s = static_cast<Store*>(h);
  return s ? s->error.c_str() : "null store";
}

// Fisher–Yates over the sample permutation (the data never moves — the
// reference's global_shuffle also permutes channel order, data_set.h:
// global shuffle path).  The permutation restarts from identity, so a
// given seed yields the same order regardless of prior shuffles.
void ingest_shuffle(void* h, uint64_t seed) {
  Store* s = static_cast<Store*>(h);
  if (!s) return;
  std::mt19937_64 rng(seed);
  int64_t n = static_cast<int64_t>(s->order.size());
  for (int64_t i = 0; i < n; ++i) s->order[i] = i;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
    std::swap(s->order[i], s->order[j]);
  }
}

// Copy up to `count` samples starting at permutation position `start`
// into `out` (count*ncols f64, caller-allocated).  Returns rows written;
// 0 = past the end.  The CALLER owns the cursor — concurrent iterators
// over one store each keep their own position.
int64_t ingest_copy_rows(void* h, double* out, int64_t start, int64_t count) {
  Store* s = static_cast<Store*>(h);
  if (!s || count <= 0 || start < 0) return 0;
  int64_t n = static_cast<int64_t>(s->order.size());
  int64_t take = n - start;
  if (take <= 0) return 0;
  if (take > count) take = count;
  for (int64_t r = 0; r < take; ++r) {
    const double* src = s->arena.data() + s->order[start + r] * s->ncols;
    std::memcpy(out + r * s->ncols, src,
                sizeof(double) * static_cast<size_t>(s->ncols));
  }
  return take;
}

void ingest_clear(void* h) {
  Store* s = static_cast<Store*>(h);
  if (!s) return;
  s->arena.clear();
  s->arena.shrink_to_fit();
  s->order.clear();
  s->error.clear();
}

}  // extern "C"

"""Native runtime components (C++, ctypes-bound).

The reference's runtime is C++ where it matters for throughput — the
ingest stack above all (framework/data_set.h, data_feed.h run the whole
file→shuffle→batch path without Python in the loop).  This package holds
the TPU framework's native equivalents.  pybind11 isn't available in this
image, so the ABI is plain C over ctypes.

The shared library builds from the in-tree source on first use (g++ -O2)
and is cached under ``~/.cache/paddle_tpu/native`` keyed by a source hash —
the same "compile on first touch, cache after" contract as XLA kernels.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

__all__ = ["ingest_lib", "c_api_path", "NativeBuildError"]

_CACHE_DIR = os.path.expanduser("~/.cache/paddle_tpu/native")
_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ingest.cc")

_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _build(src: str, tag: str, extra_flags=(), extra_srcs=()) -> str:
    h = hashlib.sha256()
    for p in (src,) + tuple(extra_srcs):
        with open(p, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()[:16]
    out = os.path.join(_CACHE_DIR, f"{tag}-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_CACHE_DIR, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}-{threading.get_ident()}"
    cmd = (["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            src] + list(extra_flags) + ["-o", tmp])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
    except FileNotFoundError as e:
        raise NativeBuildError(f"g++ not available: {e}")
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-2000:]}")
    os.replace(tmp, out)  # atomic publish; concurrent builders converge
    return out


def ingest_lib() -> ctypes.CDLL:
    """The ingest engine library, built/cached on first call."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        path = _build(_SRC, "ingest")
        lib = ctypes.CDLL(path)
        lib.ingest_create.restype = ctypes.c_void_p
        lib.ingest_create.argtypes = [ctypes.c_int64]
        lib.ingest_destroy.argtypes = [ctypes.c_void_p]
        lib.ingest_load.restype = ctypes.c_int64
        lib.ingest_load.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.c_int64, ctypes.c_int64]
        lib.ingest_size.restype = ctypes.c_int64
        lib.ingest_size.argtypes = [ctypes.c_void_p]
        lib.ingest_error.restype = ctypes.c_char_p
        lib.ingest_error.argtypes = [ctypes.c_void_p]
        lib.ingest_shuffle.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ingest_copy_rows.restype = ctypes.c_int64
        lib.ingest_copy_rows.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_double),
                                         ctypes.c_int64, ctypes.c_int64]
        lib.ingest_clear.argtypes = [ctypes.c_void_p]
        lib.ingest_create_multislot.restype = ctypes.c_void_p
        lib.ingest_create_multislot.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        lib.ingest_copy_slot.restype = ctypes.c_int64
        lib.ingest_copy_slot.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


_CAPI_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "capi.cc")


def c_api_path() -> str:
    """Build (once, cached) and return the C inference ABI shared library
    (paddle_tpu_c.h).  Unlike :func:`ingest_lib` this is linked by C/Go
    programs, not loaded via ctypes here — the embedded interpreter would
    clash with the running one."""
    # flags from the RUNNING interpreter (sysconfig), not whatever
    # python3-config is on PATH — a mismatched system interpreter would
    # embed a runtime that cannot import this package
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ldver = sysconfig.get_config_var("LDVERSION") \
        or sysconfig.get_config_var("VERSION")
    syslibs = ((sysconfig.get_config_var("LIBS") or "").split()
               + (sysconfig.get_config_var("SYSLIBS") or "").split())
    flags = [f"-I{inc}", f"-I{os.path.dirname(_CAPI_SRC)}"]
    if libdir:
        flags.append(f"-L{libdir}")
    flags.append(f"-lpython{ldver}")
    flags += syslibs
    hdr = os.path.join(os.path.dirname(_CAPI_SRC), "paddle_tpu_c.h")
    with _lock:
        return _build(_CAPI_SRC, "capi", extra_flags=flags,
                      extra_srcs=(hdr,))

"""paddle_tpu.hapi — high-level Model API (paddle.hapi parity).

Reference: python/paddle/hapi/ (model.py, callbacks.py).
"""
from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401

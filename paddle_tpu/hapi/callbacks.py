"""hapi callbacks.

Parity: python/paddle/hapi/callbacks.py — Callback (:71), CallbackList,
ProgBarLogger (:237), ModelCheckpoint (:450), LRScheduler (:524),
EarlyStopping (:608).
"""
from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np

__all__ = [
    "Callback",
    "CallbackList",
    "ProgBarLogger",
    "ModelCheckpoint",
    "LRScheduler",
    "EarlyStopping",
    "config_callbacks",
]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # train
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    # eval
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    # predict
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Periodic stdout logging (the reference renders a progress bar; here a
    compact line every ``log_freq`` steps — terminal-friendly under drivers)."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()
        self._window_t0 = time.time()
        self._window_steps = 0
        self._window_samples = 0

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            # device scalars (0-d arrays) sync here — i.e. only when printed
            if isinstance(v, (numbers.Number, np.generic)) or getattr(v, "ndim", None) == 0:
                parts.append(f"{k}: {float(v):.4f}")
            elif isinstance(v, (list, tuple)) and v and isinstance(v[0], numbers.Number):
                parts.append(f"{k}: " + "/".join(f"{float(x):.4f}" for x in v))
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        # per-step timing (ref capability: profiler.h step stats; VERDICT
        # asked for step timing in callbacks so perf work isn't blind)
        self._window_steps += 1
        bs = (logs or {}).get("batch_size")
        if isinstance(bs, numbers.Number):
            self._window_samples += int(bs)
        if self.verbose and step % self.log_freq == 0:
            # sync on the window's last loss BEFORE reading the clock —
            # steps dispatch async, so without this dt measures host
            # dispatch (~µs) instead of device time
            v = (logs or {}).get("loss")
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()
            dt = time.time() - self._window_t0
            perf = ""
            if self._window_steps and dt > 0:
                perf = f" - {dt * 1e3 / self._window_steps:.1f} ms/step"
                if self._window_samples:
                    perf += f" - {self._window_samples / dt:.1f} samples/s"
            print(f"Epoch {self.epoch}: step {step}/{self.steps or '?'} - "
                  f"{self._fmt(logs)}{perf}")
            self._window_t0 = time.time()
            self._window_steps = 0
            self._window_samples = 0

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"Epoch {epoch} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step and/or by_epoch)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        assert by_step != by_epoch, "step either per batch or per epoch"
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            -np.inf if self.mode == "max" else np.inf
        )
        self.model.stop_training = False

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(cur)
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement for {self.wait} evals")


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=10, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or [],
    })
    return lst

"""paddle.Model — the high-level train/eval/predict API.

Parity: python/paddle/hapi/model.py:813 (Model.prepare/fit/evaluate/predict/
save/load/train_batch/eval_batch/predict_batch/summary).

TPU-native design: the reference maintains TWO adapters (a static-graph one
building Programs per mode, :254, and a dygraph one, :639).  Here there is
exactly one path: ``prepare()`` builds jit-compiled step functions

    train_step(params, opt_state, buffers, key, lr, *batch)
      → loss, outputs, new_params, new_opt_state, new_buffers

from ``nn.functional_call`` + ``jax.value_and_grad`` + the functional
optimizer — the whole forward/backward/update is ONE fused XLA executable
(replacing the per-op Executor loop, executor.cc:474).  Old params/opt
buffers are donated, so the update is in-place on device memory.

State lives functionally during fit() and is written back to the Layer's
Parameter boxes after every batch (cheap rebinding of device arrays), so
eager inspection (`model.network.weight`) always sees current values.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _random
from ..framework import serialization
from ..framework.flags import flag as _flag
from ..framework.errors import InvalidArgumentError
from ..metric import Metric
from ..nn.layer_base import Layer, functional_call
from ..optimizer.optimizer import Optimizer
from . import callbacks as _callbacks_mod

__all__ = ["Model"]


def _tuplize(x):
    return x if isinstance(x, (tuple, list)) else (x,)


class Model:
    """Wrap a Layer with train/eval/predict conveniences.

    ``inputs``/``labels`` may be specs (lists) — only their *count* matters
    here (how to split a dataloader batch); shapes/dtypes come from tracing.
    """

    def __init__(self, network: Layer, inputs=None, labels=None):
        self._steps_per_execution = 1
        self._multi_train_step = None
        from ..static import InputSpec

        self.network = network
        self._n_inputs = len(_tuplize(inputs)) if inputs is not None else None
        # shape-carrying entries (InputSpec or example tensors) enable
        # save(training=False); name-only specs don't.  All-or-nothing:
        # a partial spec list would export with the wrong arity.
        self._input_specs = None
        if inputs is not None:
            ins = _tuplize(inputs)
            specs = [s for s in ins
                     if isinstance(s, InputSpec)
                     or (hasattr(s, "shape") and hasattr(s, "dtype"))]
            if len(specs) == len(ins):
                self._input_specs = specs
        self._n_labels = len(_tuplize(labels)) if labels is not None else 1
        self._optimizer: Optional[Optimizer] = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._eval_step = None
        self._predict_step = None
        self._opt_state = None
        self._plan = None
        self.stop_training = False
        self._save_dir = None
        self._finite_check = None  # lazily-built FLAGS_check_nan_inf probe

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer: Optional[Optimizer] = None, loss=None,
                metrics: Optional[Sequence[Metric]] = None, amp_configs=None,
                steps_per_execution: int = 1):
        if loss is not None and not (isinstance(loss, Layer) or callable(loss)):
            raise InvalidArgumentError("loss must be a Layer or callable")
        steps_per_execution = int(steps_per_execution)
        if steps_per_execution < 1:
            raise InvalidArgumentError("steps_per_execution must be >= 1")
        if steps_per_execution > 1 and metrics:
            raise InvalidArgumentError(
                "steps_per_execution > 1 cannot update host-side metrics "
                "per inner step; drop metrics or keep it at 1")
        if steps_per_execution > 1 and optimizer is not None and \
                getattr(optimizer, "lr_scheduler", None) is not None:
            import warnings

            warnings.warn(
                "steps_per_execution > 1: the learning rate is read once per "
                "execution, so an LRScheduler advances per execution (every "
                f"{steps_per_execution} optimizer steps), not per step — "
                "matching Keras. Scale the scheduler's step granularity "
                "accordingly.", UserWarning)
        self._steps_per_execution = steps_per_execution
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = list(metrics or [])
        self._metrics_precomputed = False  # set by the 1F1B path
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise InvalidArgumentError(f"metric {m!r} is not a Metric")

        net = self.network
        loss_fn = loss

        if isinstance(amp_configs, str):  # paddle-parity: amp_configs="O1"
            amp_configs = {"level": amp_configs}
        amp_cfg = dict(amp_configs or {})
        # keep only the autocast policy knobs; scaler keys (init_loss_scaling
        # etc.) belong to GradScaler and are irrelevant for bf16
        _AC_KEYS = {"enable", "custom_white_list", "custom_black_list",
                    "level", "dtype"}
        amp_cfg = {k: v for k, v in amp_cfg.items() if k in _AC_KEYS}
        use_amp = bool(amp_cfg) and amp_cfg.get("level", "O1") != "O0"

        def forward_loss(params, buffers, key, training, *batch):
            import contextlib

            from ..amp import auto_cast as _ac

            inputs, labels = self._split_batch(batch)
            ctx = _ac(**amp_cfg) if use_amp else contextlib.nullcontext()
            with ctx:  # loss layers are black-listed → compute in f32
                out, new_bufs = functional_call(
                    net, params, *inputs, buffers=buffers, rngs=key,
                    training=training, return_buffers=True,
                )
                outs = _tuplize(out)
                if loss_fn is not None:
                    loss_val = loss_fn(*(tuple(outs) + tuple(labels)))
                else:
                    loss_val = jnp.zeros(())
            return loss_val, (out, new_bufs)

        opt = optimizer

        from ..framework.selected_rows import (build_sparse_step,
                                               sparse_param_names)

        sparse_map = sparse_param_names(net)  # id(box) -> dotted name

        def train_step(params, opt_state, buffers, key, lr, *batch):
            fl = lambda p: forward_loss(p, buffers, key, True, *batch)
            if sparse_map:
                # Embedding(sparse=True) present: two-phase differentiation
                # producing SelectedRows table grads — no O(vocab) cotangent
                names = set(sparse_map.values())
                shapes = {k: tuple(v.shape) for k, v in params.items()
                          if k in names}
                (loss_val, (out, new_bufs)), grads = build_sparse_step(
                    fl, sparse_map, shapes)(params)
            else:
                grad_fn = jax.value_and_grad(fl, has_aux=True)
                (loss_val, (out, new_bufs)), grads = grad_fn(params)
            plan = self._plan
            if plan is not None and hasattr(plan, "transform_gradients"):
                # comm-precision plans reduce per-replica grads explicitly
                # (inside their shard_map body) — e.g. fp16_allreduce
                grads = plan.transform_gradients(grads)
            new_params, new_opt_state = opt.update(grads, opt_state, params, lr=lr)
            return loss_val, out, new_params, new_opt_state, new_bufs

        def eval_step(params, buffers, *batch):
            loss_val, (out, _) = forward_loss(params, buffers, None, False, *batch)
            return loss_val, out

        def predict_step(params, buffers, *inputs):
            out = functional_call(net, params, *inputs, buffers=buffers,
                                  training=False)
            return out

        # fleet path: distributed_optimizer tagged the optimizer — lower the
        # strategy to mesh shardings (replaces meta-opt minimize, SURVEY §3.4)
        self._plan = None
        use_1f1b, pipe_micro = False, None
        strategy = getattr(optimizer, "_fleet_strategy", None)
        if strategy is not None:
            from ..distributed.fleet.plan import ShardingPlan

            if strategy.recompute:
                # reference: RecomputeOptimizer (fluid/optimizer.py:4547) —
                # here jax.checkpoint on the repeated block layers
                from ..nn.recompute import apply_recompute

                rc_cfg = strategy.recompute_configs or {}
                wrapped = apply_recompute(
                    net, rc_cfg.get("layer_classes"), rc_cfg.get("policy"))
                if wrapped == 0:
                    import warnings

                    warnings.warn(
                        "strategy.recompute matched no block sublayers — "
                        "pass recompute_configs={'layer_classes': [...]}",
                        RuntimeWarning)
            if strategy.pipeline or strategy.pp_degree > 1:
                # reference: PipelineOptimizer (fluid/optimizer.py:3695) —
                # here the block stack pipelines over the `pipe` mesh axis
                # (distributed/pipeline_parallel.py); plumb the microbatch
                # count to every pipeline-capable sublayer
                pc = strategy.pipeline_configs or {}
                micro = int(pc.get("accumulate_steps", 0)) or None
                sched = str(pc.get("schedule", "gpipe")).lower()
                if sched not in ("gpipe", "f-then-b", "1f1b"):
                    # validate at use time too: the paddle idiom assigns
                    # pipeline_configs after construction, bypassing
                    # DistributedStrategy.__post_init__
                    raise InvalidArgumentError(
                        "pipeline_configs['schedule'] must be 'gpipe'/"
                        f"'F-then-B'/'1F1B', got {sched!r}")
                if sched == "1f1b":
                    if not hasattr(net, "pipeline_decompose"):
                        raise InvalidArgumentError(
                            "pipeline schedule '1f1b' needs the network to "
                            "implement pipeline_decompose() -> {'pre', "
                            "'blocks', 'post'} (GPTForCausalLM does); "
                            "in-forward pipelining supports GPipe only")
                    if list(net.named_buffers()):
                        raise InvalidArgumentError(
                            "1F1B pipeline sections must be buffer-free "
                            "(running-stat updates cannot cross the "
                            "interleaved schedule)")
                    use_1f1b, pipe_micro = True, micro
                hits = 0
                for sub in net.sublayers(include_self=True):
                    if hasattr(sub, "pipeline_microbatches"):
                        sub.pipeline_microbatches = micro
                        hits += 1
                if hits == 0:
                    import warnings

                    warnings.warn(
                        "strategy.pipeline: no sublayer exposes a "
                        "`pipeline_microbatches` knob — the model will not "
                        "pipeline (GPTModel-style block stacks do)",
                        RuntimeWarning)
            if strategy.sequence_parallel:
                # route attention through ring/Ulysses over the sep axis
                sp_cfg = strategy.sequence_parallel_configs or {}
                method = sp_cfg.get("method", "ring")
                hits = 0
                for sub in net.sublayers(include_self=True):
                    if hasattr(sub, "sequence_parallel") and hasattr(sub, "qkv"):
                        sub.sequence_parallel = method
                        hits += 1
                if hits == 0:
                    import warnings

                    warnings.warn(
                        "strategy.sequence_parallel found no attention "
                        "layers exposing a `sequence_parallel` knob",
                        RuntimeWarning)
            if strategy.a_sync and int(
                    (strategy.a_sync_configs or {}).get("k_steps", 0)) > 0:
                # reference Geo-SGD (geo_sgd_transpiler.py:1,
                # communicator.h:413): local steps + periodic parameter-
                # delta push — see fleet/geosgd.py (pure async k_steps=0
                # was rejected at distributed_optimizer time)
                from ..distributed.fleet.geosgd import GeoSgdPlan

                self._plan = GeoSgdPlan(net, optimizer, strategy)
            elif strategy.adaptive_localsgd:
                # reference: localsgd_optimizer.py:194 — LocalSGD whose
                # sync period adapts to loss progress (fleet/localsgd.py)
                from ..distributed.fleet.localsgd import AdaptiveLocalSGDPlan

                self._plan = AdaptiveLocalSGDPlan(net, optimizer, strategy)
            elif strategy.localsgd:
                # reference: localsgd_optimizer.py — per-replica training
                # with periodic model averaging (see fleet/localsgd.py)
                from ..distributed.fleet.localsgd import LocalSGDPlan

                self._plan = LocalSGDPlan(net, optimizer, strategy)
            elif strategy.dgc:
                # reference: dgc_optimizer.py — top-k gradient compression
                # with error feedback (see fleet/dgc.py)
                from ..distributed.fleet.dgc import DGCPlan

                self._plan = DGCPlan(net, optimizer, strategy)
            elif strategy.fp16_allreduce:
                # reference: fp16_allreduce_optimizer.py — cast grads for
                # the cross-replica reduction (see fleet/fp16_allreduce.py)
                from ..distributed.fleet.fp16_allreduce import (
                    Fp16AllReducePlan)

                self._plan = Fp16AllReducePlan(net, optimizer, strategy)
            else:
                self._plan = ShardingPlan(net, optimizer, strategy)
            self._plan.place_network()
            # Embedding(sparse=True) composes with the gradient-transforming
            # strategies since r5: fp16_allreduce and DGC route SelectedRows
            # leaves through the sparse allreduce (all_gather_rows) and
            # leave compression to the dense leaves — matching
            # details/sparse_all_reduce_op_handle.cc:1

        if use_1f1b:
            # the production 1F1B path (VERDICT r3 #2, ref:
            # section_worker.cc:82-230): the train step IS the interleaved
            # schedule — per-microbatch fwd/bwd in one lax.scan over the
            # `pipe` ring, embedding vjp fed by the schedule's dx, head/loss
            # grads accumulated on the last stage, optimizer update in the
            # same jitted computation
            from ..distributed.pipeline_parallel import pipeline_train_step

            d = net.pipeline_decompose()
            blocks, pre_call, post_call = d["blocks"], d["pre"], d["post"]
            box_names = {id(box): n for n, box in net.named_parameters()}
            block_maps = [
                {n: box_names[id(b_)] for n, b_ in blk.named_parameters()}
                for blk in blocks]
            inner = sorted(block_maps[0])
            block_fullnames = {fn for m in block_maps for fn in m.values()}

            def train_step(params, opt_state, buffers, key, lr, *batch):
                inputs, labels = self._split_batch(batch)
                other = {k: v for k, v in params.items()
                         if k not in block_fullnames}
                stacked = {n: jnp.stack([params[m[n]] for m in block_maps])
                           for n in inner}
                x_emb, pre_vjp = jax.vjp(lambda op: functional_call(
                    net, op, *inputs, rngs=key, training=True,
                    call=pre_call), other)

                def head_loss(y_mb, lbl_mb, op):
                    logits = functional_call(net, op, y_mb, training=True,
                                             call=post_call)
                    return loss_fn(*(_tuplize(logits) + tuple(lbl_mb)))

                metrics = self._metrics

                def head_aux(y_mb, lbl_mb):
                    # fetch-based metrics ride the schedule: compute() per
                    # microbatch on the last stage (ref SectionWorker metric
                    # fetches, section_worker.cc:82-230); update() runs on
                    # the host with the concatenated rows — full-batch
                    # logits are never assembled
                    logits = functional_call(net, other, y_mb,
                                             training=True, call=post_call)
                    return tuple(
                        _tuplize(m.compute(_tuplize(logits)[0], *lbl_mb))
                        for m in metrics)

                loss_val, g_blocks, dx, g_head, *aux = pipeline_train_step(
                    blocks, x_emb, tuple(labels), None,
                    num_microbatches=pipe_micro, schedule="1f1b",
                    params=stacked, head_params=other,
                    head_loss_fn=head_loss,
                    head_aux_fn=head_aux if metrics else None,
                    return_dx=True, rng_key=key)
                (d_pre,) = pre_vjp(dx.astype(x_emb.dtype))
                grads = {}
                for n in inner:
                    for i, m in enumerate(block_maps):
                        grads[m[n]] = g_blocks[n][i]
                for k2 in other:
                    grads[k2] = (jnp.asarray(d_pre[k2], jnp.float32)
                                 + jnp.asarray(g_head[k2], jnp.float32))
                new_params, new_opt_state = opt.update(grads, opt_state,
                                                       params, lr=lr)
                # out = the per-metric compute() rows (full-batch order);
                # _update_metrics feeds them straight to update()
                out = aux[0] if aux else loss_val
                return loss_val, out, new_params, new_opt_state, buffers

            if self._metrics:
                self._metrics_precomputed = True

        if optimizer is not None:
            if self._plan is not None:
                if self._steps_per_execution > 1:
                    raise InvalidArgumentError(
                        "steps_per_execution > 1 does not yet compose with "
                        "fleet strategies (the plan wraps the single-step "
                        "executable); run with the default strategy")
                self._train_step = self._plan.jit_train_step(train_step)
            else:
                # donate old params/opt_state/buffers: the update happens
                # in-place in device memory
                self._train_step = jax.jit(train_step, donate_argnums=(0, 1, 2))
            if self._steps_per_execution > 1:
                k = self._steps_per_execution

                # one dispatch runs k train steps under lax.scan — the
                # Keras steps_per_execution idea, which matters doubly on
                # TPU where a ~50ms step can be dominated by host dispatch
                # (the LR is read once per execution; schedulers advance
                # between executions, as in Keras)
                def multi_step(params, opt_state, buffers, key, lr,
                               *stacked):
                    keys = jax.random.split(key, k)

                    def body(carry, xs):
                        p, s, b = carry
                        key_t = xs[0]
                        batch = xs[1:]
                        loss_t, _, p, s, b = train_step(p, s, b, key_t, lr,
                                                        *batch)
                        return (p, s, b), loss_t

                    (params, opt_state, buffers), losses = jax.lax.scan(
                        body, (params, opt_state, buffers),
                        (keys,) + stacked)
                    return losses, params, opt_state, buffers

                self._multi_train_step = jax.jit(
                    multi_step, donate_argnums=(0, 1, 2))
        self._eval_step = jax.jit(eval_step)
        self._predict_step = jax.jit(predict_step)
        self._opt_state = None
        return self

    def _split_batch(self, batch):
        n_in = self._n_inputs
        if n_in is None:
            n_in = max(len(batch) - self._n_labels, 1)
        return batch[:n_in], batch[n_in:]

    # -- functional state plumbing -------------------------------------------
    def _pull_state(self):
        params = self.network.param_pytree(trainable_only=True)
        buffers = self.network.buffer_pytree()
        return params, buffers

    def _push_state(self, params, buffers):
        boxes = dict(self.network.named_parameters())
        for name, v in params.items():
            boxes[name].value = v
        bufs = dict(self.network.named_buffers())
        for name, v in buffers.items():
            bufs[name].value = v

    def _ensure_opt_state(self, params, buffers=None):
        if self._opt_state is None:
            if self._plan is not None:
                self._opt_state = self._plan.init_opt_state(
                    self._optimizer, params, buffers)
            else:
                self._opt_state = self._optimizer.init(params)

    def _train_batches_device(self, batches):
        """Run len(batches) == steps_per_execution train steps in ONE
        dispatch; returns the per-step loss vector (device array)."""
        if self._multi_train_step is None:
            raise InvalidArgumentError(
                "call prepare(optimizer=..., loss=..., "
                "steps_per_execution=k) first")
        from ..distributed.heartbeat import maybe_beat

        maybe_beat()
        stacked = tuple(
            jnp.stack([jnp.asarray(b[i]) for b in batches])
            for i in range(len(batches[0])))
        params, buffers = self._pull_state()
        self._ensure_opt_state(params, buffers)
        key = _random.default_generator().next_key()
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        losses, params, self._opt_state, buffers = self._multi_train_step(
            params, self._opt_state, buffers, key, lr, *stacked)
        self._push_state(params, buffers)
        from ..framework import monitor as _monitor

        _monitor.stat_add("total_train_steps", len(batches))
        if _flag("check_nan_inf"):
            self._check_nan_inf(losses, params, buffers)
        if _flag("benchmark"):
            jax.block_until_ready(losses)
        return losses

    # -- batch-level API -----------------------------------------------------
    def train_batch(self, inputs, labels=None):
        """One optimization step; returns (loss, metrics_results)."""
        loss_val, metrics = self._train_batch_device(inputs, labels)
        return float(loss_val), metrics

    def _train_batch_device(self, inputs, labels=None):
        """Like train_batch but leaves the loss as a device scalar — no host
        sync, so fit()'s loop can dispatch ahead of the device (the loss is
        only materialized at logging points)."""
        if self._train_step is None:
            raise InvalidArgumentError("call prepare(optimizer=..., loss=...) first")
        from ..distributed.heartbeat import maybe_beat

        maybe_beat()  # liveness signal for the launch watchdog (no-op
        #               unless PADDLE_TPU_HEARTBEAT_FILE is set)
        batch = tuple(_tuplize(inputs)) + tuple(_tuplize(labels) if labels is not None else ())
        if self._plan is not None:
            batch = self._plan.shard_batch(batch)
        else:
            batch = tuple(jnp.asarray(b) for b in batch)
        params, buffers = self._pull_state()
        self._ensure_opt_state(params, buffers)
        key = _random.default_generator().next_key()
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        loss_val, out, params, self._opt_state, buffers = self._train_step(
            params, self._opt_state, buffers, key, lr, *batch)
        self._push_state(params, buffers)
        from ..framework import monitor as _monitor

        _monitor.stat_add("total_train_steps")
        if _flag("check_nan_inf"):
            # debug mode (ref: FLAGS_check_nan_inf nan sweep,
            # framework/details/nan_inf_utils.h:33) — syncs every step
            self._check_nan_inf(loss_val, params, buffers)
        if _flag("benchmark"):
            jax.block_until_ready(loss_val)
        metrics = self._update_metrics(
            out, batch[len(_tuplize(inputs)):],
            precomputed=getattr(self, "_metrics_precomputed", False))
        return loss_val, metrics

    def _check_nan_inf(self, loss_val, params, buffers):
        if self._finite_check is None:
            def all_finite(l, tree):
                leaves = jax.tree_util.tree_leaves(tree)
                ok = jnp.isfinite(l).all()
                if leaves:
                    ok = jnp.logical_and(
                        ok, jnp.array([jnp.isfinite(p).all()
                                       for p in leaves]).all())
                return ok

            self._finite_check = jax.jit(all_finite)
        if not bool(self._finite_check(loss_val, (params, buffers))):
            bad = [] if np.isfinite(np.asarray(loss_val)).all() else ["loss"]
            for tree in (params, buffers):
                bad += [n for n, v in tree.items()
                        if not np.isfinite(np.asarray(v)).all()]
            raise RuntimeError(
                f"FLAGS_check_nan_inf: non-finite values after train step "
                f"in: {bad[:8]}{' …' if len(bad) > 8 else ''}")

    def eval_batch(self, inputs, labels=None):
        loss_val, metrics = self._eval_batch_device(inputs, labels)
        return float(loss_val), metrics

    def _eval_batch_device(self, inputs, labels=None):
        """eval_batch without the loss host-sync — the loss stays a device
        scalar so evaluate()'s loop dispatches ahead of the device, the
        same way fit() does.  NOTE: metrics (if prepared) still sync per
        batch — Metric.compute/update are host-side numpy by design; the
        async win applies to loss-only evaluation."""
        if self._eval_step is None:
            raise InvalidArgumentError("call prepare(loss=...) first")
        from ..distributed.heartbeat import maybe_beat

        maybe_beat()  # eval between epochs must not read as a hang
        batch = tuple(_tuplize(inputs)) + tuple(_tuplize(labels) if labels is not None else ())
        if self._plan is not None:
            batch = self._plan.shard_batch(batch)
        else:
            batch = tuple(jnp.asarray(b) for b in batch)
        params, buffers = self._pull_state()
        loss_val, out = self._eval_step(params, buffers, *batch)
        _, labels_part = self._split_batch(batch)
        metrics = self._update_metrics(out, labels_part)
        return loss_val, metrics

    def predict_batch(self, inputs):
        from ..distributed.heartbeat import maybe_beat

        maybe_beat()
        if self._plan is not None:
            inputs = self._plan.shard_batch(tuple(_tuplize(inputs)))
        else:
            inputs = tuple(jnp.asarray(b) for b in _tuplize(inputs))
        params, buffers = self._pull_state()
        return self._predict_step(params, buffers, *inputs)

    def _update_metrics(self, out, labels, precomputed: bool = False):
        results = []
        if precomputed:
            # 1F1B train steps: `out` is the per-metric tuple of compute()
            # rows already produced inside the schedule (full-batch order).
            # Eval/predict assemble full outputs and never take this branch.
            for m, computed in zip(self._metrics, out):
                results.append(m.update(*computed))
            return results
        outs = _tuplize(out)
        for m in self._metrics:
            computed = m.compute(outs[0], *labels)
            results.append(m.update(computed) if not isinstance(computed, tuple)
                           else m.update(*computed))
        return results

    # -- loops ---------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, drop_last, num_workers,
                   allow_partial=False):
        from ..io import DataLoader, Dataset

        if data is None or hasattr(data, "__next__") or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            if self._plan is not None and not allow_partial and not drop_last:
                # a partial final batch can't split across the data shards
                if len(data) % batch_size:
                    import warnings

                    warnings.warn(
                        f"dropping the final partial batch "
                        f"({len(data) % batch_size} samples) — it cannot "
                        f"split across {self._plan.n_data_shards} data "
                        f"shards", RuntimeWarning)
                drop_last = True
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers,
                              return_numpy=True)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        """Train over ``train_data`` (ref: hapi/model.py Model.fit).

        ``drop_last``: drop a final batch smaller than ``batch_size``.
        Under a distributed plan an uneven final batch cannot split across
        the data shards, so it is dropped regardless — pass
        ``drop_last=True`` (or size the dataset to a multiple of
        ``batch_size``) to acknowledge this and silence the warning.
        """
        train_loader = self._as_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers)
        if epochs > 1 and hasattr(train_loader, "__next__"):
            raise InvalidArgumentError(
                "train_data is a one-shot iterator but epochs > 1: epochs "
                "after the first would train on zero batches.  Pass a "
                "Dataset/DataLoader (re-iterable) or epochs=1."
            )
        self._save_dir = save_dir
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        if self._steps_per_execution > 1:
            # the loop below fires callbacks once per EXECUTION, and the
            # exact execution count depends on batch-size raggedness the
            # loader only reveals while iterating — report unknown length
            # rather than a wrong total
            steps = None
        cbks = _callbacks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=self._metrics_names(),
        )
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs: Dict[str, Any] = {}
            spe = self._steps_per_execution

            def _grouped(loader):
                """steps_per_execution batching: yield ("multi", [k
                batches]) for full UNIFORM groups, ("single", batch) for
                ragged tails — both a short group at epoch end and a
                smaller final batch (drop_last=False) that would break
                jnp.stack (and everything when spe == 1)."""
                pending = []
                group_bs = None
                for b in loader:
                    if spe == 1:
                        yield "single", b
                        continue
                    b = _tuplize(b)
                    if pending and np.shape(b[0])[0] != group_bs:
                        for p in pending:  # flush, preserving step order
                            yield "single", p
                        pending = []
                    if not pending:
                        group_bs = np.shape(b[0])[0]
                    pending.append(b)
                    if len(pending) == spe:
                        yield "multi", pending
                        pending = []
                for b in pending:
                    yield "single", b

            for step, (kind, batch) in enumerate(_grouped(train_loader)):
                cbks.on_train_batch_begin(step)
                if kind == "multi":
                    losses = self._train_batches_device(batch)
                    logs = {"loss": losses.mean(),
                            "batch_size": sum(np.shape(b[0])[0]
                                              for b in batch)}
                    cbks.on_train_batch_end(step, logs)
                    if self.stop_training:
                        break
                    continue
                batch = _tuplize(batch)
                n_in = (self._n_inputs if self._n_inputs is not None
                        else max(len(batch) - self._n_labels, 1))
                loss_val, metrics = self._train_batch_device(batch[:n_in], batch[n_in:])
                logs = {"loss": loss_val}  # device scalar; callbacks pull it
                # flatten multi-output metric results (e.g. Accuracy
                # topk=(1,5)) so they pair 1:1 with the flattened names,
                # matching the epoch-end handling
                flat_results = [r for res in metrics for r in _tuplize(res)]
                for name, res in zip(self._metrics_names(), flat_results):
                    logs[name] = res
                logs["batch_size"] = np.asarray(batch[0]).shape[0]
                cbks.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            # epoch-end logs report accumulated metric values
            for m in self._metrics:
                for name, val in zip(_tuplize(m.name()), _tuplize(m.accumulate())):
                    logs[name] = val
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks, _inner=True)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _inner=False):
        loader = self._as_loader(eval_data, batch_size, False, False, num_workers)
        cbks = callbacks if _inner else _callbacks_mod.config_callbacks(
            callbacks, model=self, verbose=verbose, metrics=self._metrics_names())
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        batch_losses = []  # device scalars — loss syncs once, at the end
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            batch = _tuplize(batch)
            n_in = (self._n_inputs if self._n_inputs is not None
                    else max(len(batch) - self._n_labels, 1))
            loss_val, _ = self._eval_batch_device(batch[:n_in], batch[n_in:])
            batch_losses.append(loss_val)
            cbks.on_eval_batch_end(step, {"loss": loss_val})
        total_loss = float(jnp.stack(batch_losses).sum()) if batch_losses else 0.0
        n_batches = len(batch_losses)
        if n_batches == 0:
            import warnings

            warnings.warn(
                "evaluate() saw zero batches (dataset smaller than one "
                "data-parallel batch?) — metrics are meaningless",
                RuntimeWarning)
        logs = {"loss": total_loss / max(n_batches, 1)}
        for m in self._metrics:
            for name, val in zip(_tuplize(m.name()), _tuplize(m.accumulate())):
                logs[name] = val
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, False, num_workers,
                                 allow_partial=True)
        outputs = []
        for batch in loader:
            batch = _tuplize(batch)
            n_in = (self._n_inputs if self._n_inputs is not None else len(batch))
            inputs = batch[:n_in]
            pad = 0
            if self._plan is not None:
                # pad the partial final batch to shardability, slice it off
                # after — predictions stay 1:1 with the input dataset
                n = np.asarray(inputs[0]).shape[0]
                shards = self._plan.n_data_shards
                pad = (-n) % shards
                if pad:
                    inputs = tuple(
                        np.concatenate([np.asarray(b),
                                        np.repeat(np.asarray(b)[-1:], pad, axis=0)])
                        for b in inputs)
            out = self.predict_batch(inputs)
            if pad:
                out = jax.tree_util.tree_map(lambda o: o[:-pad], out)
            outputs.append(jax.tree_util.tree_map(np.asarray, out))
        if stack_outputs and outputs:
            outputs = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(xs, axis=0), *outputs)
        return outputs

    # -- persistence ---------------------------------------------------------
    def save(self, path: str, training: bool = True, input_spec=None):
        """``training=True``: writes ``path.pdparams`` (+ ``path.pdopt``).
        ``training=False``: exports an AOT inference module
        (``path.pdmodel`` + ``path.pdiparams`` — see paddle_tpu.inference;
        reference: hapi Model.save → paddle.jit.save, hapi/model.py:1004).
        serialization.save creates parent directories itself."""
        if not training:
            from ..inference import save_inference_model

            spec = input_spec or self._input_specs
            if spec is None:
                raise InvalidArgumentError(
                    "save(training=False) needs input shapes: pass "
                    "input_spec=[InputSpec(...)] here or declare them in "
                    "Model(inputs=[InputSpec(...)])")
            save_inference_model(path, self.network, spec)
            return
        serialization.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            opt_state = {"state": jax.tree_util.tree_map(np.asarray, self._opt_state)} \
                if self._opt_state is not None else {}
            sched = self._optimizer.lr_scheduler
            if sched is not None:
                opt_state["LR_Scheduler"] = sched.state_dict()
            else:
                opt_state["lr"] = self._optimizer.get_lr()
            serialization.save(opt_state, path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer: bool = False):
        state = serialization.load(path + ".pdparams")
        missing = self.network.set_state_dict(state)
        if missing and not skip_mismatch:
            raise InvalidArgumentError(f"unmatched keys in checkpoint: {missing[:5]}")
        if not reset_optimizer and os.path.exists(path + ".pdopt"):
            opt_state = serialization.load(path + ".pdopt")
            if "state" in opt_state:
                self._opt_state = jax.tree_util.tree_map(
                    jnp.asarray, opt_state["state"])
                if self._plan is not None and hasattr(self._plan,
                                                      "on_state_restored"):
                    self._plan.on_state_restored()
            if self._optimizer is not None:
                sched = self._optimizer.lr_scheduler
                if sched is not None and "LR_Scheduler" in opt_state:
                    sched.set_state_dict(opt_state["LR_Scheduler"])
                elif sched is None and "lr" in opt_state:
                    self._optimizer.set_lr(float(opt_state["lr"]))
        return self

    # -- misc ----------------------------------------------------------------
    def parameters(self):
        return self.network.parameters()

    def _metrics_names(self):
        names = []
        for m in self._metrics:
            names.extend(_tuplize(m.name()))
        return names

    def summary(self, input_size=None, dtype=None):
        rows = []
        total = 0
        trainable = 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            if p.trainable:
                trainable += n
            rows.append((name, tuple(p.shape), n))
        width = max([len(r[0]) for r in rows], default=10) + 2
        lines = [f"{'Layer':<{width}}{'Shape':<20}{'Params':>12}"]
        lines += [f"{n:<{width}}{str(s):<20}{c:>12,}" for n, s, c in rows]
        lines.append(f"Total params: {total:,}")
        lines.append(f"Trainable params: {trainable:,}")
        print("\n".join(lines))
        return {"total_params": total, "trainable_params": trainable}

"""paddle.regularizer — L1Decay / L2Decay.

Parity: python/paddle/regularizer.py (L1Decay:20, L2Decay:82 over
fluid/regularizer.py append_regularization_ops).  The reference appends
a regularization op to each parameter's gradient in the Program; here
the optimizer adds the penalty gradient in its (jit-traced) update —
same math, zero graph surgery.  Pass an instance as ``weight_decay=``
to any optimizer (a bare float keeps meaning L2, as before).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base: maps a parameter value to its penalty gradient dP/dw."""

    def __call__(self, w):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    """P = coeff * ||w||_1 → dP/dw = coeff * sign(w) (ref:
    regularizer.py:20, fluid L1DecayRegularizer)."""

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __call__(self, w):
        return self.coeff * jnp.sign(w)

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"


class L2Decay(WeightDecayRegularizer):
    """P = 0.5 * coeff * ||w||² → dP/dw = coeff * w (ref:
    regularizer.py:82, fluid L2DecayRegularizer)."""

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __call__(self, w):
        return self.coeff * w

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"

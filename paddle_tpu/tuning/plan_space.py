"""Sharding-plan measured search — the ``"plan"`` client of the engine.

SNIPPETS-style "naive sharding" picks one partition spec by hand and
hopes; this module enumerates per-parameter-group mesh-axis assignments
over the existing ``data/sharding/model/sep/expert/pipe`` axes plus the
collective schedule dials (`fp16_allreduce`, gradient bucketing,
overlap), rejects invalid assignments with
``analysis.check_plan.is_valid_plan`` BEFORE any compile, and times the
survivors as real train steps (the caller supplies the step measure —
typically ``Executor.run_steps`` on the real program).  The winner is
persisted in the shared tuning cache keyed
``plan | tag | param-bucket | mesh | device_kind`` and applied via
:func:`apply_plan` (parameter ``partition_spec`` annotations +
``DistributedStrategy.apply_tuned``).

A candidate config is JSON-plain::

    {"axes": {"<group>": "model" | "sharding" | "none", ...},
     "fp16_allreduce": 0 | 1,
     "allreduce_bucket_mb": 0 | 16 | 64,
     "overlap_grad_sync": 0 | 1}

Enumeration is deliberately naive — an axis is proposed for a group's
first large-enough dim whether or not it divides; that is exactly the
class of mistake the P501/P502/P503 pre-filter exists to catch, and it
keeps the filter on the load-bearing path.
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..framework.flags import flag
from . import engine

__all__ = ["param_groups", "plan_candidates", "tune_plan", "apply_plan",
           "make_step_measure", "DECODE_DIALS", "decode_schedule_candidates",
           "tune_decode_schedule", "apply_decode_schedule"]

#: mesh axes a parameter group may be assigned to ("none" = replicated);
#: ``data`` stays the batch axis and is never a parameter axis here.
#: ``expert`` is proposed like any other axis — the P506 pre-filter
#: rejects it on non-expert parameter groups before any compile
PARAM_AXES = ("none", "model", "sharding", "sep", "expert", "pipe")

#: collective schedule dials and their sweep values
COLLECTIVE_DIALS = {
    "fp16_allreduce": (0, 1),
    "allreduce_bucket_mb": (0, 16, 64),
    "overlap_grad_sync": (1, 0),
}


def param_groups(shapes: Dict[str, tuple]) -> Dict[str, Dict[str, tuple]]:
    """Group parameter names by their first dotted component — layers
    tune together (one axis choice per module, not per tensor), which
    keeps the space polynomial in modules instead of exponential in
    tensors."""
    groups: Dict[str, Dict[str, tuple]] = {}
    for name, shape in shapes.items():
        groups.setdefault(name.split(".", 1)[0], {})[name] = tuple(shape)
    return groups


def network_shapes(network) -> Dict[str, tuple]:
    out = {}
    for name, box in network.named_parameters():
        try:
            out[name] = tuple(box.value.shape)
        except Exception:  # deleted/donated array: metadata unavailable
            continue
    return out


def _specs_for(groups: Dict[str, Dict[str, tuple]], axes: Dict[str, str],
               mesh_shape: Dict[str, int]) -> Dict[str, tuple]:
    """Lower a per-group axis assignment onto per-parameter partition
    specs: the group's axis goes on each parameter's FIRST dim at least
    as large as the axis (naive on purpose — divisibility is the
    pre-filter's job, see module docstring)."""
    specs: Dict[str, tuple] = {}
    for gname, params in groups.items():
        ax = axes.get(gname, "none")
        size = mesh_shape.get(ax, 1)
        for pname, shape in params.items():
            if ax == "none" or size <= 1:
                specs[pname] = ()
                continue
            d = next((i for i, s in enumerate(shape) if s >= size), None)
            if d is None:
                specs[pname] = ()
                continue
            spec = [None] * (d + 1)
            spec[d] = ax
            specs[pname] = tuple(spec)
    return specs


class _PlanView:
    """Duck-typed stand-in ``check_plan.is_valid_plan`` accepts: shapes
    and specs without a live network or a constructed ShardingPlan."""

    def __init__(self, shapes: Dict[str, tuple],
                 specs: Dict[str, tuple], mesh):
        self.param_shapes = shapes
        self.param_specs = specs
        self.mesh = mesh


def is_valid_candidate(config: dict, groups: Dict[str, Dict[str, tuple]],
                       mesh) -> bool:
    """P501–P504 pre-filter for one candidate: materialize its specs and
    run the boolean checker — no DiagnosticCollector, no compile."""
    from ..analysis import is_valid_plan

    shapes = {n: s for g in groups.values() for n, s in g.items()}
    specs = _specs_for(groups, config.get("axes", {}), dict(mesh.shape))
    return is_valid_plan(_PlanView(shapes, specs, mesh))


def plan_candidates(groups: Dict[str, Dict[str, tuple]], mesh, *,
                    base: Optional[dict] = None,
                    max_candidates: int = 64) -> List[dict]:
    """Enumerate candidate plans: the full (axes × dials) product when it
    fits ``max_candidates``, else a coordinate sweep around ``base`` (one
    group or one dial varied at a time) — the AutoTVM-style fallback that
    keeps measurement cost linear in the number of knobs."""
    mesh_shape = dict(mesh.shape)
    # only propose axes that exist with size > 1 (plus replication)
    axis_opts = ["none"] + [a for a in PARAM_AXES[1:]
                            if mesh_shape.get(a, 1) > 1]
    gnames = sorted(groups)
    base = dict(base or {})
    base_axes = dict(base.get("axes") or {g: "none" for g in gnames})
    for g in gnames:
        base_axes.setdefault(g, "none")
    base_cfg = {
        "axes": {g: base_axes[g] for g in gnames},
        "fp16_allreduce": int(base.get("fp16_allreduce", 0)),
        "allreduce_bucket_mb": int(base.get("allreduce_bucket_mb", 0)),
        "overlap_grad_sync": int(base.get("overlap_grad_sync", 1)),
    }

    def cfg(axes, dials):
        return {"axes": dict(axes), **dials}

    total = (len(axis_opts) ** len(gnames)) * int(
        np.prod([len(v) for v in COLLECTIVE_DIALS.values()]))
    out: List[dict] = [base_cfg]
    if total <= max_candidates:
        dial_items = sorted(COLLECTIVE_DIALS.items())
        for combo in itertools.product(*(axis_opts for _ in gnames)):
            axes = dict(zip(gnames, combo))
            for dvals in itertools.product(*(v for _, v in dial_items)):
                dials = {k: int(v) for (k, _), v
                         in zip(dial_items, dvals)}
                out.append(cfg(axes, dials))
    else:
        base_dials = {k: base_cfg[k] for k in COLLECTIVE_DIALS}
        for g in gnames:  # one group's axis at a time
            for ax in axis_opts:
                axes = dict(base_cfg["axes"])
                axes[g] = ax
                out.append(cfg(axes, base_dials))
        for dial, values in sorted(COLLECTIVE_DIALS.items()):
            for v in values:  # one dial at a time
                dials = dict(base_dials)
                dials[dial] = int(v)
                out.append(cfg(base_cfg["axes"], dials))
    return engine.dedup_candidates(out[:max_candidates + 1], base_cfg)


def _param_bucket(groups: Dict[str, Dict[str, tuple]]) -> str:
    """Pow2-bucketed total parameter count: nearby model sizes share one
    plan entry, mirroring the kernel space's shape bucketing."""
    total = sum(int(np.prod(s)) if s else 1
                for g in groups.values() for s in g.values())
    return f"p{engine.next_pow2(max(total, 1))}"


def tune_plan(tag: str, *, measure: Callable[[dict], float],
              network=None, shapes: Optional[Dict[str, tuple]] = None,
              mesh=None, base: Optional[dict] = None,
              max_candidates: int = 64,
              details: Optional[dict] = None) -> dict:
    """Measured search over sharding plans for one workload ``tag``.

    ``measure(config) -> ms`` times a candidate END TO END — apply the
    config (``apply_plan``/``apply_tuned``), build the program, and run
    real train steps (``Executor.run_steps``); see
    :func:`make_step_measure`.  Lower is better; raise
    :class:`engine.CandidateError` to reject.  Off (``
    FLAGS_measured_search=off``) the base/default plan is returned
    untimed.  The winner persists in the shared tuning cache."""
    if mesh is None:
        from ..distributed.mesh import get_mesh

        mesh = get_mesh()
    if shapes is None:
        shapes = network_shapes(network)
    groups = param_groups(shapes)
    key = "|".join([tag, _param_bucket(groups), engine.mesh_key(mesh),
                    engine.device_kind()])
    measurable = str(flag("measured_search")).lower() != "off"
    base_cfg: List[dict] = []

    def heuristic() -> dict:
        if not base_cfg:
            base_cfg.append(plan_candidates(groups, mesh, base=base,
                                            max_candidates=0)[0])
        return base_cfg[0]

    return engine.resolve(
        "plan", tag, key,
        candidates=lambda: plan_candidates(groups, mesh, base=base,
                                           max_candidates=max_candidates),
        measure=measure,
        heuristic=heuristic,
        measurable=measurable,
        prefilter=lambda c: is_valid_candidate(c, groups, mesh),
        details=details)


def make_step_measure(run_step: Callable[[dict], object], *,
                      repeats: int = 2) -> Callable[[dict], float]:
    """Adapt a "apply config then run N train steps" callable into the
    engine's measure contract with the warm-call + best-of-N discipline:
    ``run_step(config)`` must apply the candidate and execute the step
    batch (e.g. ``exe.run_steps(..., iterations=k)``), returning the
    fetched values (blocked on inside ``measure_ms``)."""

    def measure(config: dict) -> float:
        return engine.measure_ms(run_step, (config,), repeats=repeats)

    return measure


# ---------------------------------------------------------------------------
# Sharded-decode overlap schedules (the serving twin of the collective
# dials above).  The dials live in distributed.collective and move WHERE
# the tensor/expert-parallel all-reduces land in the traced decode step
# (GSPMD placement freedom — value-preserving by construction), which a
# latency-bound decode step cares about; see collective.set_overlap_schedule.

#: sharded-decode overlap dials and their sweep values; the all-zeros
#: base is the historical placement (reduce immediately at every
#: RowParallelLinear output) and is always a candidate.
DECODE_DIALS = {
    "defer_row_reduce": (0, 1),
    "mlp_collective_split": (0, 1),
}


def decode_schedule_candidates(base: Optional[dict] = None) -> List[dict]:
    """The full dial product (4 configs), base first."""
    base_cfg = {k: int((base or {}).get(k, 0)) for k in DECODE_DIALS}
    items = sorted(DECODE_DIALS.items())
    out = [base_cfg]
    for combo in itertools.product(*(v for _, v in items)):
        out.append({k: int(v) for (k, _), v in zip(items, combo)})
    return engine.dedup_candidates(out, base_cfg)


def tune_decode_schedule(tag: str, *, measure: Callable[[dict], float],
                         mesh=None, base: Optional[dict] = None,
                         details: Optional[dict] = None) -> dict:
    """Measured search over sharded-decode overlap schedules.

    ``measure(config) -> ms`` must apply the config
    (:func:`apply_decode_schedule`), RETRACE the decode step (the dials
    are trace-time), and time real decode steps — the serving engines
    wire this into ``warmup()`` so the search lands before
    ``mark_warm()`` and K701 stays silent.  The winner persists in the
    shared tuning cache (``plan`` space, key ``decode_schedule:<tag> |
    mesh | device_kind``): a warm restart replays it from disk with zero
    searches.  Off (``FLAGS_measured_search=off``) the base placement is
    returned untimed."""
    if mesh is None:
        from ..distributed.mesh import get_mesh

        mesh = get_mesh()
    name = f"decode_schedule:{tag}"
    key = "|".join([name, engine.mesh_key(mesh), engine.device_kind()])
    measurable = str(flag("measured_search")).lower() != "off"
    base_cfg = {k: int((base or {}).get(k, 0)) for k in DECODE_DIALS}
    return engine.resolve(
        "plan", name, key,
        candidates=lambda: decode_schedule_candidates(base),
        measure=measure,
        heuristic=lambda: base_cfg,
        measurable=measurable,
        details=details)


def apply_decode_schedule(config: dict) -> dict:
    """Install a decode-schedule winner; functions traced afterwards pick
    it up.  Returns the previous schedule (for restore)."""
    from ..distributed.collective import set_overlap_schedule

    return set_overlap_schedule(
        {k: int(config.get(k, 0)) for k in DECODE_DIALS})


def apply_plan(config: dict, *, network=None, strategy=None, mesh=None):
    """Apply a plan winner: lower the per-group axis assignment onto
    parameter ``partition_spec`` annotations (the hook
    ``ShardingPlan.__init__`` reads) and the collective dials onto the
    strategy.  Returns ``(strategy, specs)``."""
    specs: Dict[str, tuple] = {}
    if network is not None:
        if mesh is None:
            from ..distributed.mesh import get_mesh

            mesh = get_mesh()
        shapes = network_shapes(network)
        groups = param_groups(shapes)
        specs = _specs_for(groups, config.get("axes", {}),
                           dict(mesh.shape))
        for name, box in network.named_parameters():
            spec = specs.get(name, ())
            box.partition_spec = tuple(spec) if any(
                a is not None for a in spec) else None
    if strategy is not None:
        strategy.apply_tuned(config)
    return strategy, specs

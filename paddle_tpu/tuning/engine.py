"""Generic measured-search engine — the core the kernel autotuner, the
sharding-plan tuner, and the serving-config tuner all share.

PR 4's lesson was that measured search on the real backend beats
heuristics for Pallas tile sizes; this module is that search loop with
the kernel-specific parts factored out, so ANY config space can use it:

* **candidate enumeration** is the client's (a list, or a lazy callable
  so cache hits never pay enumeration);
* **validity pre-filter** rejects candidates before any compile (the
  kernel client filters on VMEM fit inside its space; the plan client
  filters through ``analysis.check_plan.is_valid_plan``);
* **compile + time on the real backend** via :func:`measure_ms` — an
  untimed warm call first (absorbs compilation), then best-of-N wall
  times, so dispatch jitter can't crown a flaky winner;
* **persistent JSON cache** keyed ``space | client key | device kind``
  where the client key carries the shape bucket and (for distributed
  spaces) the mesh — entries carry ``version``/``space``/``name``
  fields (schema v2); stale pre-versioned entries are ignored, never a
  crash, and :func:`clear_cache` can scope a wipe to one space;
* **counters and trace events**: every resolution publishes an
  ``("autotune", name)`` event with the space attached, so
  ``analysis.RetraceMonitor`` raises K701 for ANY measured search after
  :func:`mark_warm` — kernel, plan, or serving — and the profiler grows
  one "Measured search" summary section covering all three.

Clients: ``ops.autotune`` (space ``"kernel"``), ``tuning.plan_space``
(``"plan"``), ``tuning.serving_space`` (``"serving"``).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..framework import trace_events
from ..framework.errors import InvalidArgumentError
from ..framework.flags import flag

__all__ = [
    "SCHEMA_VERSION", "SPACES", "resolve", "measure_ms", "cache_path",
    "clear_cache", "get_counters", "reset_counters", "mark_warm", "is_warm",
    "reset_warm", "bucket_shape", "next_pow2", "device_kind", "mesh_key",
    "CandidateError",
]

#: disk-cache entry schema.  v1 entries (PR 4's kernel-only format, no
#: ``version``/``space`` fields) are ignored on load — a stale cache
#: degrades to a re-search, never a crash.
SCHEMA_VERSION = 2

#: the registered config spaces (informational; the engine accepts any
#: space string, these are the ones shipped in-tree)
SPACES = ("kernel", "plan", "serving")

_lock = threading.RLock()
_mem_cache: Dict[str, dict] = {}          # spaced key -> config
_heuristic_cache: Dict[str, dict] = {}    # spaced key -> untimed default
_counters: Dict[str, Dict[str, int]] = {}  # client name -> counters
_spaces: Dict[str, str] = {}               # client name -> space
_warm = False                              # set by serving warmup; see K701

_disk_state = {"path": None, "entries": None}  # lazily-loaded JSON cache

_COUNTER_KEYS = ("hits", "disk_hits", "searches", "heuristic",
                 "configs_timed", "search_failures", "searches_after_warm",
                 "prefiltered")


class CandidateError(Exception):
    """Raised by a measure callback to reject one candidate (fails to
    lower, violates a latency budget, …) without aborting the search."""


# -- keys --------------------------------------------------------------------
def next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def bucket_shape(shape) -> Tuple[int, ...]:
    """Shape bucket for cache keys: each dim rounds up to a power of two,
    so nearby geometries (ragged batches, serving buckets) share one
    entry.  Clients clamp configs to the real shape at use time, so a
    winner from a larger bucket member stays valid."""
    return tuple(next_pow2(d) for d in shape)


def device_kind() -> str:
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:  # backend not initialized / unreachable
        return jax.default_backend()


def mesh_key(mesh=None) -> str:
    """Stable mesh component for plan/serving cache keys: axis sizes in
    canonical order (``pipe1.data8.sharding1.sep1.model1``).  Accepts any
    object with a ``.shape`` mapping (a real ``jax.sharding.Mesh`` or a
    test stub); ``None`` reads the active global mesh."""
    if mesh is None:
        from ..distributed.mesh import get_mesh

        mesh = get_mesh()
    shape = dict(mesh.shape)
    return ".".join(f"{a}{shape[a]}" for a in sorted(shape))


def _spaced(space: str, key: str) -> str:
    return f"{space}|{key}"


# -- persistent cache --------------------------------------------------------
def cache_path() -> Optional[str]:
    """Resolved on-disk cache path (``FLAGS_kernel_tuning_cache`` — one
    file holds every space's winners), or ``None`` when persistence is
    disabled."""
    val = str(flag("kernel_tuning_cache") or "").strip()
    if val.lower() in ("0", "off", "none", "false", "disabled"):
        return None
    if not val:
        return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                            "kernel_tuning.json")
    return val


def _valid_entry(v) -> bool:
    """Schema filter: v2+ entries only.  PR-4-era kernel entries carry no
    ``version`` field — they key differently anyway (no space prefix), so
    they are dropped rather than trusted across the schema change."""
    return (isinstance(v, dict) and "config" in v
            and isinstance(v.get("version"), int)
            and v["version"] >= SCHEMA_VERSION)


def _disk_entries() -> Dict[str, dict]:
    """The loaded disk cache, reloaded when the flag re-points it.
    Stale-schema entries are ignored (never a crash)."""
    path = cache_path()
    if path is None:
        return {}
    if _disk_state["path"] != path or _disk_state["entries"] is None:
        entries = {}
        try:
            with open(path) as f:
                data = json.load(f)
            if isinstance(data, dict):
                entries = {k: v for k, v in data.get("entries", {}).items()
                           if _valid_entry(v)}
        except (OSError, ValueError):
            entries = {}
        _disk_state["path"] = path
        _disk_state["entries"] = entries
    return _disk_state["entries"]


def _disk_store(spaced_key: str, space: str, name: str, config: dict,
                best_ms: float) -> None:
    path = cache_path()
    if path is None:
        return
    entries = dict(_disk_entries())
    # merge with concurrent writers: reread before rewrite (stale-schema
    # entries on disk are dropped, not re-persisted)
    try:
        with open(path) as f:
            on_disk = json.load(f).get("entries", {})
        if isinstance(on_disk, dict):
            entries = {**{k: v for k, v in on_disk.items()
                          if _valid_entry(v)}, **entries}
    except (OSError, ValueError):
        pass
    entry = {"space": space, "name": name, "config": dict(config),
             "best_ms": round(float(best_ms), 4),
             "version": SCHEMA_VERSION}
    if space == "kernel":
        entry["kernel"] = name  # PR-4 field name, kept for tooling compat
    entries[spaced_key] = entry
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": SCHEMA_VERSION, "entries": entries}, f,
                      indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        return  # read-only cache dir: winners stay process-local
    _disk_state["path"] = path
    _disk_state["entries"] = entries


def _entry_space(key: str, entry: dict) -> str:
    return entry.get("space") or key.split("|", 1)[0]


def clear_cache(memory: bool = True, disk: bool = False,
                space: Optional[str] = None) -> None:
    """Drop tuned winners.  ``disk=True`` also clears the JSON file;
    ``space`` scopes the wipe to one config space (``"kernel"`` /
    ``"plan"`` / ``"serving"``) so re-tuning sharding plans doesn't cost
    the kernel winners, and vice versa."""
    with _lock:
        if memory:
            if space is None:
                _mem_cache.clear()
                _heuristic_cache.clear()
            else:
                pre = _spaced(space, "")
                for cache in (_mem_cache, _heuristic_cache):
                    for k in [k for k in cache if k.startswith(pre)]:
                        del cache[k]
        _disk_state["path"] = None
        _disk_state["entries"] = None
    if not disk:
        return
    path = cache_path()
    if path is None:
        return
    if space is None:
        try:
            os.remove(path)
        except OSError:
            pass
        return
    # scope-aware disk clear: rewrite the file without that space's
    # entries (stale-schema entries are dropped along the way)
    try:
        with open(path) as f:
            on_disk = json.load(f).get("entries", {})
    except (OSError, ValueError):
        return
    keep = {k: v for k, v in on_disk.items()
            if _valid_entry(v) and _entry_space(k, v) != space}
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": SCHEMA_VERSION, "entries": keep}, f,
                      indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass


# -- counters / warm state ---------------------------------------------------
def _bump(name: str, field: str, n: int = 1) -> Dict[str, int]:
    c = _counters.setdefault(name, {k: 0 for k in _COUNTER_KEYS})
    c[field] += n
    return c


def get_counters(name: Optional[str] = None) -> Dict:
    """Counter snapshot(s): one client's dict, or ``{name: dict}``."""
    with _lock:
        if name is not None:
            return dict(_counters.get(name, {k: 0 for k in _COUNTER_KEYS}))
        return {k: dict(v) for k, v in _counters.items()}


def reset_counters() -> None:
    with _lock:
        _counters.clear()


def mark_warm() -> None:
    """Declare tuning warmup over (serving engines call this after
    ``warmup()``): any measured search past this point — kernel tiles, a
    sharding plan, serving dials — is tuning work on a hot path, a cache
    miss the pre-warmed JSON cache should have absorbed, and is flagged
    by analysis rule K701."""
    global _warm
    with _lock:
        _warm = True


def is_warm() -> bool:
    return _warm


def reset_warm() -> None:
    """Reset the warm flag (tests / engine restarts)."""
    global _warm
    with _lock:
        _warm = False


def _publish(space: str, name: str, event: str, key: str, config: dict,
             **extra):
    with _lock:
        counters = dict(_counters.get(name, {k: 0 for k in _COUNTER_KEYS}))
        warm = _warm
    if trace_events.active():
        info = {"event": event, "key": key, "config": dict(config),
                "space": space, "warm": warm, "counters": counters}
        info.update(extra)
        trace_events.notify(("autotune", name), info)


# -- measurement -------------------------------------------------------------
def measure_ms(fn: Callable, args: Sequence = (), repeats: int = 3) -> float:
    """Wall-time ``fn(*args)``: one UNTIMED warm call first (absorbs
    compile + first-dispatch costs), then best-of-``repeats`` — a single
    timing would let dispatch jitter crown a flaky winner.  Results with
    device buffers are blocked on, so async dispatch can't hide work."""
    import jax

    def run():
        out = fn(*args)
        if out is not None:
            try:
                jax.block_until_ready(out)
            except (TypeError, ValueError):
                pass  # host-only result: fn blocked internally
        return out

    run()  # warm: compile + first dispatch, never timed
    best = math.inf
    for _ in range(max(int(repeats), 1)):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def _canonical(cfg: dict) -> dict:
    return {k: int(v) if isinstance(v, (bool, np.integer, int)) else v
            for k, v in cfg.items()}


def dedup_candidates(cands: Sequence[dict], default: dict) -> List[dict]:
    """Canonicalize + dedup a candidate list; the default is always in
    the running (appended last so an explicit duplicate keeps its spot)."""
    seen, out = set(), []
    for c in list(cands) + [default]:
        c = _canonical(c)
        sig = tuple(sorted((k, repr(v)) for k, v in c.items()))
        if sig not in seen:
            seen.add(sig)
            out.append(c)
    return out


# -- resolution --------------------------------------------------------------
def resolve(space: str, name: str, key: str, *,
            candidates: Union[Sequence[dict], Callable[[], Sequence[dict]]],
            measure: Callable[[dict], float],
            heuristic: Union[dict, Callable[[], dict]],
            measurable: bool,
            prefilter: Optional[Callable[[dict], bool]] = None,
            details: Optional[dict] = None) -> dict:
    """Resolve one config: in-memory hit → disk hit → measured search →
    untimed default.

    ``candidates`` — the config dicts to race (or a callable returning
    them, evaluated only when a search actually runs); the default MUST
    be in the list so the search can never do worse than the hand-set
    config.  ``measure(cand) -> ms`` times one candidate (lower is
    better; raise :class:`CandidateError` to reject it).  ``heuristic``
    is the untimed default used off-backend or when every candidate
    fails.  ``prefilter(cand) -> bool`` drops invalid candidates before
    any compile.  ``details`` (optional dict) is filled with the search
    outcome (event, best_ms, default_ms, per-candidate timings) for
    gates that assert on measurements."""
    if not space or "|" in space:
        raise InvalidArgumentError(f"bad search space name {space!r}")
    skey = _spaced(space, key)

    def note(**kw):
        if details is not None:
            details.update(kw)

    with _lock:
        _spaces[name] = space
        cfg = _mem_cache.get(skey)
        if cfg is None and not measurable:
            cfg = _heuristic_cache.get(skey)
        if cfg is not None:
            _bump(name, "hits")
    if cfg is not None:
        _publish(space, name, "hit", key, cfg)
        note(event="hit", config=dict(cfg))
        return dict(cfg)

    default = heuristic() if callable(heuristic) else dict(heuristic)
    default = _canonical(default)

    if not measurable:
        with _lock:
            _heuristic_cache[skey] = dict(default)
            _bump(name, "heuristic")
        _publish(space, name, "heuristic", key, default)
        note(event="heuristic", config=dict(default))
        return dict(default)

    disk = _disk_entries().get(skey)
    if disk is not None:
        cfg = dict(disk["config"])
        with _lock:
            _mem_cache[skey] = cfg
            _bump(name, "disk_hits")
        _publish(space, name, "disk_hit", key, cfg)
        note(event="disk_hit", config=dict(cfg),
             best_ms=disk.get("best_ms"))
        return dict(cfg)

    # -- measured search ------------------------------------------------------
    from .. import profiler

    cands = dedup_candidates(
        candidates() if callable(candidates) else candidates, default)
    dsig = tuple(sorted((k, repr(v)) for k, v in default.items()))
    best_cfg, best_ms, default_ms = dict(default), math.inf, None
    timed, dropped, timings = 0, 0, []
    with profiler.RecordEvent(f"measured_search/{space}/{name}"):
        for cand in cands:
            if prefilter is not None and not prefilter(cand):
                dropped += 1
                with _lock:
                    _bump(name, "prefiltered")
                continue
            try:
                ms = float(measure(cand))
            except Exception:  # fails to lower / violates a budget: skip
                with _lock:
                    _bump(name, "search_failures")
                timings.append({"config": dict(cand), "ms": None})
                continue
            timed += 1
            timings.append({"config": dict(cand), "ms": round(ms, 4)})
            if tuple(sorted((k, repr(v)) for k, v in cand.items())) == dsig:
                default_ms = ms
            if ms < best_ms:
                best_cfg, best_ms = dict(cand), ms
    if timed == 0:  # nothing measured — fall back, don't poison caches
        with _lock:
            _bump(name, "heuristic")
        _publish(space, name, "heuristic", key, default,
                 note="all candidates failed")
        note(event="heuristic", config=dict(default),
             n_candidates=len(cands), n_prefiltered=dropped,
             timings=timings)
        return dict(default)
    with _lock:
        _mem_cache[skey] = dict(best_cfg)
        _bump(name, "searches")
        _bump(name, "configs_timed", timed)
        if _warm:
            _bump(name, "searches_after_warm")
    _disk_store(skey, space, name, best_cfg, best_ms)
    _publish(space, name, "search", key, best_cfg,
             best_ms=round(best_ms, 4), n_candidates=len(cands),
             n_timed=timed, n_prefiltered=dropped)
    note(event="search", config=dict(best_cfg),
         best_ms=round(best_ms, 4),
         default_ms=None if default_ms is None else round(default_ms, 4),
         n_candidates=len(cands), n_timed=timed, n_prefiltered=dropped,
         timings=timings)
    return dict(best_cfg)


# -- profiler summary section ------------------------------------------------
_section_base: Dict[str, Dict[str, int]] = {}


def _on_profiler_reset() -> None:
    with _lock:
        _section_base.clear()
        _section_base.update({k: dict(v) for k, v in _counters.items()})


def _summary_section() -> str:
    """Counter deltas since the profiler was last reset, one row per
    tuned client across every space, as a table the
    ``profiler.summary()`` host-event report appends."""
    with _lock:
        rows = []
        for name in sorted(_counters):
            base = _section_base.get(name, {})
            d = {k: _counters[name][k] - base.get(k, 0)
                 for k in _COUNTER_KEYS}
            if any(d.values()):
                rows.append((_spaces.get(name, "kernel"), name, d))
    if not rows:
        return ""
    path = cache_path() or "<in-memory only>"
    w = max(len(r[1]) for r in rows) + 2
    sw = max(len(r[0]) for r in rows) + 2
    lines = [f"Measured search (cache: {path})",
             f"{'Space':<{sw}}{'Name':<{w}}{'Searches':>10}{'Timed':>8}"
             f"{'Hits':>8}{'Disk':>8}{'Heur':>8}{'Filt':>6}{'AfterWarm':>11}"]
    for space, name, d in rows:
        lines.append(
            f"{space:<{sw}}{name:<{w}}{d['searches']:>10}"
            f"{d['configs_timed']:>8}{d['hits']:>8}{d['disk_hits']:>8}"
            f"{d['heuristic']:>8}{d['prefiltered']:>6}"
            f"{d['searches_after_warm']:>11}")
    return "\n".join(lines)


def _register_profiler_section() -> None:
    from .. import profiler

    profiler.register_summary_section(_summary_section,
                                      on_reset=_on_profiler_reset)


_register_profiler_section()

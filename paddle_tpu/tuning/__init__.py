"""Measured-search tuning: one engine, three config spaces.

``tuning.engine`` is the generic search core (enumerate → pre-filter →
compile+time on the real backend → persistent JSON cache → counters /
trace events).  Its clients:

* ``ops.autotune`` — Pallas kernel tile parameters (space ``"kernel"``);
* ``tuning.plan_space`` — per-parameter-group mesh-axis assignment and
  collective schedule dials, pre-filtered by ``analysis.check_plan``,
  timed as real train steps (space ``"plan"``);
* ``tuning.serving_space`` — bucket sets, slot count, batching delay,
  KV page size, speculative k, timed against a replayed request trace
  under a latency budget (space ``"serving"``).

``tuning.trace`` records and replays the deterministic request traces
the serving space measures against.

Only the engine is imported eagerly — ``ops.autotune`` is a client of
it, so the config-space modules (which import analysis/distributed/
serving machinery on top of ops) load lazily via ``__getattr__``.
"""
from . import engine  # noqa: F401
from .engine import (  # noqa: F401
    CandidateError,
    clear_cache,
    get_counters,
    is_warm,
    mark_warm,
    measure_ms,
    reset_counters,
    reset_warm,
    resolve,
)

__all__ = [
    "engine", "CandidateError", "resolve", "measure_ms", "clear_cache",
    "get_counters", "reset_counters", "mark_warm", "is_warm", "reset_warm",
    "RequestTrace", "TraceRecorder", "replay",
    "plan_candidates", "tune_plan", "apply_plan",
    "serving_candidates", "tune_serving",
]

_LAZY = {
    "RequestTrace": "trace", "TraceRecorder": "trace", "replay": "trace",
    "plan_candidates": "plan_space", "tune_plan": "plan_space",
    "apply_plan": "plan_space",
    "serving_candidates": "serving_space", "tune_serving": "serving_space",
    "trace": None, "plan_space": None, "serving_space": None,
}


def __getattr__(name):
    mod = _LAZY.get(name, KeyError)
    if mod is KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    module = importlib.import_module(f".{mod or name}", __name__)
    return module if mod is None else getattr(module, name)

"""Serving-config measured search — the ``"serving"`` client of the
engine.

Every serving dial added since PR 7 is hand-set: the bucket set, the
slot count B, the micro-batcher's ``max_batch_size`` /
``max_queue_delay_ms``, and PR 11's ``FLAGS_kv_page_size`` /
``FLAGS_speculative_k``.  This module races candidate dial settings
against a DETERMINISTIC replayed request trace (``tuning.trace``) —
same prompts, same output lengths, same submission order for every
candidate — scoring milliseconds per generated token (lower is better)
under a hard p99 latency budget: a throughput winner that blows the
declared p99 is rejected (``CandidateError`` → a counted search
failure), so the tuner can never trade tail latency for tokens/s.

A candidate config is JSON-plain and maps onto
``GenerationEngine.from_tuned`` / ``InferenceEngine.from_tuned``::

    {"buckets": [16, 48], "batch_size": 8, "max_queue_delay_ms": 1.0,
     "kv_page_size": 64, "speculative_k": 4, "paged": 1,
     "quantization": "int8"}

Winners persist in the shared tuning cache keyed
``serving | tag | trace digest | mesh | device_kind`` — a tuned config
is only a cache hit against the workload it was measured on.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from ..framework.flags import flag
from . import engine
from .trace import RequestTrace, replay

__all__ = ["serving_candidates", "tune_serving", "make_replay_measure"]

#: per-dial sweep values for the coordinate search; ``None`` entries in
#: a dial's sweep mean "leave at the base value"
DIAL_SWEEPS = {
    "batch_size": (2, 4, 8, 16),
    "max_queue_delay_ms": (0.5, 1.0, 2.0, 5.0),
    "kv_page_size": (32, 64, 128),
    "speculative_k": (0, 2, 4),
    # serving precision is a measured dial like any other: the replay
    # scores quantized candidates on the same trace, so int8/fp8 wins
    # only where its tokens/s actually beats the float engine's
    "quantization": ("none", "int8", "fp8"),
    # multi-LoRA adapter-table capacity (GPTConfig.lora_capacity): every
    # decode step gathers over the whole fixed table, so capacity is a
    # per-step cost dial — swept only when the base config exposes it
    # (dials absent from base are skipped, like every other dial)
    "lora_capacity": (4, 8, 16),
}


def serving_candidates(base: Dict, *,
                       bucket_sets: Optional[Sequence[Sequence[int]]] = None,
                       sweeps: Optional[Dict[str, Sequence]] = None,
                       max_candidates: int = 24) -> List[dict]:
    """Coordinate sweep around ``base``: one dial varied at a time (plus
    each alternative bucket set), base first — so the hand-set default is
    always in the running and measurement cost stays linear in the knob
    count rather than exponential."""
    base = dict(base)
    out: List[dict] = [dict(base)]
    for bs in (bucket_sets or []):
        c = dict(base)
        c["buckets"] = [int(b) for b in bs]
        out.append(c)
    for dial, values in sorted((sweeps or DIAL_SWEEPS).items()):
        if dial not in base:
            continue  # dial not exposed by this engine's config
        for v in values:
            if v is None:
                continue
            c = dict(base)
            c[dial] = v
            out.append(c)
    return engine.dedup_candidates(out[:max_candidates], dict(base))


def make_replay_measure(factory: Callable[[dict], object],
                        trace: RequestTrace, *,
                        latency_budget_ms: Optional[float] = None,
                        results: Optional[dict] = None,
                        ) -> Callable[[dict], float]:
    """The default serving measure: build the engine for one candidate
    (``factory(config)`` returns a context manager — e.g.
    ``lambda cfg: GenerationEngine.from_tuned(model, cfg)``), warm it,
    replay the trace, and score ms per generated token.  Candidates whose
    p99 exceeds the budget raise :class:`engine.CandidateError` and count
    as search failures.  ``results`` (optional dict) collects each
    candidate's full replay stats keyed by config repr, for gate
    assertions."""

    def measure(config: dict) -> float:
        # each candidate's warmup() calls mark_warm(), but a throwaway
        # measurement engine is not the production engine going hot —
        # restore the flag so the tuner's own search can't raise K701
        was_warm = engine.is_warm()
        try:
            with factory(config) as eng:
                eng.warmup()
                stats = replay(eng, trace)
        finally:
            if not was_warm:
                engine.reset_warm()
        if results is not None:
            results[repr(sorted(config.items()))] = dict(stats)
        if (latency_budget_ms is not None
                and stats["p99_ms"] > float(latency_budget_ms)):
            raise engine.CandidateError(
                f"p99 {stats['p99_ms']}ms exceeds the "
                f"{latency_budget_ms}ms budget")
        return 1e3 / max(stats["tokens_per_sec"], 1e-9)  # ms per token

    return measure


def tune_serving(tag: str, base: Dict, *,
                 trace: RequestTrace,
                 factory: Optional[Callable[[dict], object]] = None,
                 measure: Optional[Callable[[dict], float]] = None,
                 latency_budget_ms: Optional[float] = None,
                 bucket_sets: Optional[Sequence[Sequence[int]]] = None,
                 sweeps: Optional[Dict[str, Sequence]] = None,
                 max_candidates: int = 24,
                 results: Optional[dict] = None,
                 details: Optional[dict] = None) -> dict:
    """Measured search over serving configs for one workload ``tag``.

    Supply either ``factory`` (engine builder — the default measure
    warms it and replays ``trace``) or a custom ``measure(config) ->
    score`` (lower is better; tests inject deterministic scorers).  Off
    (``FLAGS_measured_search=off``) the hand-set ``base`` is returned
    untimed.  The winner persists in the shared tuning cache and is
    applied by the caller via ``*.from_tuned``."""
    if measure is None:
        if factory is None:
            raise TypeError("tune_serving needs a factory or a measure")
        measure = make_replay_measure(factory, trace,
                                      latency_budget_ms=latency_budget_ms,
                                      results=results)
    key = "|".join([tag, trace.key(), engine.mesh_key(),
                    engine.device_kind()])
    measurable = str(flag("measured_search")).lower() != "off"
    return engine.resolve(
        "serving", tag, key,
        candidates=lambda: serving_candidates(
            base, bucket_sets=bucket_sets, sweeps=sweeps,
            max_candidates=max_candidates),
        measure=measure,
        heuristic=dict(base),
        measurable=measurable,
        details=details)

"""Deterministic request traces for serving-config measured search.

A serving dial (bucket set, slot count, batching delay, KV page size,
speculative k) can only be compared fairly when every candidate serves
the IDENTICAL workload: same prompts, same output lengths, same
submission order.  This module is that workload as a value:

* :class:`RequestTrace` — an ordered list of ``(prompt_ids, max_new)``
  requests with a stable content digest (:meth:`RequestTrace.key`) that
  lands in the measured-search cache key, so a tuned winner is bound to
  the trace it was measured on;
* :meth:`RequestTrace.synthetic` — the fixed-seed mixed-length sweep
  ``bench.py`` has always used (RandomState(17), prompts 4..48, outputs
  4..64), reproduced draw-for-draw so benches before and after this
  module see bit-identical requests;
* :class:`TraceRecorder` — capture live submissions (wrap an engine's
  ``submit``) and save them for offline tuning against production
  shapes;
* :func:`replay` — drive one engine through a trace and return the
  throughput/latency numbers the tuner scores.
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..framework.errors import InvalidArgumentError

__all__ = ["RequestTrace", "TraceRecorder", "replay"]


class RequestTrace:
    """An ordered, immutable-by-convention request workload: each entry
    is ``(prompt_ids: np.int32[L], max_new: int)``."""

    def __init__(self, entries: Sequence[Tuple[np.ndarray, int]], *,
                 name: str = "trace", seed: Optional[int] = None):
        self.entries: List[Tuple[np.ndarray, int]] = [
            (np.asarray(p, dtype=np.int32), int(n)) for p, n in entries]
        self.name = name
        self.seed = seed

    def __len__(self) -> int:
        return len(self.entries)

    def __iter__(self):
        return iter(self.entries)

    @property
    def total_new_tokens(self) -> int:
        return sum(n for _, n in self.entries)

    # -- synthesis -----------------------------------------------------------
    @classmethod
    def synthetic(cls, n: int = 48, *, seed: int = 17, vocab: int = 8192,
                  prompt_range: Tuple[int, int] = (4, 49),
                  new_range: Tuple[int, int] = (4, 65)) -> "RequestTrace":
        """The fixed-seed mixed-length sweep: ragged on both axes, the
        spread a run-batch-to-completion scheduler pays head-of-line
        blocking on.  Draw order matches the historical ``bench.py``
        inline generation exactly (lengths first, then output counts,
        then per-request tokens), so default-args output is bit-identical
        to every recorded bench number."""
        rng = np.random.RandomState(seed)
        lens = rng.randint(prompt_range[0], prompt_range[1], size=n)
        news = rng.randint(new_range[0], new_range[1], size=n)
        entries = [(rng.randint(1, vocab, size=int(L)).astype(np.int32),
                    int(m)) for L, m in zip(lens, news)]
        return cls(entries, name=f"synthetic-s{seed}-n{n}", seed=seed)

    # -- identity ------------------------------------------------------------
    def key(self) -> str:
        """Stable content digest for measured-search cache keys: a tuned
        serving config is only a cache hit against the same workload."""
        h = hashlib.sha256()
        for p, n in self.entries:
            h.update(p.tobytes())
            h.update(int(n).to_bytes(4, "little"))
        return f"{self.name}.{h.hexdigest()[:12]}"

    # -- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        data = {"version": 1, "name": self.name, "seed": self.seed,
                "requests": [{"prompt": p.tolist(), "max_new": n}
                             for p, n in self.entries]}
        with open(path, "w") as f:
            json.dump(data, f, indent=0)

    @classmethod
    def load(cls, path: str) -> "RequestTrace":
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or "requests" not in data:
            raise InvalidArgumentError(f"not a request trace: {path}")
        return cls([(np.asarray(r["prompt"], np.int32), int(r["max_new"]))
                    for r in data["requests"]],
                   name=data.get("name", "trace"), seed=data.get("seed"))


class TraceRecorder:
    """Capture live request arrivals for offline tuning: call
    :meth:`record` from the serving front door (or wrap ``submit``),
    then :meth:`trace`/:meth:`save` the workload."""

    def __init__(self, name: str = "recorded", limit: int = 10000):
        self.name = name
        self.limit = int(limit)
        self._entries: List[Tuple[np.ndarray, int]] = []

    def record(self, prompt_ids, max_new: int) -> None:
        if len(self._entries) < self.limit:
            self._entries.append(
                (np.asarray(prompt_ids, np.int32), int(max_new)))

    def wrap(self, submit):
        """``engine.submit = recorder.wrap(engine.submit)`` — record each
        request on its way in, pass through untouched."""

        def wrapped(prompt_ids, max_new, *a, **kw):
            self.record(prompt_ids, max_new)
            return submit(prompt_ids, max_new, *a, **kw)

        return wrapped

    def __len__(self) -> int:
        return len(self._entries)

    def trace(self) -> RequestTrace:
        return RequestTrace(self._entries, name=self.name)

    def save(self, path: str) -> None:
        self.trace().save(path)


def replay(engine, trace: RequestTrace, *, timeout: float = 600.0) -> dict:
    """Drive ``engine`` (a ``GenerationEngine``-shaped object: ``submit``
    returning a future whose result is the generated token list) through
    the trace in order, all requests in flight at once, and return the
    numbers the serving-space tuner scores: tokens/s end-to-end plus the
    per-request latency distribution."""
    lat: List[float] = []
    futs = []
    t0 = time.perf_counter()
    for prompt, max_new in trace:
        ts = time.perf_counter()
        f = engine.submit(prompt, max_new)
        f.add_done_callback(
            lambda _, ts=ts: lat.append(time.perf_counter() - ts))
        futs.append(f)
    tokens = sum(len(f.result(timeout)) for f in futs)
    seconds = time.perf_counter() - t0
    expected = trace.total_new_tokens
    if tokens != expected:
        raise InvalidArgumentError(
            f"trace replay produced {tokens} tokens, expected {expected}")
    lat_ms = np.asarray(sorted(lat)) * 1e3
    return {
        "tokens": tokens,
        "seconds": round(seconds, 4),
        "tokens_per_sec": round(tokens / max(seconds, 1e-9), 2),
        "mean_ms": round(float(lat_ms.mean()), 2),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "requests": len(trace),
    }

"""Alias module: ``paddle.metric.metrics`` — the reference keeps every
metric class in metrics.py and re-exports from the package
(python/paddle/metric/__init__.py); scripts importing the long path keep
working here."""


def __getattr__(name):
    from paddle_tpu import metric as _m

    try:
        return getattr(_m, name)
    except AttributeError:
        raise AttributeError(
            f"module 'paddle_tpu.metric.metrics' has no attribute {name!r}")

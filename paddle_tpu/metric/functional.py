"""Functional metrics: chunk_eval (sequence chunking F1) and mean_iou.

Reference: fluid/layers/nn.py chunk_eval:1047 over
operators/chunk_eval_op.h:40-115 (GetSegments/ChunkBegin/ChunkEnd) and
mean_iou:8845 over operators/mean_iou_op.h:90-112.

chunk_eval is a host-side metric (the reference kernel is CPU-only too);
mean_iou is dense jnp (confusion counts via bincount-style scatter-add)
so it jits and shards.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError

__all__ = ["chunk_eval", "mean_iou"]

#: scheme → (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
#: (chunk_eval_op.h:119-148)
_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_end(prev_tag, prev_type, tag, type_, other, tb, ti, te, ts):
    if prev_type == other:
        return False
    if type_ == other or type_ != prev_type:
        return True
    if prev_tag == tb or prev_tag == ti:
        return tag == tb or tag == ts
    return prev_tag == te or prev_tag == ts


def _chunk_begin(prev_tag, prev_type, tag, type_, other, tb, ti, te, ts):
    if prev_type == other:
        return type_ != other
    if type_ == other:
        return False
    if type_ != prev_type:
        return True
    if tag == tb or tag == ts:
        return True
    if tag == ti or tag == te:
        return prev_tag in (te, ts)
    return False


def _segments(labels, num_tag_types, other, tb, ti, te, ts):
    """Transcribes GetSegments (chunk_eval_op.h:40): label id →
    (tag=id%T, type=id//T); emit (begin, end, type) spans."""
    out = []
    in_chunk = False
    start = 0
    tag, type_ = -1, other
    for i, lab in enumerate(labels):
        prev_tag, prev_type = tag, type_
        tag, type_ = int(lab) % num_tag_types, int(lab) // num_tag_types
        if in_chunk and _chunk_end(prev_tag, prev_type, tag, type_, other,
                                   tb, ti, te, ts):
            out.append((start, i - 1, prev_type))
            in_chunk = False
        if _chunk_begin(prev_tag, prev_type, tag, type_, other,
                        tb, ti, te, ts):
            start = i
            in_chunk = True
    if in_chunk:
        out.append((start, len(labels) - 1, type_))
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-detection precision/recall/F1 for sequence tagging (NER)
    (ref: fluid/layers/nn.py:1047).  Dense batch form: input/label
    ``[N, M]`` (or ``[N, M, 1]``) int labels; ``seq_length`` ``[N]``
    gives valid lengths (dense-padding replacement for the reference's
    LoD input).

    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks) — scalars, reference output order.
    """
    if chunk_scheme not in _SCHEMES:
        raise InvalidArgumentError(
            f"chunk_scheme must be one of {sorted(_SCHEMES)}, "
            f"got {chunk_scheme!r}")
    num_tag, tb, ti, te, ts = _SCHEMES[chunk_scheme]
    other = int(num_chunk_types)
    excluded = set(excluded_chunk_types or ())

    pred = np.asarray(input).astype(np.int64)
    lab = np.asarray(label).astype(np.int64)
    if pred.ndim == 3:
        pred = pred[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    if pred.ndim == 1:
        pred, lab = pred[None], lab[None]
    if pred.shape != lab.shape:
        raise InvalidArgumentError(
            f"input/label shape mismatch: {pred.shape} vs {lab.shape}")
    if (pred.max(initial=0) > num_chunk_types * num_tag
            or lab.max(initial=0) > num_chunk_types * num_tag):
        raise InvalidArgumentError(
            "label ids must be <= num_chunk_types * num_tag_types "
            "(chunk_eval_op.h label check)")
    lengths = (np.asarray(seq_length).astype(np.int64)
               if seq_length is not None
               else np.full(pred.shape[0], pred.shape[1], np.int64))

    n_infer = n_label = n_correct = 0
    for i in range(pred.shape[0]):
        L = int(lengths[i])
        segs_p = [s for s in _segments(pred[i, :L], num_tag, other,
                                       tb, ti, te, ts)
                  if s[2] not in excluded]
        segs_l = [s for s in _segments(lab[i, :L], num_tag, other,
                                       tb, ti, te, ts)
                  if s[2] not in excluded]
        n_infer += len(segs_p)
        n_label += len(segs_l)
        n_correct += len(set(segs_p) & set(segs_l))

    precision = n_correct / n_infer if n_infer else 0.0
    recall = n_correct / n_label if n_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if n_correct else 0.0)
    return (np.float32(precision), np.float32(recall), np.float32(f1),
            np.int64(n_infer), np.int64(n_label), np.int64(n_correct))


def mean_iou(input, label, num_classes):
    """Mean Intersection-over-Union over classes (ref kernel
    operators/mean_iou_op.h:90-112: correct[c] += pred==label==c, a
    mismatch increments wrong[] for BOTH classes; classes with empty
    denominator are skipped in the mean).

    Returns (mean_iou f32 scalar, out_wrong ``[num_classes]`` i32,
    out_correct ``[num_classes]`` i32).
    """
    pred = jnp.asarray(input).reshape(-1).astype(jnp.int32)
    lab = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    n = int(num_classes)
    hit = pred == lab
    correct = jnp.zeros((n,), jnp.int32).at[
        jnp.where(hit, pred, n)].add(1, mode="drop")
    wrong = jnp.zeros((n,), jnp.int32).at[
        jnp.where(hit, n, pred)].add(1, mode="drop").at[
        jnp.where(hit, n, lab)].add(1, mode="drop")
    denom = correct + wrong
    valid = denom > 0
    iou = correct / jnp.maximum(denom, 1).astype(jnp.float32)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1).astype(jnp.float32)
    return miou.astype(jnp.float32), wrong, correct

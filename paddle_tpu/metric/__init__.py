"""paddle_tpu.metric — evaluation metrics (paddle.metric parity).

Reference: python/paddle/metric/metrics.py — Metric base (:47), Accuracy
(:183), Precision (:305), Recall (:405), Auc (:509).  Metrics accumulate on
host in numpy (they sit outside the jitted step; device outputs are pulled
once per logged batch).
"""
from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

from ..framework.errors import InvalidArgumentError
from .functional import chunk_eval, mean_iou  # noqa: F401
from . import metrics  # noqa: F401  (paddle.metric.metrics alias module)

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy",
           "chunk_eval", "mean_iou"]


class Metric:
    """Base metric: ``reset``/``update``/``accumulate``/``name``.

    ``compute(pred, label)`` optionally pre-processes a step's outputs (it
    may run on device values); its return feeds ``update``.
    """

    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1,), name: str = "acc"):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name
        self.reset()

    def compute(self, pred, label, *args):
        """pred [N, C] scores, label [N] or [N, 1] int → correctness matrix
        [N, maxk].  Host numpy eagerly; traced inputs (the 1F1B schedule
        computes metrics per microbatch on the last stage — ref
        section_worker.cc metric fetches) take the jnp path, mirroring the
        reference where Metric.compute is graph-composable ops."""
        import jax

        if isinstance(pred, jax.core.Tracer) or isinstance(
                label, jax.core.Tracer):
            import jax.numpy as jnp

            lbl = jnp.asarray(label).reshape(pred.shape[0], -1)[:, 0]
            # clamp like the numpy path's [:, :maxk] slice silently does
            k = min(self.maxk, int(pred.shape[-1]))
            _, topk_idx = jax.lax.top_k(jnp.asarray(pred), k)
            return (topk_idx == lbl[:, None]).astype(jnp.float32)
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(pred.shape[0], -1)[:, 0]
        topk_idx = np.argsort(-pred, axis=-1)[:, : self.maxk]
        return (topk_idx == label[:, None]).astype(np.float32)

    def update(self, correct):
        correct = np.asarray(correct)
        accs = []
        for k in self.topk:
            num = correct[:, :k].sum()
            accs.append(num / max(correct.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision: TP/(TP+FP). pred is probability of class 1."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds).flatten().round().astype(np.int64)
        labels = np.asarray(labels).flatten().astype(np.int64)
        if preds.shape != labels.shape:
            raise InvalidArgumentError("pred/label shape mismatch")
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom > 0 else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall: TP/(TP+FN)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds).flatten().round().astype(np.int64)
        labels = np.asarray(labels).flatten().astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom > 0 else 0.0

    def name(self):
        return self._name


def bucket_auc(stat_pos, stat_neg, degenerate: float = 0.0) -> float:
    """Trapezoid AUC over bucketed score histograms, sweeping thresholds
    high→low (the reference's estimate in both metrics.py:509 and the
    fleet metric.py:203).  ``degenerate``: value when one class is empty
    (the two reference surfaces disagree: 0.0 for the Metric, 0.5 for
    fleet.metrics — callers pick)."""
    pos = np.asarray(stat_pos, dtype=np.float64).ravel()
    neg = np.asarray(stat_neg, dtype=np.float64).ravel()
    tot_pos = tot_neg = area = 0.0
    for p, n in zip(pos[::-1], neg[::-1]):
        area += n * (tot_pos + p / 2.0)
        tot_pos += p
        tot_neg += n
    if tot_pos == 0 or tot_neg == 0:
        return degenerate
    return float(area / (tot_pos * tot_neg))


class Auc(Metric):
    """ROC AUC via thresholded confusion histogram (reference uses the same
    bucketed approximation, metrics.py:509 num_thresholds=4095)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.curve = curve
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:  # [N,2] softmax → prob of positive class
            preds = preds[:, 1]
        preds = preds.flatten()
        labels = np.asarray(labels).flatten().astype(np.int64)
        buckets = np.clip(
            (preds * self.num_thresholds).astype(np.int64), 0, self.num_thresholds
        )
        pos = np.bincount(buckets[labels == 1], minlength=self.num_thresholds + 1)
        neg = np.bincount(buckets[labels == 0], minlength=self.num_thresholds + 1)
        self._stat_pos += pos
        self._stat_neg += neg

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        return bucket_auc(self._stat_pos, self._stat_neg, degenerate=0.0)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (ref: metric/metrics.py:742 accuracy op):
    the fraction of rows whose true label appears in the top-k logits.
    ``correct``/``total`` were in-place accumulators in the reference —
    accepted and ignored (use the Accuracy Metric for accumulation)."""
    import jax.numpy as jnp

    logits = jnp.asarray(input)
    y = jnp.asarray(label).reshape(logits.shape[0], -1)[:, :1]
    topk = jnp.argsort(-logits, axis=-1)[:, :k]
    hit = (topk == y).any(axis=-1)
    return hit.astype(logits.dtype).mean()

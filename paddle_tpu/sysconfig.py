"""paddle.sysconfig — include/lib paths for building native extensions.

Parity: python/paddle/sysconfig.py:20,37.  The reference points at its
bundled C++ headers and libpaddle; here native components are plain-C
ABI over ctypes (paddle_tpu.native), so the include dir is the package's
native source tree and the lib dir is the per-user build cache where the
shared objects land after their first-use compile.
"""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include() -> str:
    """Directory holding the native C/C++ sources and headers."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")


def get_lib() -> str:
    """Directory holding the compiled native shared objects (created here
    if no native component has built yet — a -L flag must point at an
    existing directory)."""
    from .native import _CACHE_DIR

    os.makedirs(_CACHE_DIR, exist_ok=True)
    return _CACHE_DIR

"""paddle.sysconfig — include/lib paths for building native extensions.

Parity: python/paddle/sysconfig.py:20,37.  The reference points at its
bundled C++ headers and libpaddle; here native components are plain-C
ABI over ctypes (paddle_tpu.native), so the include dir is the package's
native source tree and the lib dir is the per-user build cache where the
shared objects land after their first-use compile.
"""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib",
           "enable_persistent_compilation_cache",
           "maybe_enable_persistent_compilation_cache",
           "kernel_tuning_cache_path"]


def get_include() -> str:
    """Directory holding the native C/C++ sources and headers."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")


def get_lib() -> str:
    """Directory holding the compiled native shared objects (created here
    if no native component has built yet — a -L flag must point at an
    existing directory)."""
    from .native import _CACHE_DIR

    os.makedirs(_CACHE_DIR, exist_ok=True)
    return _CACHE_DIR


# -- persistent XLA compilation cache ----------------------------------------
_pcc_enabled = False


def enable_persistent_compilation_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` so compiled
    XLA executables survive process restarts (the in-process Executor LRU
    only helps within one run).  Returns the directory used.

    Idempotent; safe to call before or after the first compile — only
    computations compiled afterwards are cached.
    """
    global _pcc_enabled
    import jax

    if not cache_dir:
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "paddle_tpu", "xla_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache even fast compiles / small entries — knob names vary across
    # jax releases, so best-effort
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):
            pass
    _pcc_enabled = True
    return cache_dir


def maybe_enable_persistent_compilation_cache() -> None:
    """Flag-gated hook (FLAGS_persistent_compilation_cache): called from
    ``Executor.__init__`` so setting the flag/env var is all a user needs.
    A value of ``1``/``true`` picks the default directory; any other
    non-empty value is used as the directory itself."""
    if _pcc_enabled:
        return
    from .framework.flags import flag

    val = str(flag("persistent_compilation_cache") or "").strip()
    if not val:
        return
    enable_persistent_compilation_cache(
        None if val.lower() in ("1", "true", "yes", "on") else val)


def kernel_tuning_cache_path() -> str | None:
    """Where the Pallas kernel autotuner persists measured block sizes
    (``FLAGS_kernel_tuning_cache``; the XLA executable cache above is a
    separate store).  ``None`` when disk persistence is disabled."""
    from .ops.autotune import cache_path

    return cache_path()

"""paddle.jit — to_static, save, load.

Parity: python/paddle/fluid/dygraph/jit.py + dygraph_to_static/
(ProgramTranslator, program_translator.py:708, TranslatedLayer in
dygraph/io.py).  The reference needs a whole AST transpiler to turn eager
code into a static Program; here eager code IS traceable — ``to_static``
is jax.jit over the layer's functional projection, and save/load ride the
AOT inference-export format (paddle_tpu.inference).

Semantics kept from the reference:
* ``to_static(layer)`` returns a callable that runs the layer compiled;
  parameters are re-read each call (training continues to work), and
  buffer updates (BN running stats) are written back eagerly.
* ``jit.save`` exports the eval-mode forward + weights; ``jit.load``
  returns a ``TranslatedLayer`` usable like a Layer for inference.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .framework import trace_events
from .framework.errors import InvalidArgumentError
from .nn.layer_base import Layer, functional_call


def _arg_signature(args):
    """Abstract (shape, dtype) per array arg / repr hash per static arg —
    the components jax.jit keys its trace cache on.  Published to
    framework.trace_events so the retrace hazard detector
    (paddle_tpu/analysis/retrace.py) can name the churning argument."""
    sig = []
    for a in args:
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            sig.append(("array", tuple(a.shape), str(a.dtype)))
        elif isinstance(a, (int, float, bool, complex)):
            sig.append(("weak", type(a).__name__))
        else:
            sig.append(("static", repr(a)[:80]))
    return tuple(sig)

__all__ = ["to_static", "not_to_static", "save", "load", "TranslatedLayer",
           "ProgramTranslator", "TracedLayer", "set_code_level",
           "set_verbosity"]

#: global to_static switch (ref: ProgramTranslator.enable —
#: program_translator.py:708); False → wrapped callables run eagerly
_to_static_enabled = True


def _jit_layer_call(layer: Layer, inner_call=None):
    """jit over (params, buffers, training, *args) → (out, new_buffers),
    delegating the substitute/restore contract to functional_call.
    ``inner_call`` overrides the callee for @to_static bound methods
    (calling ``layer(...)`` there would re-enter the descriptor)."""

    def run(params, buffers, training, *args):
        return functional_call(layer, params, *args, buffers=buffers,
                               training=training, return_buffers=True,
                               call=inner_call)

    return jax.jit(run, static_argnums=(2,))


class StaticFunction:
    """Compiled wrapper over a Layer, a bound method, or a pure fn — the
    TranslatedLayer-before-save analogue.  Retracing follows jax.jit rules
    (new input shapes/dtypes or a flipped training mode retrace; new param
    VALUES don't).

    Also a descriptor, so the canonical paddle pattern works::

        class Net(nn.Layer):
            @jit.to_static
            def forward(self, x): ...
    """

    def __init__(self, fn, input_spec=None, _bound_layer=None):
        from .dy2static import convert_to_static

        self._orig = fn
        self._input_spec = input_spec
        self._layer = fn if isinstance(fn, Layer) else _bound_layer
        if isinstance(fn, Layer):
            # transpile the forward's data-dependent control flow (the
            # reference transpiles Layer.forward — program_translator.py);
            # an instance-assigned bound forward is transpiled too, and the
            # converted forward is swapped in THROUGH Layer.__call__ so
            # forward pre/post hooks (quantization, weight-norm) stay live
            import inspect as _inspect

            inst_fwd = fn.__dict__.get("forward")
            if inst_fwd is not None and _inspect.ismethod(inst_fwd):
                target = inst_fwd.__func__
            elif inst_fwd is None:
                target = type(fn).forward
            else:
                target = None  # instance forward without self: keep native
            conv = convert_to_static(target) if target is not None else None
            if conv is None or conv is target:
                inner = None  # nothing rewritten — plain layer call path
            else:
                _MISSING = object()

                def inner(*a, _layer=fn, _conv=conv):
                    prev = _layer.__dict__.get("forward", _MISSING)
                    _layer.__dict__["forward"] = (
                        lambda *aa, **kk: _conv(_layer, *aa, **kk))
                    try:
                        return _layer(*a)
                    finally:
                        if prev is _MISSING:
                            del _layer.__dict__["forward"]
                        else:
                            _layer.__dict__["forward"] = prev
            self._jitted = _jit_layer_call(fn, inner)
        elif _bound_layer is not None:
            conv = convert_to_static(fn)
            self._jitted = _jit_layer_call(
                _bound_layer, lambda *a: conv(_bound_layer, *a))
        else:
            self._jitted = jax.jit(convert_to_static(fn))

    def __get__(self, obj, objtype=None):
        """Method-decorator support: bind the wrapped function to the Layer
        instance (per-instance compiled cache)."""
        if obj is None:
            return self
        cache = obj.__dict__.setdefault("_static_methods", {})
        key = id(self)
        if key not in cache:
            if not isinstance(obj, Layer):
                raise InvalidArgumentError(
                    "@to_static methods are supported on nn.Layer "
                    "subclasses (the trace substitutes layer parameters)")
            cache[key] = StaticFunction(self._orig, self._input_spec,
                                        _bound_layer=obj)
        return cache[key]

    def __call__(self, *args, **kwargs):
        iterations = kwargs.pop("iterations", None)
        if iterations is not None:  # fused multi-step form
            return self.run_steps(
                *args, iterations=iterations,
                fetch_every=kwargs.pop("fetch_every", 1), **kwargs)
        if not _to_static_enabled:  # ProgramTranslator.enable(False)
            if self._layer is not None and not isinstance(self._orig, Layer):
                return self._orig(self._layer, *args, **kwargs)
            return self._orig(*args, **kwargs)
        if kwargs:
            raise InvalidArgumentError(
                "to_static calls are positional-only (kwargs change the "
                "trace signature); bind keywords before wrapping")
        if trace_events.active():
            name = getattr(self._orig, "__qualname__",
                           type(self._orig).__name__)
            trace_events.notify(
                ("jit", name),
                {"args": _arg_signature(args),
                 "training": (self._layer.training
                              if self._layer is not None else None)})
        try:
            layer = self._layer
            if layer is None:
                return self._jitted(*args)
            params = layer.param_pytree()
            buffers = layer.buffer_pytree()
            out, new_bufs = self._jitted(params, buffers, layer.training,
                                         *args)
        except jax.errors.TracerBoolConversionError as e:
            # the AST-lite transpiler (paddle_tpu/dy2static.py) rewrites
            # if/while/for-range on tensors; landing here means the
            # construct was one it declines (return/break/raise inside a
            # data-dependent branch, or control flow in an undecorated
            # callee) — name the manual rewrites
            raise InvalidArgumentError(
                "to_static: this Python `if`/`while` on a tensor value "
                "could not be transpiled.  The AST pass skips branches "
                "containing return/break/continue/raise (assign a flag "
                "and return after the branch) and does not transform "
                "functions CALLED from the decorated one (decorate the "
                "callee too).  Alternatively use the callable forms — "
                "fluid.layers.cond / while_loop / case / switch_case.  "
                f"Original: {e}") from e
        boxes = dict(layer.named_buffers())
        for name, v in new_bufs.items():  # eager BN-stat semantics
            boxes[name].value = v
        return out

    # -- fused multi-step execution ------------------------------------------
    def run_steps(self, *stacked_args, iterations=None, fetch_every=1):
        """Run N forward steps as ONE jitted ``lax.scan`` dispatch.

        Each positional arg carries a leading ``iterations`` axis (the
        superbatch format ``DataLoader(superbatch=k)`` yields); buffers (BN
        running stats, step counters) are carried across the chain and
        written back once at the end, so N calls cost one device round-trip
        instead of N.  ``fetch_every=k`` keeps every k-th step's outputs
        (selected inside the jit).  Returns outputs with a leading
        ``N // fetch_every`` axis.  Equivalent to ``fn(..., iterations=N)``.
        """
        fetch_every = int(fetch_every)
        if fetch_every < 1:
            raise InvalidArgumentError("fetch_every must be >= 1")
        if iterations is None:
            for a in stacked_args:
                if hasattr(a, "shape") and len(a.shape) >= 1:
                    iterations = int(a.shape[0])
                    break
        if iterations is None:
            raise InvalidArgumentError(
                "run_steps needs iterations=N or at least one stacked "
                "array argument to infer the chain length from")
        n_steps = int(iterations)
        if n_steps < 1:
            raise InvalidArgumentError("run_steps needs iterations >= 1")
        for a in stacked_args:
            if hasattr(a, "shape") and (len(a.shape) < 1
                                        or int(a.shape[0]) != n_steps):
                raise InvalidArgumentError(
                    f"run_steps: stacked arg has leading dim "
                    f"{tuple(a.shape)[:1]}, expected iterations={n_steps}")

        if not _to_static_enabled:  # eager fallback: real per-step loop
            outs = [self(*[a[t] for a in stacked_args])
                    for t in range(n_steps)]
            outs = outs[fetch_every - 1::fetch_every]
            return jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, 0), *outs)

        if trace_events.active():
            name = getattr(self._orig, "__qualname__",
                           type(self._orig).__name__)
            trace_events.notify(
                ("jit", name),
                {"args": _arg_signature(stacked_args),
                 "mode": f"run_steps[{n_steps}]",
                 "training": (self._layer.training
                              if self._layer is not None else None)})
        layer = self._layer
        if layer is None:  # pure function: no state to carry
            chain = self._get_chain(None, fetch_every, n_steps)
            return chain(tuple(stacked_args))
        chain = self._get_chain(layer.training, fetch_every, n_steps)
        out, new_bufs = chain(layer.param_pytree(), layer.buffer_pytree(),
                              tuple(stacked_args))
        boxes = dict(layer.named_buffers())
        for name, v in new_bufs.items():
            boxes[name].value = v
        return out

    def _get_chain(self, training, fetch_every, n_steps):
        """Memoized scan-of-self._jitted chains, keyed like jax.jit would
        key (training flag is a static arg; n_steps/fetch_every shape the
        scan)."""
        cache = self.__dict__.setdefault("_chain_cache", {})
        key = (training, fetch_every, n_steps)
        if key in cache:
            return cache[key]
        jitted = self._jitted

        def subsample(ys):
            if fetch_every > 1:
                keep = jnp.arange(fetch_every - 1, n_steps, fetch_every)
                ys = jax.tree_util.tree_map(lambda y: y[keep], ys)
            return ys

        if self._layer is None:
            def chain(stacked):
                def body(carry, xs):
                    return carry, jitted(*xs)

                _, ys = jax.lax.scan(body, 0, stacked, length=n_steps)
                return subsample(ys)

            cache[key] = jax.jit(chain)
            return cache[key]

        def chain(params, buffers, stacked):
            def body(bufs, xs):
                out, nb = jitted(params, bufs, training, *xs)
                return nb, out

            bufs, ys = jax.lax.scan(body, buffers, stacked, length=n_steps)
            return subsample(ys), bufs

        # donate buffers (carried through the scan, rewritten into the
        # layer's boxes after) — NOT params, which stay live layer state
        cache[key] = jax.jit(chain, donate_argnums=(1,))
        return cache[key]

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, **kwargs):
    """Decorator/wrapper: compile a Layer or function for execution.

    Reference surface: paddle.jit.to_static (dygraph/jit.py) — there an
    AST transpiler (dygraph_to_static/program_translator.py:708) rewrites
    Python control flow into Program ops; here tracing is native and the
    CONTRACT is explicit instead:

    * tensor math, layer calls, Python control flow on CONCRETE values
      (shapes, hyperparameters, loop-over-layers) compile as-is;
    * data-dependent control flow must use the callable forms —
      ``fluid.layers.cond(pred, t, f)`` for ``if tensor:``,
      ``fluid.layers.while_loop`` for ``while tensor:``,
      ``case``/``switch_case`` for chains — each is plain Python eagerly
      and the compiled lax primitive under to_static (the same op the
      reference transpiler emits);
    * a Python ``if``/``while`` directly on a tensor raises an
      InvalidArgumentError naming that rewrite (tested in
      tests/test_static_jit_utils.py) rather than a raw tracer error.

    Retracing follows jax.jit rules; see StaticFunction.
    """
    if function is None:
        return functools.partial(to_static, input_spec=input_spec, **kwargs)
    return StaticFunction(function, input_spec)


def not_to_static(fn):
    """Parity no-op: nothing is transpiled, so nothing needs excluding."""
    return fn


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs):
    """Export ``layer`` for inference (reference: paddle.jit.save →
    TranslatedLayer artifacts).  ``input_spec``: InputSpec/example per
    forward input."""
    from .inference import save_inference_model

    target = layer._orig if isinstance(layer, StaticFunction) else layer
    spec = input_spec or (layer._input_spec
                          if isinstance(layer, StaticFunction) else None)
    if spec is None:
        raise InvalidArgumentError(
            "jit.save needs input_spec=[InputSpec(...)] (or wrap with "
            "to_static(input_spec=...))")
    if not isinstance(target, Layer):
        raise InvalidArgumentError("jit.save exports Layers")
    return save_inference_model(path, target, spec)


class TranslatedLayer:
    """A loaded inference module, callable like a Layer (reference:
    dygraph/io.py TranslatedLayer over the saved program)."""

    def __init__(self, predictor):
        self._predictor = predictor
        self.training = False

    def __call__(self, *inputs):
        outs = self._predictor.run([np.asarray(x) for x in inputs])
        return outs[0] if len(outs) == 1 else tuple(outs)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise InvalidArgumentError(
            "a loaded inference module is eval-only (the reference's "
            "TranslatedLayer trains only if exported with trainable "
            "programs — export params + rebuild the Layer to fine-tune)")


def load(path: str) -> TranslatedLayer:
    from .inference import Predictor

    return TranslatedLayer(Predictor(path))


class ProgramTranslator:
    """Global to_static control (ref: dygraph_to_static/
    program_translator.py:708).  The reference's singleton owns an AST
    transpiler cache; here compilation is jax.jit, so the surviving
    responsibility is the enable/disable switch (debugging escape hatch:
    ``ProgramTranslator().enable(False)`` runs wrapped code eagerly)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static: bool):
        global _to_static_enabled
        if not isinstance(enable_to_static, bool):
            raise InvalidArgumentError(
                "ProgramTranslator.enable expects a bool")
        _to_static_enabled = enable_to_static

    @property
    def enable_to_static(self) -> bool:
        return _to_static_enabled


_code_level = 0


def set_code_level(level: int = 100):
    """Ref: dygraph_to_static logging_utils.set_code_level — print the
    AST-transformed code.  With the AST-lite transpiler
    (paddle_tpu/dy2static.py) this now prints the transformed source of
    every function converted AFTER the call; the lowered XLA view stays
    available via jax.jit(fn).lower(*args).as_text()."""
    global _code_level
    _code_level = level


def get_code_level() -> int:
    return _code_level


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """Ref: logging_utils.set_verbosity — dy2static transpiler log level
    (alias of set_code_level here: one transform stage, one printout)."""
    set_code_level(level)


class _Dy2Static:
    """Namespace for paddle.jit.dy2static — the AST-lite transpiler
    (paddle_tpu/dy2static.py replaces fluid/dygraph/dygraph_to_static/:
    ifelse/loop/logical transformers → lax.cond/while_loop dispatch)."""

    @property
    def ProgramTranslator(self):
        return ProgramTranslator

    @property
    def convert_to_static(self):
        from .dy2static import convert_to_static

        return convert_to_static

    @property
    def Dy2StaticError(self):
        from .dy2static import Dy2StaticError

        return Dy2StaticError


dy2static = _Dy2Static()


class TracedLayer:
    """Trace a dygraph Layer into a deployable artifact (ref:
    fluid/dygraph/jit.py TracedLayer over ProgramDescTracer).  Here the
    'trace' IS jax.jit of the layer's functional projection; saving
    AOT-exports StableHLO (paddle_tpu.inference format).
    """

    def __init__(self, layer: Layer, example_inputs):
        self._layer = layer
        self._inputs = list(example_inputs)
        self._fn = _jit_layer_call(layer)

    @staticmethod
    def trace(layer: Layer, inputs):
        """→ (example_outputs, TracedLayer) — reference signature."""
        if not isinstance(layer, Layer):
            raise InvalidArgumentError("TracedLayer.trace expects a Layer")
        traced = TracedLayer(layer, inputs)
        return traced(*inputs), traced

    def __call__(self, *args):
        out, new_bufs = self._fn(self._layer.param_pytree(),
                                 self._layer.buffer_pytree(),
                                 self._layer.training, *args)
        boxes = dict(self._layer.named_buffers())
        for name, v in new_bufs.items():
            boxes[name].value = v
        return out

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        """Export for serving (ref signature kept; feed/fetch index
        filters applied by the reference are meaningless for a
        single-signature jax export and are accepted unchecked)."""
        from .inference import save_inference_model as _save
        from .static import InputSpec

        specs = [InputSpec.from_tensor(np.asarray(x), name=f"x{i}")
                 for i, x in enumerate(self._inputs)]
        return _save(path, self._layer, specs)

"""Model compression (slim) — quantization.

Capability parity: python/paddle/fluid/contrib/slim/quantization (the
reference's QAT program passes + imperative QAT + post-training
quantization).  See :mod:`paddle_tpu.slim.quantization`.
"""
from . import quantization  # noqa: F401
from .quantization import (  # noqa: F401
    FakeQuantAbsMax,
    FakeQuantChannelWiseAbsMax,
    FakeQuantMovingAverage,
    ImperativeQuantAware,
    Int8Conv2D,
    Int8Linear,
    MovingAverageAbsMaxScale,
    PostTrainingQuantization,
    QuantizedConv2D,
    QuantizedLinear,
    export_quantized,
    fake_quant_dequant,
    quantize_model_trees,
    quantize_to_fp8,
    quantize_to_int8,
    quantize_weights,
)

__all__ = quantization.__all__

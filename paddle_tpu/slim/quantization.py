"""Quantization: QAT fake-quant layers, post-training quant, int8 layers.

Capability parity (reference):
  ImperativeQuantAware / QuantizedConv2D / QuantizedLinear / FakeQuant*
      contrib/slim/quantization/imperative/qat.py:50, quant_nn.py:32-500
  PostTrainingQuantization
      contrib/slim/quantization/post_training_quantization.py:120
  QuantizationTransformPass (static-graph fake-quant insertion)
      contrib/slim/quantization/quantization_pass.py:211 — subsumed: there
      is no Program IR here, the imperative wrappers ARE the transform.

TPU-native design:
  * fake quant-dequant is a straight-through estimator around
    round/clip — everything stays jit-able and differentiable, and XLA
    fuses the qdq arithmetic into the surrounding matmul/conv.
  * observers are Layer buffers (scale/state/accum), updated functionally
    in training mode exactly like BN running stats, so QAT works under
    ``functional_call``/donated train steps and lax.scan loops.
  * int8 inference layers store int8 weights and run the matmul/conv with
    int8 operands accumulating in int32 on the MXU
    (``preferred_element_type=int32``) — real low-precision compute, not
    a dequantize-then-float emulation; the scales fold into one output
    multiplier.  They export through the standard StableHLO path
    (:mod:`paddle_tpu.inference`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError
from ..nn import functional as F
from ..nn.layer_base import Layer

__all__ = [
    "fake_quant_dequant", "FakeQuantAbsMax", "FakeQuantMovingAverage",
    "FakeQuantChannelWiseAbsMax", "MovingAverageAbsMaxScale",
    "QuantizedConv2D", "QuantizedLinear", "ImperativeQuantAware",
    "quantize_to_int8", "Int8Linear", "Int8Conv2D",
    "PostTrainingQuantization",
]


def fake_quant_dequant(x, scale, bits=8):
    """Straight-through fake quantize-dequantize.

    out = round(clip(x, ±scale) / scale * r) * scale / r,  r = 2^(b-1)-1
    (quant_nn.py FakeQuantMovingAverage formula); the gradient is the
    identity (the reference's fake_quantize_dequantize grad kernel).
    """
    r = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-9)
    xf = jnp.asarray(x, jnp.float32)
    q = jnp.round(jnp.clip(xf, -scale, scale) / scale * r) * scale / r
    return (xf + jax.lax.stop_gradient(q - xf)).astype(x.dtype)


class FakeQuantAbsMax(Layer):
    """Dynamic per-tensor abs-max fake quant (quant_nn.py:130): the scale
    is recomputed from the current tensor every call — the reference's
    weight quantizer."""

    def __init__(self, name=None, quant_bits=8, dtype="float32"):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, x):
        scale = jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)))
        return fake_quant_dequant(x, scale, self._quant_bits)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel abs-max weight fake quant (quant_nn.py:213).
    ``channel_axis`` is the output-channel axis of the weight layout."""

    def __init__(self, name=None, quant_bits=8, channel_axis=0,
                 dtype="float32"):
        super().__init__()
        self._quant_bits = quant_bits
        self._axis = channel_axis

    def forward(self, x):
        xf = jnp.asarray(x, jnp.float32)
        axes = tuple(i for i in range(xf.ndim) if i != self._axis)
        scale = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
        return fake_quant_dequant(x, scale, self._quant_bits)


class FakeQuantMovingAverage(Layer):
    """Moving-average abs-max fake quant (quant_nn.py:32).

    scale = (rate·accum + |x|max) / (rate·state + 1), with accum/state
    accumulated over training steps; eval uses the stored scale.  The
    stats are buffers so the update is functional (like BN)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self._quant_bits = quant_bits
        self.register_buffer("_scale", jnp.asarray([0.001], jnp.float32))
        self.register_buffer("_state", jnp.asarray([1.0], jnp.float32))
        self.register_buffer("_accum", jnp.asarray([1.0], jnp.float32))

    def forward(self, x):
        if self.training:
            scale = _update_moving_stats(self, x)
        else:
            scale = self._scale.value
        return fake_quant_dequant(x, scale.reshape(()), self._quant_bits)

    @property
    def scale(self):
        return self._scale.value.reshape(())


class MovingAverageAbsMaxScale(Layer):
    """Output-scale observer (quant_nn.py:500): records the moving-average
    abs-max of whatever flows through, passes the tensor unchanged."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self.register_buffer("_scale", jnp.asarray([0.001], jnp.float32))
        self.register_buffer("_state", jnp.asarray([1.0], jnp.float32))
        self.register_buffer("_accum", jnp.asarray([1.0], jnp.float32))

    def forward(self, x):
        if self.training:
            _update_moving_stats(self, x)
        return x

    @property
    def scale(self):
        return self._scale.value.reshape(())


def _replace_sublayer(model, dotted_name, new_layer):
    """Swap the sublayer at a named_sublayers path: every registered child
    lives in its parent's ``_sub_layers`` dict keyed by its path segment,
    regardless of whether it was attached by attribute or container."""
    parts = dotted_name.split(".")
    parent = model
    for p in parts[:-1]:
        parent = parent._sub_layers[p]
    parent._sub_layers[parts[-1]] = new_layer


def _update_moving_stats(obs, x):
    """scale = (rate·accum + |x|max) / (rate·state + 1) — the one shared
    moving-average observer update (quant_nn.py:81)."""
    cur = jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)))
    state = obs._state.value * obs._moving_rate + 1.0
    accum = obs._accum.value * obs._moving_rate + cur
    obs._state.value = state
    obs._accum.value = accum
    obs._scale.value = accum / state
    return obs._scale.value


def _weight_quantizer(kind, bits, channel_axis, rate=0.9):
    if kind == "abs_max":
        return FakeQuantAbsMax(quant_bits=bits)
    if kind == "channel_wise_abs_max":
        return FakeQuantChannelWiseAbsMax(quant_bits=bits,
                                          channel_axis=channel_axis)
    if kind == "moving_average_abs_max":
        return FakeQuantMovingAverage(moving_rate=rate, quant_bits=bits)
    raise InvalidArgumentError(f"unknown weight_quantize_type {kind!r}")


def _act_quantizer(kind, bits, rate):
    if kind == "abs_max":
        return FakeQuantAbsMax(quant_bits=bits)
    if kind == "moving_average_abs_max":
        return FakeQuantMovingAverage(moving_rate=rate, quant_bits=bits)
    raise InvalidArgumentError(f"unknown activation_quantize_type {kind!r}")


class QuantizedConv2D(Layer):
    """Conv2D with fake-quantized input + weight (quant_nn.py:323).  Wraps
    an existing Conv2D, sharing its Parameters."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__()
        self._inner = layer
        # OIHW weights: output channel axis 0
        self._fake_quant_weight = _weight_quantizer(
            weight_quantize_type, weight_bits, channel_axis=0,
            rate=moving_rate)
        self._fake_quant_input = _act_quantizer(
            activation_quantize_type, activation_bits, moving_rate)

    def forward(self, x):
        inner = self._inner
        x = self._fake_quant_input(x)
        w = self._fake_quant_weight(inner.weight.value)
        return F.conv2d(x, w, inner._bias(), inner.stride, inner.padding,
                        inner.dilation, inner.groups,
                        inner.data_format or "NCHW")


class QuantizedLinear(Layer):
    """Linear with fake-quantized input + weight (quant_nn.py:419)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__()
        self._inner = layer
        # (in, out) weights: output channel axis 1
        self._fake_quant_weight = _weight_quantizer(
            weight_quantize_type, weight_bits, channel_axis=1,
            rate=moving_rate)
        self._fake_quant_input = _act_quantizer(
            activation_quantize_type, activation_bits, moving_rate)

    def forward(self, x):
        inner = self._inner
        x = self._fake_quant_input(x)
        w = self._fake_quant_weight(inner.weight.value)
        out = jnp.asarray(x) @ w
        if inner.bias is not None:
            out = out + inner.bias.value
        return out


class ImperativeQuantAware:
    """Rewrite a model in place for quantization-aware training
    (qat.py:50): every quantizable sublayer is replaced by its fake-quant
    counterpart.  Fine-tune, then :meth:`convert` for int8 inference."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9,
                 quantizable_layer_type=("Conv2D", "Linear")):
        from .. import nn

        if activation_quantize_type == "channel_wise_abs_max":
            raise InvalidArgumentError(
                "activations cannot quantize channel-wise")
        self._kw = dict(weight_bits=weight_bits,
                        activation_bits=activation_bits,
                        moving_rate=moving_rate,
                        weight_quantize_type=weight_quantize_type,
                        activation_quantize_type=activation_quantize_type)
        name_map = {"Conv2D": nn.Conv2D, "Linear": nn.Linear}
        self._types = tuple(name_map[t] if isinstance(t, str) else t
                            for t in quantizable_layer_type)

    def quantize(self, model):
        from .. import nn

        for name, layer in list(model.named_sublayers()):
            if not isinstance(layer, self._types):
                continue
            if isinstance(layer, nn.Conv2D):
                q = QuantizedConv2D(layer, **self._kw)
            else:
                q = QuantizedLinear(layer, **self._kw)
            _replace_sublayer(model, name, q)
        return model

    def convert(self, model):
        """Freeze a fine-tuned QAT model to int8 inference layers, using
        the trained moving-average activation scales (the reference's
        QuantizationFreezePass + ConvertToInt8Pass in one step)."""
        from .. import nn

        for name, layer in list(model.named_sublayers()):
            if not isinstance(layer, (QuantizedConv2D, QuantizedLinear)):
                continue
            act_q = layer._fake_quant_input
            if not hasattr(act_q, "scale"):
                raise InvalidArgumentError(
                    "convert() needs a trained static activation scale: "
                    "use activation_quantize_type='moving_average_abs_max' "
                    "(abs_max recomputes per batch and cannot freeze, like "
                    "the reference QuantizationFreezePass)")
            if float(jnp.asarray(act_q._state.value).reshape(())) == 1.0:
                raise InvalidArgumentError(
                    f"activation observer for {name!r} never saw data: run "
                    "training-mode forwards before convert() (the scale is "
                    "still its init value)")
            act_scale = float(jnp.asarray(act_q.scale).reshape(()))
            if isinstance(layer, QuantizedConv2D):
                q = Int8Conv2D.from_float(layer._inner, act_scale)
            else:
                q = Int8Linear.from_float(layer._inner, act_scale)
            _replace_sublayer(model, name, q)
        return model


def quantize_to_int8(w, channel_axis=None):
    """w (float) → (int8 weights, float32 scale) by (channel-wise) abs-max."""
    wf = jnp.asarray(w, jnp.float32)
    if channel_axis is None:
        scale = jnp.max(jnp.abs(wf))
    else:
        axes = tuple(i for i in range(wf.ndim) if i != channel_axis)
        scale = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(wf / scale * 127.0), -127, 127).astype(jnp.int8)
    return q, scale


class Int8Linear(Layer):
    """Inference linear with int8 weights AND int8 activations: the matmul
    runs on int8 operands with an int32 accumulator
    (``preferred_element_type``), then one fused float rescale."""

    def __init__(self, w_int8, w_scale, bias, act_scale):
        super().__init__()
        self.register_buffer("w_q", w_int8)
        self.register_buffer("w_scale", jnp.asarray(w_scale, jnp.float32))
        if bias is not None:
            self.register_buffer("bias", jnp.asarray(bias, jnp.float32))
        else:
            self.bias = None
        self.act_scale = max(float(act_scale), 1e-9)

    @classmethod
    def from_float(cls, linear, act_scale):
        wq, ws = quantize_to_int8(linear.weight.value, channel_axis=1)
        b = None if linear.bias is None else linear.bias.value
        return cls(wq, ws, b, act_scale)

    def forward(self, x):
        xf = jnp.asarray(x, jnp.float32)
        xq = jnp.clip(jnp.round(xf / self.act_scale * 127.0),
                      -127, 127).astype(jnp.int8)
        # dot_general handles any leading batch dims ([B, S, F] transformer
        # inputs included); int8 operands, int32 accumulator
        acc = jax.lax.dot_general(
            xq, self.w_q.value, (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (
            self.w_scale.value.reshape(1, -1)
            * (self.act_scale / (127.0 * 127.0)))
        if self.bias is not None:
            out = out + self.bias.value
        return out.astype(x.dtype)


class Int8Conv2D(Layer):
    """Inference conv with int8 weights/activations, int32 MXU accumulate."""

    def __init__(self, w_int8, w_scale, bias, act_scale, stride, padding,
                 dilation, groups, data_format):
        super().__init__()
        self.register_buffer("w_q", w_int8)
        self.register_buffer("w_scale", jnp.asarray(w_scale, jnp.float32))
        if bias is not None:
            self.register_buffer("bias", jnp.asarray(bias, jnp.float32))
        else:
            self.bias = None
        self.act_scale = max(float(act_scale), 1e-9)
        self._cfg = (stride, padding, dilation, groups, data_format)

    @classmethod
    def from_float(cls, conv, act_scale):
        wq, ws = quantize_to_int8(conv.weight.value, channel_axis=0)
        b = conv._bias()
        return cls(wq, ws, b, act_scale, conv.stride, conv.padding,
                   conv.dilation, conv.groups, conv.data_format or "NCHW")

    def forward(self, x):
        from ..nn.functional import conv as _conv

        stride, padding, dilation, groups, data_format = self._cfg
        xf = jnp.asarray(x, jnp.float32)
        xq = jnp.clip(jnp.round(xf / self.act_scale * 127.0),
                      -127, 127).astype(jnp.int8)
        acc = _conv._conv_nd(xq, self.w_q.value, None, stride, padding,
                             dilation, groups, 2,
                             data_format in ("NHWC",),
                             preferred_element_type=jnp.int32)
        ch_axis = -1 if data_format == "NHWC" else 1
        shape = [1] * acc.ndim
        shape[ch_axis] = acc.shape[ch_axis]
        scale = self.w_scale.value.reshape(shape) * (
            self.act_scale / (127.0 * 127.0))
        out = acc.astype(jnp.float32) * scale
        if self.bias is not None:
            b_shape = [1] * acc.ndim
            b_shape[ch_axis] = acc.shape[ch_axis]
            out = out + self.bias.value.reshape(b_shape)
        return out.astype(x.dtype)


class PostTrainingQuantization:
    """Post-training quantization (post_training_quantization.py:120),
    eager-style: feed calibration batches, observe activation abs-max at
    every quantizable layer input, then freeze to int8 layers.

    Usage::

        ptq = PostTrainingQuantization(model)
        for batch in calib_data:
            ptq.collect(batch)           # runs the model, records scales
        int8_model = ptq.quantize()      # model rewritten with Int8 layers
    """

    def __init__(self, model, algo="abs_max", activation_bits=8,
                 weight_bits=8, quantizable_layer_type=("Conv2D", "Linear")):
        from .. import nn

        if algo not in ("abs_max", "avg"):
            raise InvalidArgumentError(
                f"algo must be abs_max or avg, got {algo!r} (KL calibration "
                "is not implemented)")
        if activation_bits != 8 or weight_bits != 8:
            raise InvalidArgumentError("only 8-bit PTQ is implemented")
        self._model = model
        self._algo = algo
        name_map = {"Conv2D": nn.Conv2D, "Linear": nn.Linear}
        self._types = tuple(name_map[t] if isinstance(t, str) else t
                            for t in quantizable_layer_type)
        self._stats = {}   # layer name → list of batch abs-max
        self._targets = {n: l for n, l in model.named_sublayers()
                         if isinstance(l, self._types)}
        self._hooks = []
        for name, layer in self._targets.items():
            self._hooks.append(layer.register_forward_pre_hook(
                self._make_hook(name)))

    def _make_hook(self, name):
        def hook(layer, inputs):
            x = inputs[0]
            self._stats.setdefault(name, []).append(
                float(jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)))))
            return None
        return hook

    def collect(self, *batch):
        """Run one calibration batch through the model (eval mode)."""
        self._model.eval()
        return self._model(*batch)

    def quantize(self):
        """Freeze observed scales into Int8 layers; returns the model."""
        from .. import nn

        for h in self._hooks:
            h.remove()
        for name, layer in self._targets.items():
            obs = self._stats.get(name)
            if not obs:
                raise InvalidArgumentError(
                    f"no calibration data flowed through layer {name!r}")
            act_scale = (max(obs) if self._algo == "abs_max"
                         else sum(obs) / len(obs))
            if isinstance(layer, nn.Conv2D):
                q = Int8Conv2D.from_float(layer, act_scale)
            else:
                q = Int8Linear.from_float(layer, act_scale)
            _replace_sublayer(self._model, name, q)
        return self._model

"""Quantization: QAT fake-quant layers, post-training quant, int8 layers.

Capability parity (reference):
  ImperativeQuantAware / QuantizedConv2D / QuantizedLinear / FakeQuant*
      contrib/slim/quantization/imperative/qat.py:50, quant_nn.py:32-500
  PostTrainingQuantization
      contrib/slim/quantization/post_training_quantization.py:120
  QuantizationTransformPass (static-graph fake-quant insertion)
      contrib/slim/quantization/quantization_pass.py:211 — subsumed: there
      is no Program IR here, the imperative wrappers ARE the transform.

TPU-native design:
  * fake quant-dequant is a straight-through estimator around
    round/clip — everything stays jit-able and differentiable, and XLA
    fuses the qdq arithmetic into the surrounding matmul/conv.
  * observers are Layer buffers (scale/state/accum), updated functionally
    in training mode exactly like BN running stats, so QAT works under
    ``functional_call``/donated train steps and lax.scan loops.
  * int8 inference layers store int8 weights and run the matmul/conv with
    int8 operands accumulating in int32 on the MXU
    (``preferred_element_type=int32``) — real low-precision compute, not
    a dequantize-then-float emulation; the scales fold into one output
    multiplier.  They export through the standard StableHLO path
    (:mod:`paddle_tpu.inference`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.errors import InvalidArgumentError
from ..nn import functional as F
from ..nn.layer_base import Layer

__all__ = [
    "fake_quant_dequant", "FakeQuantAbsMax", "FakeQuantMovingAverage",
    "FakeQuantChannelWiseAbsMax", "MovingAverageAbsMaxScale",
    "QuantizedConv2D", "QuantizedLinear", "ImperativeQuantAware",
    "quantize_to_int8", "quantize_to_fp8", "Int8Linear", "Int8Conv2D",
    "PostTrainingQuantization", "quantize_weights",
    "quantize_model_trees", "export_quantized",
]

#: serving quantization modes (``GPTConfig.quantization`` values minus
#: "none"); fp8 is the e4m3 convention of Micikevicius et al. 2022
QUANT_MODES = ("int8", "fp8")

#: largest finite float8_e4m3fn value — e4m3fn has no inf, overflow on
#: cast becomes NaN, so quantizers must clip to ±448 BEFORE the cast
FP8_E4M3_MAX = 448.0


def _notify_quant(name, **info):
    """Latest-value ``("quant", <site>)`` telemetry on the event bus —
    RetraceMonitor.quant_stats() / rule Q801 consume these snapshots."""
    from ..framework import trace_events

    if trace_events.active():
        trace_events.notify(("quant", name), dict(info))


def fake_quant_dequant(x, scale, bits=8):
    """Straight-through fake quantize-dequantize.

    out = round(clip(x, ±scale) / scale * r) * scale / r,  r = 2^(b-1)-1
    (quant_nn.py FakeQuantMovingAverage formula); the gradient is the
    identity (the reference's fake_quantize_dequantize grad kernel).
    """
    r = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-9)
    xf = jnp.asarray(x, jnp.float32)
    q = jnp.round(jnp.clip(xf, -scale, scale) / scale * r) * scale / r
    return (xf + jax.lax.stop_gradient(q - xf)).astype(x.dtype)


class FakeQuantAbsMax(Layer):
    """Dynamic per-tensor abs-max fake quant (quant_nn.py:130): the scale
    is recomputed from the current tensor every call — the reference's
    weight quantizer."""

    def __init__(self, name=None, quant_bits=8, dtype="float32"):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, x):
        scale = jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)))
        return fake_quant_dequant(x, scale, self._quant_bits)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel abs-max weight fake quant (quant_nn.py:213).
    ``channel_axis`` is the output-channel axis of the weight layout."""

    def __init__(self, name=None, quant_bits=8, channel_axis=0,
                 dtype="float32"):
        super().__init__()
        self._quant_bits = quant_bits
        self._axis = channel_axis

    def forward(self, x):
        xf = jnp.asarray(x, jnp.float32)
        axes = tuple(i for i in range(xf.ndim) if i != self._axis)
        scale = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
        return fake_quant_dequant(x, scale, self._quant_bits)


class FakeQuantMovingAverage(Layer):
    """Moving-average abs-max fake quant (quant_nn.py:32).

    scale = (rate·accum + |x|max) / (rate·state + 1), with accum/state
    accumulated over training steps; eval uses the stored scale.  The
    stats are buffers so the update is functional (like BN)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self._quant_bits = quant_bits
        self.register_buffer("_scale", jnp.asarray([0.001], jnp.float32))
        self.register_buffer("_state", jnp.asarray([1.0], jnp.float32))
        self.register_buffer("_accum", jnp.asarray([1.0], jnp.float32))

    def forward(self, x):
        if self.training:
            scale = _update_moving_stats(self, x)
        else:
            scale = self._scale.value
        return fake_quant_dequant(x, scale.reshape(()), self._quant_bits)

    @property
    def scale(self):
        return self._scale.value.reshape(())


class MovingAverageAbsMaxScale(Layer):
    """Output-scale observer (quant_nn.py:500): records the moving-average
    abs-max of whatever flows through, passes the tensor unchanged."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self.register_buffer("_scale", jnp.asarray([0.001], jnp.float32))
        self.register_buffer("_state", jnp.asarray([1.0], jnp.float32))
        self.register_buffer("_accum", jnp.asarray([1.0], jnp.float32))

    def forward(self, x):
        if self.training:
            _update_moving_stats(self, x)
        return x

    @property
    def scale(self):
        return self._scale.value.reshape(())


def _replace_sublayer(model, dotted_name, new_layer):
    """Swap the sublayer at a named_sublayers path: every registered child
    lives in its parent's ``_sub_layers`` dict keyed by its path segment,
    regardless of whether it was attached by attribute or container."""
    parts = dotted_name.split(".")
    parent = model
    for p in parts[:-1]:
        parent = parent._sub_layers[p]
    parent._sub_layers[parts[-1]] = new_layer


def _update_moving_stats(obs, x):
    """scale = (rate·accum + |x|max) / (rate·state + 1) — the one shared
    moving-average observer update (quant_nn.py:81)."""
    cur = jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)))
    state = obs._state.value * obs._moving_rate + 1.0
    accum = obs._accum.value * obs._moving_rate + cur
    obs._state.value = state
    obs._accum.value = accum
    obs._scale.value = accum / state
    return obs._scale.value


def _weight_quantizer(kind, bits, channel_axis, rate=0.9):
    if kind == "abs_max":
        return FakeQuantAbsMax(quant_bits=bits)
    if kind == "channel_wise_abs_max":
        return FakeQuantChannelWiseAbsMax(quant_bits=bits,
                                          channel_axis=channel_axis)
    if kind == "moving_average_abs_max":
        return FakeQuantMovingAverage(moving_rate=rate, quant_bits=bits)
    raise InvalidArgumentError(f"unknown weight_quantize_type {kind!r}")


def _act_quantizer(kind, bits, rate):
    if kind == "abs_max":
        return FakeQuantAbsMax(quant_bits=bits)
    if kind == "moving_average_abs_max":
        return FakeQuantMovingAverage(moving_rate=rate, quant_bits=bits)
    raise InvalidArgumentError(f"unknown activation_quantize_type {kind!r}")


class QuantizedConv2D(Layer):
    """Conv2D with fake-quantized input + weight (quant_nn.py:323).  Wraps
    an existing Conv2D, sharing its Parameters."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__()
        self._inner = layer
        # OIHW weights: output channel axis 0
        self._fake_quant_weight = _weight_quantizer(
            weight_quantize_type, weight_bits, channel_axis=0,
            rate=moving_rate)
        self._fake_quant_input = _act_quantizer(
            activation_quantize_type, activation_bits, moving_rate)

    def forward(self, x):
        inner = self._inner
        x = self._fake_quant_input(x)
        w = self._fake_quant_weight(inner.weight.value)
        return F.conv2d(x, w, inner._bias(), inner.stride, inner.padding,
                        inner.dilation, inner.groups,
                        inner.data_format or "NCHW")


class QuantizedLinear(Layer):
    """Linear with fake-quantized input + weight (quant_nn.py:419)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        super().__init__()
        self._inner = layer
        # (in, out) weights: output channel axis 1
        self._fake_quant_weight = _weight_quantizer(
            weight_quantize_type, weight_bits, channel_axis=1,
            rate=moving_rate)
        self._fake_quant_input = _act_quantizer(
            activation_quantize_type, activation_bits, moving_rate)

    def forward(self, x):
        inner = self._inner
        x = self._fake_quant_input(x)
        w = self._fake_quant_weight(inner.weight.value)
        out = jnp.asarray(x) @ w
        if inner.bias is not None:
            out = out + inner.bias.value
        return out


class ImperativeQuantAware:
    """Rewrite a model in place for quantization-aware training
    (qat.py:50): every quantizable sublayer is replaced by its fake-quant
    counterpart.  Fine-tune, then :meth:`convert` for int8 inference."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9,
                 quantizable_layer_type=("Conv2D", "Linear")):
        from .. import nn

        if activation_quantize_type == "channel_wise_abs_max":
            raise InvalidArgumentError(
                "activations cannot quantize channel-wise")
        self._kw = dict(weight_bits=weight_bits,
                        activation_bits=activation_bits,
                        moving_rate=moving_rate,
                        weight_quantize_type=weight_quantize_type,
                        activation_quantize_type=activation_quantize_type)
        name_map = {"Conv2D": nn.Conv2D, "Linear": nn.Linear}
        self._types = tuple(name_map[t] if isinstance(t, str) else t
                            for t in quantizable_layer_type)

    def quantize(self, model):
        from .. import nn

        for name, layer in list(model.named_sublayers()):
            if not isinstance(layer, self._types):
                continue
            if isinstance(layer, nn.Conv2D):
                q = QuantizedConv2D(layer, **self._kw)
            else:
                q = QuantizedLinear(layer, **self._kw)
            _replace_sublayer(model, name, q)
        return model

    def convert(self, model):
        """Freeze a fine-tuned QAT model to int8 inference layers, using
        the trained moving-average activation scales (the reference's
        QuantizationFreezePass + ConvertToInt8Pass in one step)."""
        from .. import nn

        targets = [(n, l) for n, l in list(model.named_sublayers())
                   if isinstance(l, (QuantizedConv2D, QuantizedLinear))]
        stale = sum(
            1 for _, l in targets
            if hasattr(l._fake_quant_input, "_state")
            and float(jnp.asarray(
                l._fake_quant_input._state.value).reshape(())) == 1.0)
        _notify_quant("qat", kind="calibration", layers=len(targets),
                      calibrated=len(targets) - stale,
                      uncalibrated_layers=stale)
        for name, layer in targets:
            act_q = layer._fake_quant_input
            if not hasattr(act_q, "scale"):
                raise InvalidArgumentError(
                    "convert() needs a trained static activation scale: "
                    "use activation_quantize_type='moving_average_abs_max' "
                    "(abs_max recomputes per batch and cannot freeze, like "
                    "the reference QuantizationFreezePass)")
            if float(jnp.asarray(act_q._state.value).reshape(())) == 1.0:
                raise InvalidArgumentError(
                    f"activation observer for {name!r} never saw data: run "
                    "training-mode forwards before convert() (the scale is "
                    "still its init value)")
            act_scale = float(jnp.asarray(act_q.scale).reshape(()))
            if isinstance(layer, QuantizedConv2D):
                q = Int8Conv2D.from_float(layer._inner, act_scale)
            else:
                q = Int8Linear.from_float(layer._inner, act_scale)
            _replace_sublayer(model, name, q)
        return model


def quantize_to_int8(w, channel_axis=None):
    """w (float) → (int8 weights, float32 scale) by (channel-wise) abs-max."""
    wf = jnp.asarray(w, jnp.float32)
    if channel_axis is None:
        scale = jnp.max(jnp.abs(wf))
    else:
        axes = tuple(i for i in range(wf.ndim) if i != channel_axis)
        scale = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(wf / scale * 127.0), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_to_fp8(w, channel_axis=None):
    """w (float) → (fp8-e4m3 weights, float32 scale) by (channel-wise)
    abs-max, mirroring :func:`quantize_to_int8`: dequant is
    ``q * scale / FP8_E4M3_MAX``.  The clip BEFORE the cast matters:
    e4m3fn has no inf, so an overflowing cast silently produces NaN."""
    wf = jnp.asarray(w, jnp.float32)
    if channel_axis is None:
        scale = jnp.max(jnp.abs(wf))
    else:
        axes = tuple(i for i in range(wf.ndim) if i != channel_axis)
        scale = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scale = jnp.maximum(scale, 1e-9)
    q = jnp.clip(wf / scale * FP8_E4M3_MAX,
                 -FP8_E4M3_MAX, FP8_E4M3_MAX).astype(jnp.float8_e4m3fn)
    return q, scale


_QUANT_DTYPES = ("int8", "float8_e4m3fn")


def _is_quantized_dtype(dtype) -> bool:
    return str(jnp.dtype(dtype)) in _QUANT_DTYPES


def _quantize_weight(w, mode):
    """One [in, out] weight → (quantized weight, [out] float32 dequant
    multiplier): ``w ≈ w_q.astype(f32) * weight_scale`` per channel."""
    if mode == "int8":
        q, scale = quantize_to_int8(w, channel_axis=w.ndim - 1)
        return q, (scale / 127.0).reshape(-1).astype(jnp.float32)
    if mode == "fp8":
        q, scale = quantize_to_fp8(w, channel_axis=w.ndim - 1)
        return q, (scale / FP8_E4M3_MAX).reshape(-1).astype(jnp.float32)
    raise InvalidArgumentError(
        f"quantization mode must be one of {QUANT_MODES}, got {mode!r}")


def _serving_targets(model):
    """The Linear hot paths the quantized serving stack routes: every
    tensor-parallel linear (GPT qkv/out/fc1/fc2, BERT attention + the
    shared ParallelMLP all build on these two classes)."""
    from ..distributed.meta_parallel import (ColumnParallelLinear,
                                             RowParallelLinear)

    return [(n, l) for n, l in model.named_sublayers(include_self=True)
            if isinstance(l, (ColumnParallelLinear, RowParallelLinear))]


def quantize_weights(model, mode="int8"):
    """Quantize a model's parallel-linear weights IN PLACE for serving.

    Each ColumnParallelLinear / RowParallelLinear weight becomes an int8
    (or fp8-e4m3) tensor plus a per-output-channel ``weight_scale``
    buffer; the layers' forwards dispatch on the weight dtype, so the
    swap needs no layer replacement.  Idempotent: already-quantized
    layers are left alone.  Returns the model."""
    if mode not in QUANT_MODES:
        raise InvalidArgumentError(
            f"quantization mode must be one of {QUANT_MODES}, got {mode!r}")
    for _, layer in _serving_targets(model):
        w = layer.weight.value
        if _is_quantized_dtype(w.dtype):
            continue
        wq, ws = _quantize_weight(w, mode)
        spec = getattr(layer.weight, "partition_spec", None)
        layer.weight.value = wq
        if "weight_scale" in layer._buffers:
            layer.weight_scale.value = ws
        else:
            layer.register_buffer("weight_scale", ws)
        if spec is not None:
            layer.weight.partition_spec = spec
    return model


def quantize_model_trees(model, mode="int8"):
    """Non-mutating tree quantization for serving engines: returns
    ``(params, buffers)`` flat pytrees with the parallel-linear weights
    quantized and ``weight_scale`` entries filled in, while the model's
    own weights stay float.

    The scale BUFFER BOXES are registered on the model when absent —
    ``functional_call`` binds tree leaves by dotted name onto existing
    boxes only.  That registration is benign for float engines sharing
    the model: the float forward never reads the scales, and a
    same-structure float tree simply carries the unit scales along.
    This is what lets ``tuning.serving_space`` sweep the quantization
    dial none→int8→fp8 over ONE model without cross-candidate damage."""
    if mode not in QUANT_MODES:
        raise InvalidArgumentError(
            f"quantization mode must be one of {QUANT_MODES}, got {mode!r}")
    targets = _serving_targets(model)
    for _, layer in targets:
        if "weight_scale" not in layer._buffers:
            layer.register_buffer(
                "weight_scale",
                jnp.ones((layer.weight.value.shape[-1],), jnp.float32))
    params = model.param_pytree()
    buffers = model.buffer_pytree()
    for name, layer in targets:
        dot = f"{name}." if name else ""
        wkey, skey = f"{dot}weight", f"{dot}weight_scale"
        w = params[wkey]
        if _is_quantized_dtype(w.dtype):
            continue
        wq, ws = _quantize_weight(w, mode)
        params[wkey] = wq
        buffers[skey] = ws
    return params, buffers


def export_quantized(model, path, mode="int8"):
    """Write a quantized weight artifact: ``<path>.pdiparams`` holding
    the quantized params/buffers trees (plus the mode tag), and a
    ``<path>.pdiparams.manifest.json`` sidecar carrying the artifact's
    sha256 — the same integrity convention the checkpoint manifest uses.

    The artifact is a drop-in for ``Predictor.swap_weights`` /
    ``GenerationEngine.swap_weights`` / ``Router.swap_weights_rolling``
    against an engine built with the matching ``quantized=`` mode: the
    trees keep the exact (shape, dtype) structure those engines compiled
    against, so the hot swap costs zero recompiles.  Returns the
    ``.pdiparams`` path."""
    import json
    import os

    from ..framework import serialization
    from ..incubate.checkpoint import _sha256

    params, buffers = quantize_model_trees(model, mode)
    prefix = (path[: -len(".pdiparams")]
              if path.endswith(".pdiparams") else path)
    artifact = prefix + ".pdiparams"
    serialization.save(
        {"params": params, "buffers": buffers, "quantization": mode},
        artifact)
    manifest = {
        "format": "paddle_tpu.quantized_weights.v1",
        "quantization": mode,
        "file": os.path.basename(artifact),
        "sha256": _sha256(artifact),
        "num_params": len(params),
        "num_buffers": len(buffers),
    }
    mpath = artifact + ".manifest.json"
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, mpath)
    return artifact


class Int8Linear(Layer):
    """Inference linear with int8 weights AND int8 activations: the matmul
    runs on int8 operands with an int32 accumulator
    (``preferred_element_type``), then one fused float rescale."""

    def __init__(self, w_int8, w_scale, bias, act_scale):
        super().__init__()
        self.register_buffer("w_q", w_int8)
        self.register_buffer("w_scale", jnp.asarray(w_scale, jnp.float32))
        if bias is not None:
            self.register_buffer("bias", jnp.asarray(bias, jnp.float32))
        else:
            self.bias = None
        self.act_scale = max(float(act_scale), 1e-9)

    @classmethod
    def from_float(cls, linear, act_scale):
        wq, ws = quantize_to_int8(linear.weight.value, channel_axis=1)
        b = None if linear.bias is None else linear.bias.value
        return cls(wq, ws, b, act_scale)

    def forward(self, x):
        xf = jnp.asarray(x, jnp.float32)
        xq = jnp.clip(jnp.round(xf / self.act_scale * 127.0),
                      -127, 127).astype(jnp.int8)
        # dot_general handles any leading batch dims ([B, S, F] transformer
        # inputs included); int8 operands, int32 accumulator
        acc = jax.lax.dot_general(
            xq, self.w_q.value, (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (
            self.w_scale.value.reshape(1, -1)
            * (self.act_scale / (127.0 * 127.0)))
        if self.bias is not None:
            out = out + self.bias.value
        return out.astype(x.dtype)


class Int8Conv2D(Layer):
    """Inference conv with int8 weights/activations, int32 MXU accumulate."""

    def __init__(self, w_int8, w_scale, bias, act_scale, stride, padding,
                 dilation, groups, data_format):
        super().__init__()
        self.register_buffer("w_q", w_int8)
        self.register_buffer("w_scale", jnp.asarray(w_scale, jnp.float32))
        if bias is not None:
            self.register_buffer("bias", jnp.asarray(bias, jnp.float32))
        else:
            self.bias = None
        self.act_scale = max(float(act_scale), 1e-9)
        self._cfg = (stride, padding, dilation, groups, data_format)

    @classmethod
    def from_float(cls, conv, act_scale):
        wq, ws = quantize_to_int8(conv.weight.value, channel_axis=0)
        b = conv._bias()
        return cls(wq, ws, b, act_scale, conv.stride, conv.padding,
                   conv.dilation, conv.groups, conv.data_format or "NCHW")

    def forward(self, x):
        from ..nn.functional import conv as _conv

        stride, padding, dilation, groups, data_format = self._cfg
        xf = jnp.asarray(x, jnp.float32)
        xq = jnp.clip(jnp.round(xf / self.act_scale * 127.0),
                      -127, 127).astype(jnp.int8)
        acc = _conv._conv_nd(xq, self.w_q.value, None, stride, padding,
                             dilation, groups, 2,
                             data_format in ("NHWC",),
                             preferred_element_type=jnp.int32)
        ch_axis = -1 if data_format == "NHWC" else 1
        shape = [1] * acc.ndim
        shape[ch_axis] = acc.shape[ch_axis]
        scale = self.w_scale.value.reshape(shape) * (
            self.act_scale / (127.0 * 127.0))
        out = acc.astype(jnp.float32) * scale
        if self.bias is not None:
            b_shape = [1] * acc.ndim
            b_shape[ch_axis] = acc.shape[ch_axis]
            out = out + self.bias.value.reshape(b_shape)
        return out.astype(x.dtype)


class PostTrainingQuantization:
    """Post-training quantization (post_training_quantization.py:120),
    eager-style: feed calibration batches, observe activation abs-max at
    every quantizable layer input, then freeze to int8 layers.

    Usage::

        ptq = PostTrainingQuantization(model)
        for batch in calib_data:
            ptq.collect(batch)           # runs the model, records scales
        int8_model = ptq.quantize()      # model rewritten with Int8 layers
    """

    def __init__(self, model, algo="abs_max", activation_bits=8,
                 weight_bits=8, quantizable_layer_type=("Conv2D", "Linear")):
        from .. import nn

        if algo not in ("abs_max", "avg"):
            raise InvalidArgumentError(
                f"algo must be abs_max or avg, got {algo!r} (KL calibration "
                "is not implemented)")
        if activation_bits != 8 or weight_bits != 8:
            raise InvalidArgumentError("only 8-bit PTQ is implemented")
        self._model = model
        self._algo = algo
        name_map = {"Conv2D": nn.Conv2D, "Linear": nn.Linear}
        self._types = tuple(name_map[t] if isinstance(t, str) else t
                            for t in quantizable_layer_type)
        self._stats = {}   # layer name → list of host batch abs-max floats
        self._pending = {}  # layer name → list of DEVICE abs-max scalars
        self._targets = {n: l for n, l in model.named_sublayers()
                         if isinstance(l, self._types)}
        self._hooks = []
        for name, layer in self._targets.items():
            self._hooks.append(layer.register_forward_pre_hook(
                self._make_hook(name)))

    def _make_hook(self, name):
        def hook(layer, inputs):
            x = inputs[0]
            # accumulate the per-layer abs-max ON DEVICE: a float() here
            # would force one blocking device→host sync per quantizable
            # layer per batch (the calibration host-sync storm); collect()
            # drains the whole pending tree in a single transfer instead
            self._pending.setdefault(name, []).append(
                jnp.max(jnp.abs(jnp.asarray(x, jnp.float32))))
            return None
        return hook

    def collect(self, *batch):
        """Run one calibration batch through the model (eval mode), then
        sync every layer's pending device maxima in ONE transfer."""
        self._model.eval()
        out = self._model(*batch)
        pending, self._pending = self._pending, {}
        if pending:
            host = jax.device_get(pending)
            for name, vals in host.items():
                self._stats.setdefault(name, []).extend(
                    float(v) for v in vals)
        self._emit_calibration()
        return out

    def _emit_calibration(self):
        seen = sum(1 for n in self._targets if self._stats.get(n))
        _notify_quant("ptq", kind="calibration",
                      layers=len(self._targets), calibrated=seen,
                      uncalibrated_layers=len(self._targets) - seen)

    def quantize(self):
        """Freeze observed scales into Int8 layers; returns the model."""
        from .. import nn

        for h in self._hooks:
            h.remove()
        self._emit_calibration()  # final snapshot feeds rule Q801
        for name, layer in self._targets.items():
            obs = self._stats.get(name)
            if not obs:
                raise InvalidArgumentError(
                    f"no calibration data flowed through layer {name!r}")
            act_scale = (max(obs) if self._algo == "abs_max"
                         else sum(obs) / len(obs))
            if isinstance(layer, nn.Conv2D):
                q = Int8Conv2D.from_float(layer, act_scale)
            else:
                q = Int8Linear.from_float(layer, act_scale)
            _replace_sublayer(self._model, name, q)
        return self._model

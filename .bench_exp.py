"""Throwaway perf experiments for the BERT bench (delete before commit)."""
import sys
import time

import numpy as np

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "base"

import jax

if VARIANT == "rbg":
    jax.config.update("jax_default_prng_impl", "rbg")
if VARIANT == "partitionable":
    jax.config.update("jax_threefry_partitionable", True)

import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu import optimizer as popt
from paddle_tpu.models import BertForPretraining, bert_base

BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 256
SEQ = 128
MAX_PRED = 20

paddle.seed(0)
cfg = bert_base()
if VARIANT == "nodrop":
    cfg.dropout = 0.0
net = BertForPretraining(cfg).astype("bfloat16")
if VARIANT == "attndrop0":  # attention-probs dropout off, hidden on
    for lyr in net.bert.layers:
        lyr.attn.drop.p = 0.0
if VARIANT == "hiddendrop0":  # hidden dropouts off, attention on
    net.bert.embeddings.drop.p = 0.0
    for lyr in net.bert.layers:
        lyr.drop.p = 0.0
        lyr.mlp.drop.p = 0.0
if VARIANT == "remat":
    import jax as _jax
    for lyr in net.bert.layers:
        _orig = lyr.forward
        lyr.forward = _jax.checkpoint(_orig, policy=_jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
opt = popt.AdamW(learning_rate=1e-4, weight_decay=0.01, multi_precision=True)
model = paddle.Model(
    net,
    inputs=["input_ids", "token_type_ids", "attention_mask", "masked_positions"],
    labels=["mlm_labels", "nsp_labels"])
model.prepare(optimizer=opt, loss=net.loss)

rng = np.random.RandomState(0)
ids = rng.randint(0, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
token_type = (rng.uniform(size=(BATCH, SEQ)) < 0.5).astype(np.int32)
attn_mask = np.ones((BATCH, SEQ), np.int32)
positions = np.stack([
    np.sort(rng.choice(SEQ, MAX_PRED, replace=False))
    for _ in range(BATCH)]).astype(np.int32)
mlm_labels = np.take_along_axis(ids, positions, axis=1)
nsp_labels = rng.randint(0, 2, size=(BATCH, 1)).astype(np.int32)


def step():
    loss, _ = model._train_batch_device(
        [ids, token_type, attn_mask, positions], [mlm_labels, nsp_labels])
    return loss


for _ in range(3):
    loss = step()
float(loss)
t0 = time.perf_counter()
for _ in range(10):
    loss = step()
final = float(loss)
dt = time.perf_counter() - t0
assert np.isfinite(final)
print(f"VARIANT={VARIANT} BATCH={BATCH}: {BATCH*10/dt:.1f} seq/s "
      f"({dt*100:.1f} ms/step) loss={final:.3f}")

"""setup shim — version stamping from git.

Parity: the reference's cmake/version.cmake writes PADDLE_VERSION and the
git commit into the build (fluid/platform/init.cc prints it); here the
sdist/wheel build stamps ``paddle_tpu/version.py`` with the commit of the
checkout so ``paddle_tpu.version.git_commit`` identifies a build.  All
static metadata lives in pyproject.toml.
"""
import os
import re
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.command.sdist import sdist


def _git_commit() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() if out.returncode == 0 else "unknown"
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def _stamp(path: str):
    """Write the checkout commit into a copied version.py (never the
    in-tree source).  Keeps an existing non-unknown stamp: a wheel built
    from an sdist has no .git but the sdist was already stamped."""
    if not os.path.exists(path):
        return
    commit = _git_commit()
    with open(path) as f:
        src = f.read()
    if commit == "unknown" and 'git_commit = "unknown"' not in src:
        return  # already carries a real commit from the sdist stamp
    src = re.sub(r"^git_commit = .*$", f'git_commit = "{commit}"',
                 src, flags=re.M)
    with open(path, "w") as f:
        f.write(src)


class BuildPyStampVersion(build_py):
    def run(self):
        super().run()
        _stamp(os.path.join(self.build_lib, "paddle_tpu", "version.py"))


class SdistStampVersion(sdist):
    def make_release_tree(self, base_dir, files):
        super().make_release_tree(base_dir, files)
        # the release tree hard-links by default — copy before writing so
        # the stamp never touches the working tree's version.py
        target = os.path.join(base_dir, "paddle_tpu", "version.py")
        if os.path.exists(target):
            os.unlink(target)
            import shutil

            shutil.copyfile(os.path.join("paddle_tpu", "version.py"), target)
        _stamp(target)


setup(cmdclass={"build_py": BuildPyStampVersion,
                "sdist": SdistStampVersion})

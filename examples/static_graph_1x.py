"""The classic 1.x static-graph flow, end to end.

Build a Program with fluid.data + op-builders, bind an optimizer with
minimize(), run startup, then drive the Executor — exactly the
fit_a_line / recognize_digits book recipe.  Under the hood the recorded
graph compiles into ONE jitted XLA computation per feed signature
(static/graph.py); there is no op-by-op interpreter.

    python examples/static_graph_1x.py
"""
import numpy as np

import paddle_tpu.fluid as fluid


def main():
    main_prog, startup_prog = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup_prog):
        img = fluid.data("img", [-1, 1, 28, 28])
        label = fluid.data("label", [-1, 1], dtype="int64")
        conv = fluid.layers.conv2d(img, num_filters=8, filter_size=5,
                                   act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
        bn = fluid.layers.batch_norm(pool)
        pred = fluid.layers.fc(bn, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_prog)

    rng = np.random.RandomState(0)
    protos = rng.rand(10, 1, 28, 28).astype(np.float32)
    for step in range(60):
        y = rng.randint(0, 10, 64)
        x = protos[y] + 0.1 * rng.randn(64, 1, 28, 28).astype(np.float32)
        loss_v, = exe.run(main_prog,
                          feed={"img": x,
                                "label": y[:, None].astype(np.int64)},
                          fetch_list=[loss])
        if step % 10 == 0:
            print(f"step {step:3d}  loss {float(loss_v):.4f}")

    print("final loss:", float(loss_v))


if __name__ == "__main__":
    main()

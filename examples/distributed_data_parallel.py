"""Data parallelism + ZeRO over whatever devices are visible.

On a multi-chip host this shards the batch over all chips and the
optimizer state over the `sharding` axis; on one chip it degrades to
plain training.  For CPU experimentation:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed_data_parallel.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet


def main():
    strategy = fleet.DistributedStrategy(sharding=True)  # DP + ZeRO slots
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)

    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 1))
    opt = fleet.distributed_optimizer(
        popt.AdamW(learning_rate=1e-3, multi_precision=True))
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=opt, loss=nn.MSELoss())

    import jax

    n = max(len(jax.devices()), 1)
    rng = np.random.RandomState(0)
    x = rng.randn(16 * n, 32).astype(np.float32)
    y = rng.randn(16 * n, 1).astype(np.float32)
    for step in range(5):
        loss, _ = model.train_batch([x], [y])
        print(f"step {step}: loss={loss:.5f} on {n} device(s)")


if __name__ == "__main__":
    main()

"""CTR training at recommender scale: SelectedRows sparse gradients.

A Wide&Deep model whose embedding tables use ``sparse=True`` — the
backward produces (ids, rows) COO gradients and ``Adam(lazy_mode=True)``
updates ONLY the rows a minibatch touched, so the per-step cost is
independent of vocabulary size (framework/selected_rows.py; the
reference needed a parameter-server cluster for this).

For tables beyond HBM, swap in incubate.HostEmbeddingTable (pull rows →
train on device → push row grads; see its docstring).

    python examples/sparse_ctr_training.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer as popt
from paddle_tpu.models import WideDeep


def main():
    VOCAB = 1_000_000  # a million-id hashed feature space, one host
    paddle.seed(0)
    net = WideDeep(num_fields=8, vocab_size=VOCAB, embed_dim=32,
                   dense_dim=8, hidden_sizes=(64, 32), sparse=True)
    model = paddle.Model(net, inputs=["sparse", "dense"], labels=["label"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-3, lazy_mode=True),
                  loss=net.loss)

    rng = np.random.RandomState(0)
    for step in range(20):
        ids = rng.randint(0, VOCAB, size=(256, 8)).astype(np.int32)
        dense = rng.randn(256, 8).astype(np.float32)
        click = (rng.uniform(size=(256, 1)) < 0.3).astype(np.float32)
        loss, _ = model.train_batch([ids, dense], [click])
        if step % 5 == 0:
            print(f"step {step:2d}  loss {float(np.asarray(loss)):.4f}")

    w = net.embedding.weight.value
    print(f"table {w.shape} — only ~{20 * 256 * 8:,} of {VOCAB:,} rows "
          f"were ever touched; untouched rows never moved")


if __name__ == "__main__":
    main()

"""Post-training quantization: calibrate, freeze to int8 layers (real
int8 matmuls with int32 accumulation on the MXU), export, reload."""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import Config, create_predictor, save_inference_model
from paddle_tpu.slim import PostTrainingQuantization
from paddle_tpu.static import InputSpec
from paddle_tpu.vision.models import LeNet


def main():
    paddle.seed(0)
    net = LeNet()
    net.eval()
    rng = np.random.RandomState(0)
    calib = [rng.uniform(0, 1, (16, 1, 28, 28)).astype(np.float32)
             for _ in range(4)]
    ref = np.asarray(net(paddle.to_tensor(calib[0])))

    ptq = PostTrainingQuantization(net)
    for batch in calib:
        ptq.collect(paddle.to_tensor(batch))
    qnet = ptq.quantize()
    out = np.asarray(qnet(paddle.to_tensor(calib[0])))
    err = np.abs(out - ref).max() / np.abs(ref).max()
    print(f"int8 vs float relative error: {err:.4f}")

    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "lenet_int8")
        save_inference_model(prefix, qnet,
                             [InputSpec([None, 1, 28, 28], "float32")],
                             platforms=("cpu",))
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        out2 = pred.run([calib[0]])[0]
        print("export/reload max deviation:",
              float(np.abs(np.asarray(out2) - out).max()))


if __name__ == "__main__":
    main()

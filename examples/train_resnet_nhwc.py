"""ResNet-18 training the TPU-first way: NHWC layout, bf16 params with
f32 master weights, and several optimizer steps per host dispatch."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.vision.models import resnet18


def main():
    paddle.seed(0)
    net = resnet18(num_classes=10, data_format="NHWC").astype("bfloat16")
    opt = popt.Momentum(learning_rate=0.05, momentum=0.9,
                        multi_precision=True, weight_decay=1e-4)
    model = paddle.Model(net, inputs=["image"], labels=["label"])
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                  steps_per_execution=4)

    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (256, 64, 64, 3)).astype(np.float32)
    y = rng.randint(0, 10, (256, 1)).astype(np.int64)
    model.fit(paddle.io.TensorDataset([x, y]), batch_size=32, epochs=3,
              verbose=1)


if __name__ == "__main__":
    main()

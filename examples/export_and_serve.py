"""Export a model to the portable StableHLO format and serve it twice:
from Python (Predictor) and from a real C program linked against the
C ABI (paddle_tpu_c.h)."""
import os
import subprocess
import sys
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import Config, create_predictor, save_inference_model
from paddle_tpu.native import c_api_path
from paddle_tpu.static import InputSpec

C_PROGRAM = r"""
#include <stdint.h>
#include <stdio.h>
#include "paddle_tpu_c.h"
int main(int argc, char** argv) {
    void* p = pd_predictor_create(argv[1], argv[2]);
    if (!p) { fprintf(stderr, "%s\n", pd_last_error()); return 1; }
    float in[8];
    for (int i = 0; i < 8; i++) in[i] = 0.25f * i;
    const float* ins[1] = {in};
    int64_t shape[2] = {1, 8};
    const int64_t* shapes[1] = {shape};
    int nd[1] = {2};
    float* out; int64_t oshape[4]; int ond;
    if (pd_predictor_run(p, ins, shapes, nd, 1, &out, oshape, 4, &ond)) {
        fprintf(stderr, "%s\n", pd_last_error()); return 2;
    }
    printf("C output[0] = %f\n", out[0]);
    pd_free(out);
    pd_predictor_destroy(p);
    return 0;
}
"""


def main():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "model")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))

        # Python serving
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        x = (0.25 * np.arange(8, dtype=np.float32)).reshape(1, 8)
        out = pred.run([x])[0]
        print("Python output[0] =", float(np.asarray(out)[0, 0]))

        # C serving (same artifacts, same runtime)
        lib = c_api_path()
        csrc = os.path.join(td, "main.c")
        open(csrc, "w").write(C_PROGRAM)
        exe = os.path.join(td, "demo")
        hdr = os.path.dirname(lib)
        from paddle_tpu import native

        subprocess.run(["gcc", csrc, lib,
                        f"-I{os.path.dirname(native.__file__)}",
                        "-o", exe, f"-Wl,-rpath,{hdr}"], check=True)
        env = dict(os.environ, PADDLE_TPU_C_PLATFORM="cpu")
        subprocess.run([exe, prefix + ".pdmodel", prefix + ".pdiparams"],
                       check=True, env=env)


if __name__ == "__main__":
    main()

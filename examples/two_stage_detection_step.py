"""The Faster-RCNN training chain end to end on synthetic boxes:
anchors -> RPN losses (rpn_target_assign) -> proposals -> RCNN sampling
(generate_proposal_labels) -> head losses, all inside one jitted step.
See tests/test_detection_targets.py::TestTwoStageEndToEnd for the
convergence-asserted version of this wiring."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.functional.detection import anchor_generator, generate_proposals


def main():
    rng = np.random.RandomState(0)
    N, C, Hf, Wf, IM, G = 2, 8, 8, 8, 64, 2
    gt = np.zeros((N, G, 4), np.float32)
    gt[..., :2] = rng.uniform(4, 28, (N, G, 2))
    gt[..., 2:] = np.clip(gt[..., :2] + rng.uniform(16, 30, (N, G, 2)), 0, 63)
    gt_cls = rng.randint(1, 3, (N, G)).astype(np.int32)
    crowd = np.zeros((N, G), np.int32)
    im_info = np.array([[IM, IM, 1.0]] * N, np.float32)

    anchors, variances = anchor_generator(
        np.zeros((N, C, Hf, Wf), np.float32),
        anchor_sizes=[16.0, 24.0, 32.0], aspect_ratios=[1.0],
        stride=[8.0, 8.0])
    anchors_flat = jnp.asarray(anchors).reshape(-1, 4)
    M = anchors_flat.shape[0]

    bbox_pred = jnp.asarray(rng.randn(N, M, 4).astype(np.float32) * 0.1)
    cls_logits = jnp.asarray(rng.randn(N, M, 1).astype(np.float32))

    # stage 1: RPN targets
    scores, loc, lbl, tgt, inw = F.rpn_target_assign(
        bbox_pred, cls_logits, anchors_flat, None, gt, crowd, im_info,
        rpn_batch_size_per_im=32, use_random=True,
        key=jax.random.PRNGKey(0))
    print("RPN: sampled", int((np.asarray(lbl) >= 0).sum()), "anchors,",
          int((np.asarray(lbl) == 1).sum()), "positive")

    # proposals
    rois, probs, counts = generate_proposals(
        jax.nn.sigmoid(cls_logits).reshape(N, Hf, Wf, 3).transpose(0, 3, 1, 2),
        bbox_pred.reshape(N, Hf, Wf, 12).transpose(0, 3, 1, 2),
        im_info, anchors, variances, pre_nms_top_n=64, post_nms_top_n=16,
        return_rois_num=True)
    print("proposals per image:", [int(c) for c in np.asarray(counts)])

    # stage 2: RCNN sampling
    s_rois, labels, btgt, biw, bow = F.generate_proposal_labels(
        rois, gt_cls, crowd, gt, im_info, rois_num=counts,
        batch_size_per_im=16, fg_thresh=0.5, class_nums=3,
        use_random=True, key=jax.random.PRNGKey(1))
    lbls = np.asarray(labels).reshape(-1)
    print("RCNN minibatch:", int((lbls >= 0).sum()), "rois,",
          int((lbls > 0).sum()), "foreground")


if __name__ == "__main__":
    main()

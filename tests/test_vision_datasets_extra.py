"""Flowers / VOC2012 datasets, folder loaders, metric.accuracy.

Reference capability: vision/datasets/flowers.py:43, voc2012.py:41,
folder.py loaders, metric/metrics.py:742 — fixtures synthesize the real
archive layouts (tgz of jpgs + .mat labels; VOCdevkit tar).
"""
import io
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.datasets import (
    VOC2012,
    Flowers,
    cv2_loader,
    default_loader,
    pil_loader,
)


def _jpg_bytes(color, size=(8, 8)):
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", size, color).save(buf, format="JPEG")
    return buf.getvalue()


def _png_bytes(value, size=(8, 8)):
    from PIL import Image

    buf = io.BytesIO()
    Image.new("L", size, value).save(buf, format="PNG")
    return buf.getvalue()


def _add(tar, name, blob):
    info = tarfile.TarInfo(name)
    info.size = len(blob)
    tar.addfile(info, io.BytesIO(blob))


class TestFlowers:
    @pytest.fixture
    def files(self, tmp_path):
        import scipy.io as scio

        data = os.path.join(tmp_path, "102flowers.tgz")
        with tarfile.open(data, "w:gz") as t:
            for i in range(1, 7):
                _add(t, "jpg/image_%05d.jpg" % i,
                     _jpg_bytes((i * 30 % 255, 0, 0)))
        labels = os.path.join(tmp_path, "imagelabels.mat")
        scio.savemat(labels, {"labels": np.array([[1, 2, 3, 1, 2, 3]])})
        setid = os.path.join(tmp_path, "setid.mat")
        scio.savemat(setid, {"trnid": np.array([[1, 2, 3, 4]]),
                             "valid": np.array([[5]]),
                             "tstid": np.array([[6]])})
        return data, labels, setid

    def test_splits_and_samples(self, files):
        data, labels, setid = files
        train = Flowers(data_file=data, label_file=labels, setid_file=setid,
                        mode="train")
        assert len(train) == 4
        img, y = train[0]
        assert img.shape == (8, 8, 3) and int(y) == 0  # label 1 → 0-based
        test = Flowers(data_file=data, label_file=labels, setid_file=setid,
                       mode="test")
        assert len(test) == 1 and int(test[0][1]) == 2

    def test_transform_and_missing(self, files, tmp_path):
        data, labels, setid = files
        ds = Flowers(data_file=data, label_file=labels, setid_file=setid,
                     mode="valid", transform=lambda im: im.mean())
        assert np.isscalar(ds[0][0]) or ds[0][0].shape == ()
        with pytest.raises(FileNotFoundError, match="egress"):
            Flowers(data_file=os.path.join(tmp_path, "nope.tgz"),
                    label_file=labels, setid_file=setid)
        with pytest.raises(ValueError, match="backend"):
            Flowers(data_file=data, label_file=labels, setid_file=setid,
                    backend="CV2")

    def test_pickles_for_dataloader_workers(self, files):
        """Tar handles open lazily per process — the dataset must pickle
        (DataLoader num_workers>0 ships it to spawn workers)."""
        import pickle

        data, labels, setid = files
        ds = Flowers(data_file=data, label_file=labels, setid_file=setid,
                     mode="train")
        _ = ds[0]  # force the tar open in THIS process
        clone = pickle.loads(pickle.dumps(ds))
        img, y = clone[0]
        assert img.shape == (8, 8, 3)

        from paddle_tpu.io import DataLoader

        loader = DataLoader(ds, batch_size=2, num_workers=2,
                            drop_last=True)
        batch = next(iter(loader))
        assert batch[0].shape[0] == 2


class TestVOC2012:
    @pytest.fixture
    def archive(self, tmp_path):
        p = os.path.join(tmp_path, "VOCtrainval_11-May-2012.tar")
        names = ["2007_000001", "2007_000002"]
        with tarfile.open(p, "w") as t:
            _add(t, "VOCdevkit/VOC2012/ImageSets/Segmentation/trainval.txt",
                 "\n".join(names).encode())
            _add(t, "VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt",
                 names[0].encode())
            _add(t, "VOCdevkit/VOC2012/ImageSets/Segmentation/val.txt",
                 names[1].encode())
            for n in names:
                _add(t, f"VOCdevkit/VOC2012/JPEGImages/{n}.jpg",
                     _jpg_bytes((0, 128, 0)))
                _add(t, f"VOCdevkit/VOC2012/SegmentationClass/{n}.png",
                     _png_bytes(7))
        return p

    def test_modes_and_samples(self, archive):
        ds = VOC2012(data_file=archive, mode="train")
        assert len(ds) == 2
        img, mask = ds[0]
        assert img.shape == (8, 8, 3)
        assert mask.shape == (8, 8) and (np.asarray(mask) == 7).all()
        assert len(VOC2012(data_file=archive, mode="test")) == 1
        assert len(VOC2012(data_file=archive, mode="valid")) == 1


class TestLoaders:
    def test_three_loaders(self, tmp_path):
        p = os.path.join(tmp_path, "x.jpg")
        with open(p, "wb") as f:
            f.write(_jpg_bytes((10, 120, 230), size=(4, 4)))
        pil = pil_loader(p)
        assert hasattr(pil, "convert")  # a PIL image
        arr = cv2_loader(p)
        assert arr.shape == (4, 4, 3)
        # cv2.imread convention: BGR — channel-reversed vs the PIL read
        np.testing.assert_array_equal(arr, np.asarray(pil)[..., ::-1])
        np.testing.assert_array_equal(default_loader(p), np.asarray(pil))


class TestAccuracyFunctional:
    def test_topk(self):
        logits = np.array([[0.1, 0.9, 0.0],
                           [0.8, 0.1, 0.1],
                           [0.3, 0.3, 0.4]], np.float32)
        y = np.array([[1], [2], [2]])
        assert float(paddle.metric.accuracy(logits, y, k=1)) == \
            pytest.approx(2 / 3)
        assert float(paddle.metric.accuracy(logits, y, k=2)) == \
            pytest.approx(2 / 3)
        assert float(paddle.metric.accuracy(logits, y, k=3)) == 1.0

"""Worker liveness: HeartBeatMonitor + watch() hang detection.

Reference capability: heart_beat_monitor.h:51 (chief-side trainer beat
tracking) — here transport-agnostic monitor + mtime-file transport wired
into the launch watchdog.
"""
import os
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.heartbeat import (
    ENV_FILE,
    FileHeartbeat,
    HeartBeatMonitor,
)
from paddle_tpu.distributed.parallel import watch


class TestHeartBeatMonitor:
    def test_stalled_worker_detected_within_deadline(self):
        lost = []
        mon = HeartBeatMonitor(workers=3, timeout=0.3, interval=0.05,
                               on_lost=lambda i, age: lost.append(i))
        mon.start()
        try:
            t0 = time.monotonic()
            # workers 0 and 2 beat; worker 1 stalls
            while time.monotonic() - t0 < 0.8:
                mon.update(0)
                mon.update(2)
                time.sleep(0.05)
            assert mon.lost_workers() == [1]
            assert lost == [1]
        finally:
            mon.stop()

    def test_lost_fires_once_and_rearms(self):
        lost = []
        mon = HeartBeatMonitor(workers=1, timeout=0.2, interval=0.05,
                               on_lost=lambda i, age: lost.append(i))
        mon.start()
        try:
            time.sleep(0.5)            # outage 1
            assert lost == [0]
            mon.update(0)              # recovery re-arms
            assert mon.lost_workers() == []
            time.sleep(0.5)            # outage 2
            assert lost == [0, 0]
        finally:
            mon.stop()

    def test_stop_is_prompt_even_with_long_interval(self):
        # stop() must interrupt the sweep pause (Event.wait), not ride
        # out a full time.sleep(interval)
        mon = HeartBeatMonitor(workers=1, timeout=60, interval=5.0)
        mon.start()
        t0 = time.monotonic()
        mon.stop()
        assert time.monotonic() - t0 < 1.0

    def test_no_on_lost_after_stop(self):
        # a sweep racing stop() may latch the lost state, but the
        # callback must not fire after shutdown
        fired = []

        def on_lost(i, age):
            fired.append(i)

        mon = HeartBeatMonitor(workers=1, timeout=0.05, interval=0.02,
                               on_lost=on_lost)
        mon.start()
        mon.stop()  # before the worker ever went stale-and-swept
        fired_at_stop = list(fired)
        time.sleep(0.3)  # were the thread still sweeping, it would fire
        assert fired == fired_at_stop
        assert mon._thread is None

    def test_validation(self):
        with pytest.raises(Exception):
            HeartBeatMonitor(workers=0)
        with pytest.raises(Exception):
            HeartBeatMonitor(workers=2, timeout=-1)
        mon = HeartBeatMonitor(workers=2)
        with pytest.raises(Exception):
            mon.update(5)


class TestFileHeartbeat:
    def test_beat_updates_age(self, tmp_path):
        hb = FileHeartbeat(str(tmp_path / "beat"))
        assert hb.age() < 5
        time.sleep(0.05)
        a1 = hb.age()
        hb.beat()
        assert hb.age() <= a1

    def test_missing_file_is_infinitely_old(self, tmp_path):
        hb = FileHeartbeat(str(tmp_path / "b"))
        os.unlink(hb.path)
        assert hb.age() == float("inf")


class TestMaybeBeat:
    def _reset(self):
        from paddle_tpu.distributed import heartbeat as hb

        hb._last_beat = 0.0
        hb._writer = None
        return hb

    def test_concurrent_callers_are_safe(self, tmp_path, monkeypatch):
        # the serving router's health sweep and the training loop both
        # call maybe_beat(); concurrent callers must neither crash nor
        # corrupt the writer — one thread beats, the others skip
        import threading

        hb = self._reset()
        path = str(tmp_path / "beat")
        monkeypatch.setenv(ENV_FILE, path)
        errors = []
        start = threading.Barrier(8)

        def hammer():
            try:
                start.wait(5)
                for _ in range(200):
                    hb.maybe_beat(min_interval=0.0)
            except Exception as e:  # noqa: BLE001 — the assertion
                errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert errors == []
        assert os.path.exists(path)
        assert hb._writer is not None and hb._writer.path == path
        self._reset()

    def test_throttles_to_min_interval(self, tmp_path, monkeypatch):
        hb = self._reset()
        path = str(tmp_path / "beat")
        monkeypatch.setenv(ENV_FILE, path)
        hb.maybe_beat(min_interval=3600.0)
        size0 = os.stat(path).st_size
        for _ in range(50):
            hb.maybe_beat(min_interval=3600.0)  # all inside the interval
        assert os.stat(path).st_size == size0
        self._reset()

    def test_noop_without_env(self, monkeypatch):
        hb = self._reset()
        monkeypatch.delenv(ENV_FILE, raising=False)
        hb.maybe_beat(min_interval=0.0)  # must not raise or create files
        assert hb._writer is None


class TestWatchHangDetection:
    def _script(self, tmp_path, body):
        p = tmp_path / "trainer.py"
        p.write_text(textwrap.dedent(body))
        return str(p)

    def test_hung_trainer_killed_and_restarted(self, tmp_path):
        # the trainer beats twice then hangs forever; the watchdog must
        # kill it within the deadline and burn one restart, after which
        # the marker file lets the second attempt exit cleanly
        marker = tmp_path / "second_run"
        script = self._script(tmp_path, f"""
            import os, sys, time
            marker = {str(marker)!r}
            hb = os.environ["{ENV_FILE}"]
            if os.path.exists(marker):
                sys.exit(0)           # restarted run: succeed
            open(marker, "w").close()
            for _ in range(2):
                with open(hb, "a"): os.utime(hb, None)
                time.sleep(0.05)
            time.sleep(3600)          # hang (no more beats)
        """)
        t0 = time.monotonic()
        rc = watch([sys.executable, script], max_restarts=1, _sleep=0.05,
                   hang_timeout=2.0, startup_grace=30.0)
        dt = time.monotonic() - t0
        assert rc == 0
        assert dt < 30, f"hang not detected within deadline ({dt:.1f}s)"

    def test_healthy_trainer_not_killed(self, tmp_path):
        script = self._script(tmp_path, f"""
            import os, time
            hb = os.environ["{ENV_FILE}"]
            for _ in range(10):
                with open(hb, "a"): os.utime(hb, None)
                time.sleep(0.05)
        """)
        rc = watch([sys.executable, script], max_restarts=0,
                   hang_timeout=2.0)
        assert rc == 0

    def test_no_timeout_keeps_old_behavior(self, tmp_path):
        script = self._script(tmp_path, "import sys; sys.exit(0)")
        assert watch([sys.executable, script], max_restarts=0) == 0

    def test_too_small_timeout_rejected(self, tmp_path):
        script = self._script(tmp_path, "import sys; sys.exit(0)")
        for bad in (0, -1, 0.5, 1.9):
            with pytest.raises(Exception, match="hang_timeout"):
                watch([sys.executable, script], hang_timeout=bad)

    def test_beat_survives_pruned_tempdir(self, tmp_path):
        import shutil

        d = tmp_path / "sub"
        hb = FileHeartbeat(str(d / "beat"))
        shutil.rmtree(d)
        hb.beat()  # must not raise; recreates the directory
        assert hb.age() < 5


class TestUpdateStamp:
    """Skew-tolerant beats: a remote stamp is opaque — compared only for
    equality against the same worker's prior stamp, timed on the LOCAL
    monotonic clock.  Cross-host clock skew cannot create or hide beats."""

    def _mon(self, **kw):
        kw.setdefault("workers", 2)
        kw.setdefault("timeout", 0.3)
        kw.setdefault("interval", 0.05)
        kw.setdefault("grace", 0.3)
        return HeartBeatMonitor(**kw)

    def test_changed_stamp_counts_as_beat(self):
        mon = self._mon()
        mon.start()
        try:
            t0 = time.monotonic()
            seq = 0
            while time.monotonic() - t0 < 0.8:
                mon.update(0)
                mon.update_stamp(1, (123456.0, seq))  # size changes
                seq += 1
                time.sleep(0.05)
            assert mon.lost_workers() == []
        finally:
            mon.stop()

    def test_frozen_stamp_goes_lost(self):
        # the file still EXISTS with a perfectly plausible mtime — but the
        # stamp never changes, so the worker is dead
        mon = self._mon()
        mon.start()
        try:
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.8:
                mon.update(0)
                mon.update_stamp(1, (987654.0, 64))  # same stamp forever
                time.sleep(0.05)
            assert mon.lost_workers() == [1]
        finally:
            mon.stop()

    def test_skewed_clocks_are_irrelevant(self):
        # remote mtimes jump far into the past and the future; only the
        # CHANGE matters, so the worker stays live either way
        mon = self._mon()
        mon.start()
        try:
            stamps = [(-1e9, 1), (4e9, 2), (0.0, 3), (-5.0, 4),
                      (4e9, 5), (1.0, 6), (2.0, 7), (3.0, 8),
                      (9e9, 9), (-9e9, 10), (1.5, 11), (2.5, 12)]
            for s in stamps:
                mon.update(0)
                mon.update_stamp(1, s)
                time.sleep(0.05)
            assert mon.lost_workers() == []
        finally:
            mon.stop()

    def test_new_stamp_unlatches_lost(self):
        mon = self._mon()
        mon.start()
        try:
            mon.update_stamp(1, (1.0, 1))
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.8:
                mon.update(0)
                time.sleep(0.05)
            assert 1 in mon.lost_workers()
            mon.update_stamp(1, (1.0, 2))  # host came back
            assert 1 not in mon.lost_workers()
        finally:
            mon.stop()

    def test_out_of_range_worker_rejected(self):
        mon = self._mon()
        with pytest.raises(Exception, match="worker_id"):
            mon.update_stamp(5, (1.0, 1))


class TestHeartbeatWriteFailures:
    def test_unwritable_path_suppressed_but_counted(self, tmp_path):
        from paddle_tpu.framework import monitor

        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        # parent "directory" is a regular file: _write fails, the mkdir
        # recovery fails too — beat() must swallow it and bump the stat
        hb = FileHeartbeat(str(blocker / "beat"), touch=False)
        before = monitor.get_stat("heartbeat_write_failures")
        hb.beat()  # must not raise
        assert monitor.get_stat("heartbeat_write_failures") == before + 1
        hb.beat()
        assert monitor.get_stat("heartbeat_write_failures") == before + 2


class TestPeerHeartbeatMonitor:
    def test_beating_peer_live_stalled_peer_lost(self, tmp_path):
        from paddle_tpu.distributed.heartbeat import (PeerHeartbeatMonitor,
                                                      gang_beat_path)

        hb1 = FileHeartbeat(gang_beat_path(str(tmp_path), 1))
        # rank 2 never writes a beat file at all
        mon = PeerHeartbeatMonitor(str(tmp_path), world=3, self_rank=0,
                                   timeout=0.4, interval=0.05, grace=0.4)
        mon.start()
        try:
            t0 = time.monotonic()
            while time.monotonic() - t0 < 1.2:
                hb1.beat()
                time.sleep(0.05)
            assert mon.lost_workers() == [2]  # never self_rank
            # now rank 1 stalls too
            time.sleep(0.9)
            assert mon.lost_workers() == [1, 2]
            hb1.beat()  # rank 1 recovers
            time.sleep(0.3)
            assert mon.lost_workers() == [2]
        finally:
            mon.stop()

    def test_rearm_clears_lost_and_reapplies_grace(self, tmp_path):
        from paddle_tpu.distributed.heartbeat import PeerHeartbeatMonitor

        mon = PeerHeartbeatMonitor(str(tmp_path), world=2, self_rank=0,
                                   timeout=0.3, interval=0.05, grace=0.3)
        mon.start()
        try:
            time.sleep(0.8)
            assert mon.lost_workers() == [1]
            mon.rearm(grace=5.0)  # gang relaunch window
            assert mon.lost_workers() == []
            time.sleep(0.5)  # inside the new grace: still not lost
            assert mon.lost_workers() == []
        finally:
            mon.stop()

    def test_self_rank_validated(self, tmp_path):
        from paddle_tpu.distributed.heartbeat import PeerHeartbeatMonitor

        with pytest.raises(Exception, match="self_rank"):
            PeerHeartbeatMonitor(str(tmp_path), world=2, self_rank=2)

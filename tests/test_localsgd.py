"""LocalSGD strategy — k local steps per replica, periodic model averaging.

Reference capability: transpiler/collective.py:270 (LocalSGD snapshot +
allreduce rewrite) / fleet/meta_optimizers/localsgd_optimizer.py.  Here the
assertions are trajectory-level: k=1 LocalSGD must equal plain DP for plain
SGD (averaging post-step params == stepping on averaged grads), replicas
must actually diverge between syncs, and the synced model must converge.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.framework.errors import InvalidArgumentError


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _data(n=64, d=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    y = x @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
    return x, y


def _net(d=8):
    paddle.seed(0)
    return nn.Sequential(nn.Linear(d, 16), nn.ReLU(), nn.Linear(16, 1))


def _fit(model, x, y, steps):
    losses = []
    for i in range(steps):
        loss, _ = model.train_batch([x], [y])
        losses.append(loss)
    return losses


def _prepare_localsgd(k_steps, begin_step=1, opt_factory=None):
    strat = fleet.DistributedStrategy(
        localsgd=True,
        localsgd_configs={"k_steps": k_steps, "begin_step": begin_step})
    fleet.init(is_collective=True, strategy=strat)
    net = _net()
    opt = fleet.distributed_optimizer(
        (opt_factory or (lambda: popt.SGD(learning_rate=0.1)))())
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    return model, net


class TestLocalSGDParity:
    def test_k1_matches_plain_dp_sgd(self):
        """Averaging post-step params == stepping on averaged grads for
        plain SGD, so k_steps=1 LocalSGD must retrace plain DP exactly."""
        x, y = _data()

        strat = fleet.DistributedStrategy()
        fleet.init(is_collective=True, strategy=strat)
        net_dp = _net()
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        m_dp = paddle.Model(net_dp, inputs=["x"], labels=["y"])
        m_dp.prepare(optimizer=opt, loss=nn.MSELoss())
        ref = _fit(m_dp, x, y, 6)
        fleet._initialized = False

        m_ls, _ = _prepare_localsgd(k_steps=1)
        got = _fit(m_ls, x, y, 6)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_replicas_diverge_then_sync(self):
        x, y = _data()
        m, net = _prepare_localsgd(k_steps=4)
        p0 = {n: np.asarray(p.value).copy()
              for n, p in net.named_parameters()}
        m.train_batch([x], [y])  # step 1: local only
        # Model-visible params are the last synced values — unchanged
        for n, p in net.named_parameters():
            np.testing.assert_allclose(np.asarray(p.value), p0[n])
        # but each replica advanced on a different rng/shard: locals differ
        local = m._opt_state["local"]["params"]
        some = next(iter(local.values()))
        stacked = np.asarray(some)
        assert stacked.shape[0] == 8
        # every replica moved off the init
        leaf0 = p0[next(iter(local.keys()))]
        assert not np.allclose(stacked[0], leaf0)
        # replicas saw different batch shards → different trajectories
        assert not np.allclose(stacked[0], stacked[1])

        m.train_batch([x], [y])
        m.train_batch([x], [y])
        m.train_batch([x], [y])  # step 4: sync
        local = m._opt_state["local"]["params"]
        for n, p in net.named_parameters():
            vis = np.asarray(p.value)
            assert not np.allclose(vis, p0[n]), "sync must update the model"
            stacked = np.asarray(local[n])
            for r in range(8):  # replicas reset to the average
                np.testing.assert_allclose(stacked[r], vis, rtol=1e-6)

    def test_begin_step_syncs_every_step_before(self):
        x, y = _data()
        m, net = _prepare_localsgd(k_steps=4, begin_step=3)
        p0 = {n: np.asarray(p.value).copy()
              for n, p in net.named_parameters()}
        m.train_batch([x], [y])  # t=1 < begin_step → sync
        changed = any(
            not np.allclose(np.asarray(p.value), p0[n])
            for n, p in net.named_parameters())
        assert changed, "before begin_step LocalSGD behaves like DP"

    def test_converges(self):
        x, y = _data()
        m, _ = _prepare_localsgd(
            k_steps=2, opt_factory=lambda: popt.Adam(learning_rate=1e-2))
        losses = _fit(m, x, y, 40)
        assert losses[-1] < losses[0] * 0.2, losses

    def test_rejects_hybrid_mesh(self):
        strat = fleet.DistributedStrategy(localsgd=True, mp_degree=2)
        fleet.init(is_collective=True, strategy=strat)
        net = _net()
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(InvalidArgumentError, match="localsgd"):
            m.prepare(optimizer=opt, loss=nn.MSELoss())

    def test_load_resets_sync_schedule(self, tmp_path):
        """Model.load must re-derive the step mirror from the restored
        count, or the averaging cadence drifts after restore-and-continue."""
        import os

        x, y = _data()
        m, net = _prepare_localsgd(k_steps=4)
        for _ in range(4):
            m.train_batch([x], [y])  # t=4: sync
        ck = os.path.join(tmp_path, "ck")
        m.save(ck)
        for _ in range(6):
            m.train_batch([x], [y])  # t=10
        m.load(ck)
        assert m._plan._t is None  # mirror invalidated
        m.train_batch([x], [y])    # resumes at t=5 (local, no sync)
        assert m._plan._t == 5
        assert int(np.asarray(m._opt_state["count"])) == 5

    def test_eager_step_and_distributed_model_guarded(self):
        strat = fleet.DistributedStrategy(localsgd=True)
        fleet.init(is_collective=True, strategy=strat)
        net = _net()
        opt = fleet.distributed_optimizer(
            popt.SGD(learning_rate=0.1, parameters=net.parameters()))
        with pytest.raises(InvalidArgumentError, match="localsgd"):
            opt.step({n: jnp.zeros_like(p.value)
                      for n, p in net.named_parameters()})
        with pytest.raises(InvalidArgumentError, match="localsgd"):
            fleet.distributed_model(net)

    def test_rejects_gradient_merge_combo(self):
        strat = fleet.DistributedStrategy(
            localsgd=True, gradient_merge=True,
            gradient_merge_configs={"k_steps": 2})
        fleet.init(is_collective=True, strategy=strat)
        with pytest.raises(InvalidArgumentError, match="gradient_merge"):
            fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))


class TestAdaptiveLocalSGD:
    """strategy.adaptive_localsgd — step-adaptive sync cadence (ref:
    fleet/meta_optimizers/localsgd_optimizer.py:194): k follows
    ceil(sqrt(lr0*loss/(lr*loss0)*init_k)) clamped to [1, 16]."""

    def _train(self, steps=8, init_k=2):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            adaptive_localsgd=True,
            adaptive_localsgd_configs={"init_k_steps": init_k,
                                       "begin_step": 1})
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.05))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=opt, loss=nn.MSELoss())
        rng = np.random.RandomState(1)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 1).astype(np.float32)
        losses, ks = [], []
        for _ in range(steps):
            loss, _ = model.train_batch([x], [y])
            losses.append(float(np.asarray(loss)))
            ks.append(model._plan.k_steps)
        return model, np.asarray(losses), ks

    def test_descends_and_k_adapts_within_bounds(self):
        model, losses, ks = self._train()
        assert losses[-1] < losses[0]
        assert all(1 <= k <= 16 for k in ks)
        # loss decreasing => ratio < 1 => adapted k can only shrink from
        # init... with init_k=2 and falling loss, k must reach 1
        assert ks[-1] == 1, ks

    def test_replicas_stay_stacked_per_device(self):
        model, _, _ = self._train(steps=3)
        local = next(iter(model._plan and
                          model._opt_state["local"]["params"].values()))
        import jax

        assert local.shape[0] == len(jax.devices())

    def test_loss0_recorded_at_step_one(self):
        model, losses, _ = self._train(steps=2)
        assert abs(model._plan._loss0 - losses[0]) < 1e-6

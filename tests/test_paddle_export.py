"""Reference-format checkpoint WRITER (framework/paddle_export.py).

Parity: the reference's binary save side — fluid/io.py:168 save_vars,
:598 save_params/save_persistables, :1164 save_inference_model;
tensor_util.cc TensorToStream, lod_tensor.cc:243 SerializeToStream,
framework.proto:198 ProgramDesc.  Acceptance (VERDICT r4 missing #5):
round-trip through our own importer bit-exact, and the ``__model__``
ProgramDesc decodes cleanly with ``protoc --decode`` against the
reference's framework.proto.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import fluid, nn
from paddle_tpu.framework.paddle_export import (
    build_program_desc, save_reference_inference_model,
    save_reference_state)
from paddle_tpu.framework.paddle_import import (
    adapt_state_dict, load_reference_state_dict,
    parse_program_persistables)

REF_PROTO_DIR = "/root/reference/paddle/fluid/framework"
HAVE_PROTOC = shutil.which("protoc") is not None and os.path.isdir(
    REF_PROTO_DIR)


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "fc_0.w_0": rng.randn(4, 3).astype(np.float32),
        "fc_0.b_0": rng.randn(3).astype(np.float32),
        "emb.weight": rng.randn(7, 2).astype(np.float64),
        "step": np.asarray([12], np.int64),
    }


class TestRoundTrip:
    def test_per_variable_files(self, tmp_path):
        state = _state()
        save_reference_state(state, str(tmp_path))
        back = load_reference_state_dict(str(tmp_path))
        assert set(back) == set(state)
        for n, v in state.items():
            np.testing.assert_array_equal(back[n], v)
            assert back[n].dtype == v.dtype

    def test_combined_file_sorted_order(self, tmp_path):
        state = _state()
        save_reference_state(state, str(tmp_path), filename="params")
        back = load_reference_state_dict(str(tmp_path),
                                         params_filename="params")
        for n, v in state.items():
            np.testing.assert_array_equal(back[n], v)

    def test_bf16_and_bool_round_trip(self, tmp_path):
        import ml_dtypes

        state = {
            "w_bf16": np.arange(6, dtype=np.float32).reshape(2, 3).astype(
                ml_dtypes.bfloat16),
            "mask": np.asarray([True, False, True]),
        }
        save_reference_state(state, str(tmp_path))
        back = load_reference_state_dict(str(tmp_path))
        np.testing.assert_array_equal(
            back["w_bf16"].astype(np.float32),
            state["w_bf16"].astype(np.float32))
        assert back["w_bf16"].dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(back["mask"], state["mask"])

    def test_model_lists_persistables(self, tmp_path):
        state = _state()
        save_reference_state(state, str(tmp_path))
        with open(tmp_path / "__model__", "rb") as f:
            specs = parse_program_persistables(f.read())
        assert {s["name"] for s in specs} == set(state)
        by_name = {s["name"]: s for s in specs}
        assert by_name["fc_0.w_0"]["shape"] == (4, 3)
        assert by_name["emb.weight"]["dtype"] == np.dtype(np.float64)


class TestInferenceModelLayout:
    def test_feed_fetch_plumbing_and_params(self, tmp_path):
        state = _state()
        save_reference_inference_model(
            str(tmp_path), ["x"], ["out"], state, params_filename="params")
        back = load_reference_state_dict(str(tmp_path),
                                         params_filename="params")
        for n, v in state.items():
            np.testing.assert_array_equal(back[n], v)

    @pytest.mark.skipif(not HAVE_PROTOC, reason="protoc or proto missing")
    def test_model_decodes_with_reference_proto(self, tmp_path):
        state = _state()
        save_reference_inference_model(str(tmp_path), ["img"], ["prob"],
                                       state)
        with open(tmp_path / "__model__", "rb") as f:
            blob = f.read()
        res = subprocess.run(
            ["protoc", f"--proto_path={REF_PROTO_DIR}",
             "--decode=paddle.framework.proto.ProgramDesc",
             "framework.proto"],
            input=blob, capture_output=True, timeout=60)
        assert res.returncode == 0, res.stderr.decode()
        text = res.stdout.decode()
        # the decoded text names our vars, plumbing, and ops
        for needle in ("fc_0.w_0", "emb.weight", "feed", "fetch",
                       "persistable: true", "LOD_TENSOR", 'type: "feed"',
                       'type: "fetch"', "parent_idx: -1"):
            assert needle in text, f"{needle!r} missing from decode:\n{text[:800]}"


class TestFluidIoSurface:
    """fluid.io.save_* / load_* are the 1.x entry points over the writer."""

    def _lenet_programs(self):
        from paddle_tpu.static.graph import Program
        import paddle_tpu.fluid as F

        main, startup = Program(), Program()
        with F.program_guard(main, startup):
            img = F.data("img", [-1, 1, 12, 12])
            label = F.data("label", [-1, 1], dtype="int64")
            conv = F.layers.conv2d(img, num_filters=4, filter_size=3,
                                   act="relu")
            pool = F.layers.pool2d(conv, pool_size=2, pool_stride=2)
            pred = F.layers.fc(pool, size=10, act="softmax")
            loss = F.layers.mean(F.layers.cross_entropy(pred, label))
            F.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss, pred

    def test_program_save_load_round_trip(self, tmp_path):
        main, startup, loss, pred = self._lenet_programs()
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        img = rng.rand(4, 1, 12, 12).astype(np.float32)
        lbl = rng.randint(0, 10, (4, 1)).astype(np.int64)
        exe.run(main, feed={"img": img, "label": lbl}, fetch_list=[loss])
        trained = {n: np.asarray(v) for n, v in main.scope.items()}

        fluid.io.save_persistables(exe, str(tmp_path), main_program=main)

        main2, startup2, loss2, pred2 = self._lenet_programs()
        exe.run(startup2)
        fluid.io.load_persistables(exe, str(tmp_path), main_program=main2)
        for n, v in trained.items():
            # same builder order → same auto names in the fresh program
            n2 = n.replace(f"_{main.idx}_", f"_{main2.idx}_")
            np.testing.assert_array_equal(np.asarray(main2.scope[n2]), v,
                                          err_msg=n)
        # and the predictions agree bit-for-bit
        p1, = exe.run(main, feed={"img": img, "label": lbl},
                      fetch_list=[pred], training=False)
        p2, = exe.run(main2, feed={"img": img, "label": lbl},
                      fetch_list=[pred2], training=False)
        np.testing.assert_array_equal(p1, p2)

    def test_layer_save_load_logits_parity(self, tmp_path):
        """The verdict's acceptance: a trained LeNet exports in the
        reference format and re-imports with exact logits parity."""
        paddle.seed(0)
        net = paddle.vision.models.LeNet()
        x = jnp.asarray(np.random.RandomState(1).randn(
            2, 1, 28, 28).astype(np.float32))
        want = np.asarray(net(x))

        fluid.io.save_params(None, str(tmp_path), main_program=net,
                             filename="params")
        paddle.seed(123)  # different init for the reload target
        net2 = paddle.vision.models.LeNet()
        fluid.io.load_params(None, str(tmp_path), main_program=net2,
                             filename="params")
        got = np.asarray(net2(x))
        np.testing.assert_array_equal(got, want)

    def test_save_vars_subset_and_predicate(self, tmp_path):
        state = _state()
        # predicate receives a Variable-like view (ref fluid/io.py:168)
        fluid.io.save_vars(None, str(tmp_path), main_program=state,
                           vars=["fc_0.w_0", "fc_0.b_0", "step"],
                           predicate=lambda var: var.persistable
                           and var.name.startswith("fc"))
        back = load_reference_state_dict(str(tmp_path))
        assert set(back) == {"fc_0.w_0", "fc_0.b_0"}

    def test_missing_file_for_model_listed_var_raises(self, tmp_path):
        state = _state()
        save_reference_state(state, str(tmp_path))
        os.remove(tmp_path / "fc_0.b_0")
        with pytest.raises(Exception, match="missing"):
            load_reference_state_dict(str(tmp_path))

    def test_load_vars_missing_requested_name_raises(self, tmp_path):
        state = _state()
        save_reference_state(state, str(tmp_path))
        with pytest.raises(Exception, match="no variables"):
            fluid.io.load_vars(None, str(tmp_path), main_program=state
                               and {}, vars=["nope.w_0"])

    def test_foreign_checkpoint_into_program_raises(self, tmp_path):
        from paddle_tpu.static.graph import Program
        import paddle_tpu.fluid as F

        save_reference_state({"alien.w_0": np.zeros((3, 3), np.float32)},
                             str(tmp_path))
        main, startup = Program(), Program()
        with F.program_guard(main, startup):
            x = F.data("x", [-1, 4])
            F.layers.fc(x, 2)
        with pytest.raises(Exception, match="counterpart"):
            fluid.io.load_persistables(None, str(tmp_path),
                                       main_program=main)

    def test_load_program_state_reads_our_export(self, tmp_path):
        from paddle_tpu import static

        state = _state()
        save_reference_state(state, str(tmp_path))
        back = static.load_program_state(str(tmp_path))
        for n, v in state.items():
            np.testing.assert_array_equal(back[n], v)

    def test_adapt_state_dict_reimports_layer(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        fluid.io.save_persistables(None, str(tmp_path), main_program=net)
        sd = load_reference_state_dict(str(tmp_path))
        mapped = adapt_state_dict(sd, net)
        assert set(mapped) == set(net.state_dict())

"""Two-stage detector training target ops vs transcribed C++ oracles.

Oracles transcribe (SURVEY §4 OpTest style, use_random=False so reservoir
sampling degenerates to first-k and both sides agree exactly):
  operators/detection/rpn_target_assign_op.cc (ScoreAssign:172-275,
  GetAllFgBgGt:520-600)
  operators/detection/generate_proposal_labels_op.cc (SampleRoisForOneImage)
  operators/detection/generate_mask_labels_op.cc + mask_util.cc
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

EPS = 1e-5


def _iou1(a, b):
    """+1-pixel IoU (bbox_util.h BboxOverlaps)."""
    iw = min(a[2], b[2]) - max(a[0], b[0]) + 1
    ih = min(a[3], b[3]) - max(a[1], b[1]) + 1
    inter = max(iw, 0) * max(ih, 0)
    ua = ((a[2] - a[0] + 1) * (a[3] - a[1] + 1)
          + (b[2] - b[0] + 1) * (b[3] - b[1] + 1) - inter)
    return inter / ua if inter > 0 else 0.0


def _delta(ex, gt, w=None):
    ew = ex[2] - ex[0] + 1
    eh = ex[3] - ex[1] + 1
    ex_x, ex_y = ex[0] + 0.5 * ew, ex[1] + 0.5 * eh
    gw = gt[2] - gt[0] + 1
    gh = gt[3] - gt[1] + 1
    gx, gy = gt[0] + 0.5 * gw, gt[1] + 0.5 * gh
    d = np.array([(gx - ex_x) / ew, (gy - ex_y) / eh,
                  np.log(gw / ew), np.log(gh / eh)])
    if w is not None:
        d = d / np.asarray(w)
    return d


def _rpn_candidates(anchors, gt, crowd, im_info, straddle, pos, neg):
    """Shared candidate-set computation (rpn_target_assign_op.cc:172-230):
    returns (inside idx list, iou [inside x gts], fg cand, bg cand,
    anchor→gt argmax) in inside-index space."""
    M = len(anchors)
    ih, iw, scale = im_info
    if straddle >= 0:
        inside = [i for i in range(M)
                  if anchors[i, 0] >= -straddle and anchors[i, 1] >= -straddle
                  and anchors[i, 2] < iw + straddle
                  and anchors[i, 3] < ih + straddle]
    else:
        inside = list(range(M))
    gts = [g * scale for g, c in zip(gt, crowd) if c == 0]
    iou = np.array([[_iou1(anchors[i], g) for g in gts] for i in inside])
    a2g_max = iou.max(1)
    a2g_arg = iou.argmax(1)
    g2a_max = iou.max(0)
    fg_cand = [k for k in range(len(inside))
               if any(abs(iou[k, j] - g2a_max[j]) < EPS
                      for j in range(len(gts))) or a2g_max[k] >= pos]
    bg_cand = [k for k in range(len(inside)) if a2g_max[k] < neg]
    return inside, gts, fg_cand, bg_cand, a2g_arg


def _rpn_oracle_one(anchors, gt, crowd, im_info, B, straddle, pos, neg, frac):
    """Transcribes rpn_target_assign_op.cc per image, use_random=False."""
    inside, gts, fg_cand, bg_cand, a2g_arg = _rpn_candidates(
        anchors, gt, crowd, im_info, straddle, pos, neg)
    quota = int(frac * B)
    fg_sel = fg_cand[:quota]
    bg_sel = bg_cand[:B - len(fg_sel)]
    label = {}
    for k in fg_sel:
        label[k] = 1
    fakes = 0
    for k in bg_sel:
        if label.get(k) == 1:
            fakes += 1
        label[k] = 0
    real_fg = [k for k in fg_sel if label.get(k) == 1]
    loc_k = [fg_sel[0]] * fakes + real_fg
    weights = [0.0] * fakes + [1.0] * len(real_fg)
    score_k = real_fg + bg_sel
    score_lbl = [1] * len(real_fg) + [0] * len(bg_sel)
    loc_idx = [inside[k] for k in loc_k]
    score_idx = [inside[k] for k in score_k]
    tgt = [_delta(anchors[inside[k]], gts[a2g_arg[k]]) for k in loc_k]
    return loc_idx, weights, tgt, score_idx, score_lbl


class TestRpnTargetAssign:
    def _data(self, seed, N=2, M=40, G=4):
        rng = np.random.RandomState(seed)
        anchors = np.zeros((M, 4), np.float32)
        anchors[:, :2] = rng.uniform(-10, 70, (M, 2))
        anchors[:, 2:] = anchors[:, :2] + rng.uniform(5, 40, (M, 2))
        gt = np.zeros((N, G, 4), np.float32)
        gt[..., :2] = rng.uniform(0, 50, (N, G, 2))
        gt[..., 2:] = gt[..., :2] + rng.uniform(10, 40, (N, G, 2))
        crowd = (rng.uniform(size=(N, G)) < 0.2).astype(np.int32)
        im_info = np.array([[90, 90, 1.0], [90, 90, 0.5]], np.float32)[:N]
        bbox_pred = rng.randn(N, M, 4).astype(np.float32)
        cls_logits = rng.randn(N, M, 1).astype(np.float32)
        return anchors, gt, crowd, im_info, bbox_pred, cls_logits

    @pytest.mark.parametrize("seed", [0, 3, 7])
    def test_vs_oracle_deterministic(self, seed):
        B, frac, straddle = 16, 0.5, 0.0
        anchors, gt, crowd, im_info, bbox_pred, cls_logits = self._data(seed)
        N, M = bbox_pred.shape[:2]
        scores, loc, lbl, tgt, inw = F.rpn_target_assign(
            bbox_pred, cls_logits, anchors, None, gt, crowd, im_info,
            rpn_batch_size_per_im=B, rpn_straddle_thresh=straddle,
            rpn_fg_fraction=frac, use_random=False)
        F_cap = max(int(frac * B), 1)
        loc_np = np.asarray(loc).reshape(N, F_cap, 4)
        tgt_np = np.asarray(tgt).reshape(N, F_cap, 4)
        inw_np = np.asarray(inw).reshape(N, F_cap, 4)
        lbl_np = np.asarray(lbl).reshape(N, B)
        sc_np = np.asarray(scores).reshape(N, B)
        for n in range(N):
            loc_idx, w, t, score_idx, score_lbl = _rpn_oracle_one(
                anchors, gt[n], crowd[n], im_info[n], B, straddle, 0.7, 0.3,
                frac)
            k = len(loc_idx)
            np.testing.assert_allclose(
                loc_np[n, :k], bbox_pred[n][loc_idx], atol=1e-5,
                err_msg="predicted_location gather")
            np.testing.assert_allclose(
                inw_np[n, :k], np.repeat(np.array(w)[:, None], 4, 1))
            np.testing.assert_allclose(tgt_np[n, :k], np.array(t), atol=1e-4)
            assert (inw_np[n, k:] == 0).all()
            s = len(score_idx)
            np.testing.assert_array_equal(lbl_np[n, :s], score_lbl)
            assert (lbl_np[n, s:] == -1).all()
            np.testing.assert_allclose(
                sc_np[n, :s], cls_logits[n, score_idx, 0], atol=1e-6)

    def test_random_mode_quotas(self):
        B, frac = 16, 0.5
        anchors, gt, crowd, im_info, bbox_pred, cls_logits = self._data(1)
        N = bbox_pred.shape[0]
        scores, loc, lbl, tgt, inw = F.rpn_target_assign(
            bbox_pred, cls_logits, anchors, None, gt, crowd, im_info,
            rpn_batch_size_per_im=B, rpn_fg_fraction=frac, use_random=True,
            key=jax.random.PRNGKey(42))
        lbl_np = np.asarray(lbl).reshape(N, B)
        sc_np = np.asarray(scores).reshape(N, B)
        for n in range(N):
            fg = (lbl_np[n] == 1).sum()
            valid = (lbl_np[n] >= 0).sum()
            assert fg <= int(frac * B)
            assert valid <= B
            assert valid > 0
            # containment: every selected anchor must come from the oracle
            # candidate sets (random logits are unique, so gathered score
            # values identify the chosen anchors)
            inside, _, fg_c, bg_c, _ = _rpn_candidates(
                anchors, gt[n], crowd[n], im_info[n], 0.0, 0.7, 0.3)
            fg_cand = {inside[kk] for kk in fg_c}
            bg_cand = {inside[kk] for kk in bg_c}
            logits_flat = cls_logits[n, :, 0]
            for slot in range(B):
                if lbl_np[n, slot] < 0:
                    continue
                idx = int(np.argmin(np.abs(logits_flat - sc_np[n, slot])))
                allowed = fg_cand if lbl_np[n, slot] == 1 else \
                    fg_cand | bg_cand  # a bg slot may be an overwritten fg
                assert idx in allowed, (slot, idx)

    def test_jit_compiles(self):
        anchors, gt, crowd, im_info, bbox_pred, cls_logits = self._data(2)
        f = jax.jit(lambda bp, cl, g, c, ii, k: F.rpn_target_assign(
            bp, cl, anchors, None, g, c, ii, rpn_batch_size_per_im=16,
            use_random=True, key=k))
        out = f(bbox_pred, cls_logits, gt, crowd, im_info,
                jax.random.PRNGKey(0))
        assert out[0].shape == (2 * 16, 1)


def _gpl_oracle_one(rois, gt, gt_cls, crowd, im_info, B, frac, fg_t, bg_hi,
                    bg_lo, reg_w, C, agnostic):
    """Transcribes SampleRoisForOneImage, use_random=False."""
    scale = im_info[2]
    rois = rois / scale
    boxes = np.concatenate([gt, rois], 0)
    G = len(gt)
    iou = np.array([[_iou1(b, g) for g in gt] for b in boxes])
    max_ov = iou.max(1)
    for i in range(G):
        if crowd[i]:
            max_ov[i] = -1.0
    fg, mapped = [], []
    bg = []
    for i in range(len(boxes)):
        if max_ov[i] >= fg_t:
            for j in range(G):
                if abs(max_ov[i] - iou[i, j]) < EPS:
                    fg.append(i)
                    mapped.append(j)
                    break
        elif bg_lo <= max_ov[i] < bg_hi:
            bg.append(i)
    quota = int(np.floor(B * frac))
    fg_sel, map_sel = fg[:quota], mapped[:quota]
    bg_sel = bg[:B - len(fg_sel)]
    rows = fg_sel + bg_sel
    labels = [gt_cls[j] for j in map_sel] + [0] * len(bg_sel)
    out_rois = boxes[rows] * scale
    tgt = np.zeros((len(rows), 4 * C))
    w = np.zeros((len(rows), 4 * C))
    for r, (i, lb) in enumerate(zip(rows, labels)):
        if lb > 0:
            d = _delta(boxes[i], gt[mapped[fg.index(i)]], reg_w)
            slot = 1 if agnostic else lb
            tgt[r, 4 * slot:4 * slot + 4] = d
            w[r, 4 * slot:4 * slot + 4] = 1
    max_out = max_ov[rows]
    return out_rois, labels, tgt, w, max_out


class TestGenerateProposalLabels:
    def _data(self, seed, N=2, R=12, G=3):
        rng = np.random.RandomState(seed)
        gt = np.zeros((N, G, 4), np.float32)
        gt[..., :2] = rng.uniform(0, 40, (N, G, 2))
        gt[..., 2:] = gt[..., :2] + rng.uniform(10, 30, (N, G, 2))
        rois = np.zeros((N, R, 4), np.float32)
        rois[..., :2] = rng.uniform(0, 40, (N, R, 2))
        rois[..., 2:] = rois[..., :2] + rng.uniform(5, 30, (N, R, 2))
        # make some rois near-gt so fg exists
        rois[:, :G] = gt + rng.uniform(-2, 2, (N, G, 4)).astype(np.float32)
        gt_cls = rng.randint(1, 5, (N, G)).astype(np.int32)
        crowd = np.zeros((N, G), np.int32)
        crowd[:, -1] = 1
        im_info = np.array([[80, 80, 1.0], [80, 80, 2.0]], np.float32)[:N]
        return rois, gt, gt_cls, crowd, im_info

    @pytest.mark.parametrize("seed", [0, 5])
    @pytest.mark.parametrize("agnostic", [False, True])
    def test_vs_oracle(self, seed, agnostic):
        B, frac, C = 10, 0.25, 5
        reg_w = (0.1, 0.1, 0.2, 0.2)
        rois, gt, gt_cls, crowd, im_info = self._data(seed)
        N = rois.shape[0]
        r, lbls, bt, biw, bow, mo = F.generate_proposal_labels(
            rois, gt_cls, crowd, gt, im_info, batch_size_per_im=B,
            fg_fraction=frac, fg_thresh=0.25, bg_thresh_hi=0.5,
            bg_thresh_lo=0.0, bbox_reg_weights=reg_w, class_nums=C,
            use_random=False, is_cls_agnostic=agnostic,
            return_max_overlap=True)
        r = np.asarray(r).reshape(N, B, 4)
        lbls = np.asarray(lbls).reshape(N, B)
        bt = np.asarray(bt).reshape(N, B, 4 * C)
        biw = np.asarray(biw).reshape(N, B, 4 * C)
        mo = np.asarray(mo).reshape(N, B)
        for n in range(N):
            o_rois, o_lbl, o_tgt, o_w, o_mo = _gpl_oracle_one(
                rois[n], gt[n], gt_cls[n], crowd[n], im_info[n], B, frac,
                0.25, 0.5, 0.0, reg_w, C, agnostic)
            k = len(o_lbl)
            assert k > 0
            np.testing.assert_allclose(r[n, :k], o_rois, atol=1e-4)
            np.testing.assert_array_equal(lbls[n, :k], o_lbl)
            assert (lbls[n, k:] == -1).all()
            np.testing.assert_allclose(bt[n, :k], o_tgt, atol=1e-4)
            np.testing.assert_allclose(biw[n, :k], o_w)
            np.testing.assert_allclose(mo[n, :k], o_mo, atol=1e-5)

    def test_gt_joins_proposals(self):
        # a gt box with no nearby roi must still appear as its own fg row
        rois, gt, gt_cls, crowd, im_info = self._data(3)
        rois[:, :, :] = 70.0  # push all rois away
        rois[:, :, 2:] = 75.0
        r, lbls, *_ = F.generate_proposal_labels(
            rois, gt_cls, crowd, gt, im_info, batch_size_per_im=8,
            class_nums=5, use_random=False)
        lbls = np.asarray(lbls).reshape(2, 8)
        assert (lbls[0] > 0).sum() >= 1  # gt-derived fg rows exist

    def test_random_quota(self):
        rois, gt, gt_cls, crowd, im_info = self._data(4)
        r, lbls, *_ = F.generate_proposal_labels(
            rois, gt_cls, crowd, gt, im_info, batch_size_per_im=8,
            fg_fraction=0.25, class_nums=5, use_random=True,
            key=jax.random.PRNGKey(7))
        lbls = np.asarray(lbls).reshape(2, 8)
        for n in range(2):
            assert (lbls[n] > 0).sum() <= 2  # floor(8*0.25)


class TestGenerateMaskLabels:
    def test_rectangle_masks_exact(self):
        # rectangle polygons: even-odd rasterization is exact vs geometry
        N, G, R, Pp, V, C, M = 1, 2, 4, 1, 6, 3, 14
        gt = np.array([[[10, 10, 30, 30], [40, 40, 60, 60]]], np.float32)
        polys = np.zeros((N, G, Pp, V, 2), np.float32)
        for g in range(G):
            x0, y0, x1, y1 = gt[0, g]
            polys[0, g, 0, :4] = [[x0, y0], [x1, y0], [x1, y1], [x0, y1]]
        nv = np.full((N, G, Pp), 4, np.int32)
        pn = np.ones((N, G), np.int32)
        gt_cls = np.array([[1, 2]], np.int32)
        crowd = np.zeros((N, G), np.int32)
        im_info = np.array([[80, 80, 1.0]], np.float32)
        # roi 0 covers gt0 shifted; roi 1 = gt1; roi 2 bg; roi 3 padding
        rois = np.array([[[5, 5, 25, 25], [40, 40, 60, 60],
                          [0, 0, 70, 70], [0, 0, 0, 0]]], np.float32)
        labels = np.array([[1, 2, 0, -1]], np.int32)
        mr, hm, mi, mn = F.generate_mask_labels(
            im_info, gt_cls, crowd, polys, rois, labels, C, M,
            poly_vertex_num=nv, poly_num=pn, rois_num=np.array([3]))
        assert int(mn[0]) == 2
        mi = np.asarray(mi).reshape(N * R, C, M, M)
        mr = np.asarray(mr).reshape(N * R, 4)
        np.testing.assert_allclose(mr[0], rois[0, 0])
        # expected mask for roi 0 (class 1): pixel centers inside gt0
        # mapped into roi-relative grid coords
        bx0, by0, bx1, by1 = rois[0, 0]
        w, h = bx1 - bx0, by1 - by0
        exp = np.zeros((M, M), np.int32)
        for i in range(M):
            for j in range(M):
                cx = bx0 + (j + 0.5) * w / M
                cy = by0 + (i + 0.5) * h / M
                # half-open rasterization convention: a center exactly on
                # the min edge is inside, on the max edge outside
                exp[i, j] = int(10 <= cx < 30 and 10 <= cy < 30)
        np.testing.assert_array_equal(mi[0, 1], exp)
        assert (mi[0, 0] == -1).all() and (mi[0, 2] == -1).all()
        # roi 1 == gt1 exactly: the class-2 slot is all ones
        assert (mi[1, 2] == 1).all()
        assert (mi[1, 1] == -1).all()
        # rows beyond the fg count are all ignore
        assert (mi[2] == -1).all() and (mi[3] == -1).all()

    def test_no_fg_fallback(self):
        # no fg roi → one all-ignore row on roi 0 with class 0 (op.cc:260)
        N, G, R, Pp, V, C, M = 1, 1, 3, 1, 4, 2, 8
        gt = np.array([[[10, 10, 30, 30]]], np.float32)
        polys = np.zeros((N, G, Pp, V, 2), np.float32)
        polys[0, 0, 0] = [[10, 10], [30, 10], [30, 30], [10, 30]]
        nv = np.full((N, G, Pp), 4, np.int32)
        pn = np.ones((N, G), np.int32)
        rois = np.array([[[0, 0, 5, 5], [50, 50, 60, 60],
                          [1, 1, 2, 2]]], np.float32)
        labels = np.zeros((N, R), np.int32)
        mr, hm, mi, mn = F.generate_mask_labels(
            np.array([[80, 80, 1.0]], np.float32), np.array([[1]], np.int32),
            np.zeros((N, G), np.int32), polys, rois, labels, C, M,
            poly_vertex_num=nv, poly_num=pn)
        assert int(mn[0]) == 1
        mi = np.asarray(mi).reshape(R, -1)
        assert (mi[0] == -1).all()
        np.testing.assert_allclose(np.asarray(mr)[0], rois[0, 0])
        assert int(np.asarray(hm)[0, 0]) == 0  # first bg roi index


class TestRetinanetTargetAssign:
    def test_labels_and_fg_num(self):
        rng = np.random.RandomState(0)
        N, M, G, C = 2, 30, 3, 4
        anchors = np.zeros((M, 4), np.float32)
        anchors[:, :2] = rng.uniform(0, 50, (M, 2))
        anchors[:, 2:] = anchors[:, :2] + rng.uniform(5, 30, (M, 2))
        gt = np.zeros((N, G, 4), np.float32)
        gt[..., :2] = rng.uniform(0, 40, (N, G, 2))
        gt[..., 2:] = gt[..., :2] + rng.uniform(10, 30, (N, G, 2))
        gl = rng.randint(1, C + 1, (N, G)).astype(np.int32)
        crowd = np.zeros((N, G), np.int32)
        im_info = np.array([[80, 80, 1.0]] * N, np.float32)
        bbox_pred = rng.randn(N, M, 4).astype(np.float32)
        cls_logits = rng.randn(N, M, C).astype(np.float32)
        s, l, lb, tb, iw, fgn = F.retinanet_target_assign(
            bbox_pred, cls_logits, anchors, None, gt, gl, crowd, im_info,
            num_classes=C, positive_overlap=0.5, negative_overlap=0.4)
        lb = np.asarray(lb).reshape(N, M)
        fgn = np.asarray(fgn).ravel()
        for n in range(N):
            # no subsampling: every anchor with IoU ≥ 0.5 is fg (its gt's
            # class), everything < 0.4 is bg (0), padding -1
            iou = np.array([[_iou1(a, g) for g in gt[n]] for a in anchors])
            amax = iou.max(1)
            aarg = iou.argmax(1)
            tie = np.any(np.abs(iou - iou.max(0, keepdims=True)) < EPS, 1)
            n_fg_cand = ((amax >= 0.5) | tie).sum()
            # fg_num = fg_fake_num + 1 (kernel:598); no sampling, so the
            # fake-inclusive fg count is exactly the candidate count
            assert fgn[n] == n_fg_cand + 1, (fgn[n], n_fg_cand)
            valid = lb[n][lb[n] >= 0]
            fg_lbls = lb[n][(lb[n] > 0)]
            assert len(fg_lbls) > 0
            assert set(np.unique(fg_lbls)).issubset(set(gl[n].tolist()))


class TestRcnnHeadTraining:
    def test_head_converges_on_synthetic_boxes(self):
        """End-to-end: generate_proposal_labels feeds a tiny RCNN head
        (roi features → cls + box deltas) whose jitted train step converges
        on fixed synthetic boxes — the two-stage training wiring the
        reference exercises via its Faster-RCNN configs."""
        import paddle_tpu.optimizer as popt

        rng = np.random.RandomState(0)
        N, R, G, C = 2, 16, 2, 3  # 2 real classes + bg
        gt = np.zeros((N, G, 4), np.float32)
        gt[..., :2] = rng.uniform(5, 30, (N, G, 2))
        gt[..., 2:] = gt[..., :2] + rng.uniform(15, 30, (N, G, 2))
        gt_cls = rng.randint(1, C, (N, G)).astype(np.int32)
        crowd = np.zeros((N, G), np.int32)
        im_info = np.array([[80, 80, 1.0]] * N, np.float32)
        rois = np.zeros((N, R, 4), np.float32)
        rois[..., :2] = rng.uniform(0, 50, (N, R, 2))
        rois[..., 2:] = rois[..., :2] + rng.uniform(8, 30, (N, R, 2))
        rois[:, :G] = gt + rng.uniform(-3, 3, (N, G, 4)).astype(np.float32)

        B = 12
        s_rois, labels, tgt, in_w, out_w = F.generate_proposal_labels(
            rois, gt_cls, crowd, gt, im_info, batch_size_per_im=B,
            fg_fraction=0.5, fg_thresh=0.5, class_nums=C,
            use_random=False)[:5]
        s_rois = jnp.asarray(s_rois)
        labels = jnp.asarray(labels).reshape(-1)
        tgt = jnp.asarray(tgt)
        in_w = jnp.asarray(in_w)

        # tiny "roi feature": normalized roi geometry (deterministic)
        feats = jnp.concatenate(
            [s_rois / 80.0, ((s_rois[:, 2:] - s_rois[:, :2]) / 80.0)], 1)
        params = {
            "w1": jnp.asarray(rng.randn(6, 32) * 0.1),
            "w_cls": jnp.asarray(rng.randn(32, C) * 0.1),
            "w_box": jnp.asarray(rng.randn(32, 4 * C) * 0.01),
        }

        def loss_fn(p):
            h = jax.nn.relu(feats @ p["w1"])
            logits = h @ p["w_cls"]
            deltas = h @ p["w_box"]
            cls = F.cross_entropy(logits, labels, ignore_index=-1,
                                  reduction="mean")
            reg = jnp.sum(in_w * (deltas - tgt) ** 2) \
                / jnp.maximum(jnp.sum(in_w), 1.0)
            return cls + reg

        opt = popt.Adam(learning_rate=0.05)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(loss_fn)(p)
            p, s = opt.update(g, s, p, lr=0.05)
            return p, s, l

        first = None
        for i in range(200):
            params, state, l = step(params, state)
            if first is None:
                first = float(l)
        final = float(l)
        assert final < first * 0.45, (first, final)
        # classification learned: fg/bg accuracy on trained rows
        h = jax.nn.relu(feats @ params["w1"])
        pred = np.asarray((h @ params["w_cls"]).argmax(-1))
        lbl_np = np.asarray(labels)
        m = lbl_np >= 0
        acc = (pred[m] == lbl_np[m]).mean()
        assert acc > 0.8, acc


def test_fluid_layers_resolve():
    from paddle_tpu.fluid import layers as fl
    assert fl.rpn_target_assign is F.rpn_target_assign
    assert fl.generate_proposal_labels is F.generate_proposal_labels
    assert fl.generate_mask_labels is F.generate_mask_labels
    assert fl.retinanet_target_assign is F.retinanet_target_assign


class TestTwoStageEndToEnd:
    """Full Faster-RCNN-style training wiring: backbone features → RPN
    (losses via rpn_target_assign) → generate_proposals → RCNN sampling
    (generate_proposal_labels) → head losses — ONE jitted step over every
    stage, converging on synthetic boxes.  This is the chain the
    reference exercises through its Faster-RCNN configs."""

    def test_joint_rpn_rcnn_training_converges(self):
        import paddle_tpu.optimizer as popt
        from paddle_tpu.nn.functional.detection import (
            anchor_generator,
            generate_proposals,
        )

        rng = np.random.RandomState(0)
        N, C, Hf, Wf = 2, 8, 8, 8          # feature map 8x8, stride 8
        IM = 64
        A = 3                               # anchors per cell
        G = 2
        # fixed synthetic scene: gt boxes + a deterministic "backbone"
        gt = np.zeros((N, G, 4), np.float32)
        gt[..., :2] = rng.uniform(4, 28, (N, G, 2))
        gt[..., 2:] = gt[..., :2] + rng.uniform(16, 30, (N, G, 2))
        gt = np.clip(gt, 0, IM - 1)
        gt_cls = rng.randint(1, 3, (N, G)).astype(np.int32)
        crowd = np.zeros((N, G), np.int32)
        im_info = np.array([[IM, IM, 1.0]] * N, np.float32)
        feats = jnp.asarray(rng.randn(N, C, Hf, Wf).astype(np.float32) * 0.1)

        anchors, variances = anchor_generator(
            np.zeros((N, C, Hf, Wf), np.float32),
            anchor_sizes=[16.0, 24.0, 32.0], aspect_ratios=[1.0],
            stride=[8.0, 8.0])
        anchors_flat = jnp.asarray(anchors).reshape(-1, 4)
        var_flat = jnp.asarray(variances).reshape(-1, 4)
        M = anchors_flat.shape[0]
        assert M == Hf * Wf * A

        params = {
            "rpn_w": jnp.asarray(rng.randn(C, A * 5) * 0.01),   # 4 loc + 1 cls
            "head_w1": jnp.asarray(rng.randn(6, 32) * 0.1),
            "head_cls": jnp.asarray(rng.randn(32, 3) * 0.1),
            "head_box": jnp.asarray(rng.randn(32, 12) * 0.01),
        }

        def rpn_heads(p):
            # 1x1 conv as einsum: [N, C, H, W] x [C, A*5]
            o = jnp.einsum("nchw,ck->nkhw", feats, p["rpn_w"])
            o = jnp.transpose(o, (0, 2, 3, 1)).reshape(N, M, 5)
            return o[..., :4], o[..., 4:5]  # bbox_pred, cls_logits

        def loss_fn(p, key):
            bbox_pred, cls_logits = rpn_heads(p)
            # --- stage 1 losses: RPN target assignment
            scores, loc, lbl, tgt, inw = F.rpn_target_assign(
                bbox_pred, cls_logits, anchors_flat, None,
                jnp.asarray(gt), jnp.asarray(crowd), jnp.asarray(im_info),
                rpn_batch_size_per_im=32, rpn_positive_overlap=0.5,
                rpn_negative_overlap=0.3, use_random=True,
                key=jax.random.fold_in(key, 1))
            valid = (lbl >= 0).astype(jnp.float32)
            rpn_cls = jnp.sum(
                valid * (jax.nn.softplus(scores)
                         - scores * lbl.astype(jnp.float32))) \
                / jnp.maximum(valid.sum(), 1.0)
            rpn_reg = jnp.sum(jnp.asarray(inw) * (loc - tgt) ** 2) \
                / jnp.maximum(jnp.asarray(inw).sum(), 1.0)

            # --- proposals (stop-grad: sampling indices, like the
            # reference's stop_gradient=True on the op outputs)
            rois, roi_probs, roi_counts = generate_proposals(
                jax.lax.stop_gradient(
                    jax.nn.sigmoid(cls_logits).reshape(N, Hf, Wf, A)
                    .transpose(0, 3, 1, 2)),
                jax.lax.stop_gradient(
                    bbox_pred.reshape(N, Hf, Wf, A * 4)
                    .transpose(0, 3, 1, 2)),
                jnp.asarray(im_info), anchors, variances,
                pre_nms_top_n=64, post_nms_top_n=16,
                return_rois_num=True)

            # --- stage 2: sample rois → head targets
            s_rois, labels, btgt, binw, _ = F.generate_proposal_labels(
                rois, jnp.asarray(gt_cls), jnp.asarray(crowd),
                jnp.asarray(gt), jnp.asarray(im_info),
                rois_num=roi_counts, batch_size_per_im=16,
                fg_fraction=0.5, fg_thresh=0.5, class_nums=3,
                use_random=True, key=jax.random.fold_in(key, 2))
            s_rois = jax.lax.stop_gradient(jnp.asarray(s_rois))
            # tiny roi feature: normalized geometry (deterministic)
            rf = jnp.concatenate(
                [s_rois / IM, (s_rois[:, 2:] - s_rois[:, :2]) / IM], 1)
            h = jax.nn.relu(rf @ p["head_w1"])
            logits = h @ p["head_cls"]
            deltas = h @ p["head_box"]
            lbls = jnp.asarray(labels).reshape(-1)
            head_cls = F.cross_entropy(logits, lbls, ignore_index=-1,
                                       reduction="mean")
            head_reg = jnp.sum(jnp.asarray(binw) * (deltas - btgt) ** 2) \
                / jnp.maximum(jnp.asarray(binw).sum(), 1.0)
            return rpn_cls + rpn_reg + head_cls + head_reg

        opt = popt.Adam(learning_rate=0.02)
        state = opt.init(params)

        @jax.jit
        def step(p, s, key):
            l, g = jax.value_and_grad(loss_fn)(p, key)
            p, s = opt.update(g, s, p, lr=0.02)
            return p, s, l

        # one fixed sampling key: targets stay consistent across steps
        # (per-step resampling also works, just noisier to assert on)
        key = jax.random.PRNGKey(0)
        first = None
        for i in range(250):
            params, state, l = step(params, state, key)
            if first is None:
                first = float(l)
        final = float(l)
        assert np.isfinite(final)
        assert final < first * 0.5, (first, final)

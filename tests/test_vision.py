"""Vision package tests: model zoo shapes, transforms vs numpy oracle,
datasets from synthetic files, and a LeNet convergence gate.

Mirrors the reference's strategy (SURVEY §4): book-style convergence
thresholds (reference: python/paddle/fluid/tests/book/test_recognize_digits.py:126)
and numpy-oracle checks for image ops.
"""
import gzip
import os
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision import models as M
from paddle_tpu.vision import datasets as D
from paddle_tpu.vision.transforms import functional as TF


# --- models -----------------------------------------------------------------

def test_resnet_nhwc_matches_nchw():
    # data_format="NHWC" is the TPU-preferred layout (bench.py uses it);
    # same state_dict must produce identical outputs on transposed input
    paddle.seed(0)
    m1 = M.resnet18(num_classes=10)
    m2 = M.resnet18(num_classes=10, data_format="NHWC")
    m2.set_state_dict(m1.state_dict())
    m1.eval(); m2.eval()
    x = np.random.RandomState(0).uniform(-1, 1, (2, 3, 64, 64)).astype(np.float32)
    o1 = np.asarray(m1(paddle.to_tensor(x)))
    o2 = np.asarray(m2(paddle.to_tensor(x.transpose(0, 2, 3, 1))))
    assert np.abs(o1 - o2).max() < 2e-4


@pytest.mark.parametrize("mk,shape", [
    (lambda df: M.LeNet(data_format=df), (2, 1, 28, 28)),
    (lambda df: M.MobileNetV1(num_classes=5, data_format=df), (1, 3, 64, 64)),
    (lambda df: M.MobileNetV2(num_classes=5, data_format=df), (1, 3, 64, 64)),
    (lambda df: M.vgg11(batch_norm=True, num_classes=5, data_format=df),
     (1, 3, 224, 224)),
])
def test_model_zoo_nhwc_matches_nchw(mk, shape):
    # every zoo model runs the TPU-preferred layout off the SAME state_dict
    paddle.seed(0)
    m1 = mk("NCHW")
    m2 = mk("NHWC")
    m2.set_state_dict(m1.state_dict())
    m1.eval(); m2.eval()
    x = np.random.RandomState(0).uniform(-1, 1, shape).astype(np.float32)
    o1 = np.asarray(m1(paddle.to_tensor(x)))
    o2 = np.asarray(m2(paddle.to_tensor(x.transpose(0, 2, 3, 1))))
    assert np.abs(o1 - o2).max() < 5e-4


def test_lenet_forward():
    net = M.LeNet()
    out = net(np.zeros((2, 1, 28, 28), np.float32))
    assert out.shape == (2, 10)


@pytest.mark.parametrize("ctor,depth", [(M.resnet18, 18), (M.resnet50, 50)])
def test_resnet_forward(ctor, depth):
    net = ctor(num_classes=7)
    out = net(np.zeros((2, 3, 64, 64), np.float32))
    assert out.shape == (2, 7)


def test_resnet50_param_count():
    net = M.resnet50()
    n = sum(int(np.prod(p.shape)) for p in net.parameters())
    assert n == 25_557_032  # canonical ResNet-50 ImageNet param count


def test_resnet_no_pool_no_fc():
    net = M.ResNet(M.BasicBlock, 18, num_classes=-1, with_pool=False)
    out = net(np.zeros((1, 3, 32, 32), np.float32))
    assert out.shape == (1, 512, 1, 1)


def test_vgg16_forward():
    net = M.vgg16(num_classes=5)
    out = net(np.zeros((1, 3, 224, 224), np.float32))
    assert out.shape == (1, 5)


def test_mobilenet_v1_v2_forward():
    for ctor in (M.mobilenet_v1, M.mobilenet_v2):
        net = ctor(num_classes=4)
        out = net(np.zeros((1, 3, 64, 64), np.float32))
        assert out.shape == (1, 4)


def test_pretrained_requires_local_path():
    with pytest.raises(ValueError, match="no pretrained-weight download"):
        M.resnet18(pretrained=True)


# --- transforms -------------------------------------------------------------

def test_to_tensor_scales_and_chw():
    img = np.full((4, 6, 3), 255, np.uint8)
    out = TF.to_tensor(img)
    assert out.shape == (3, 4, 6)
    np.testing.assert_allclose(out, 1.0)


def test_resize_int_short_side():
    img = np.zeros((40, 80, 3), np.uint8)
    out = TF.resize(img, 20)
    assert out.shape[:2] == (20, 40)


def test_center_crop_and_crop():
    img = np.arange(5 * 5).reshape(5, 5, 1).astype(np.uint8)
    out = TF.center_crop(img, 3)
    np.testing.assert_array_equal(out[..., 0], img[1:4, 1:4, 0])


def test_flips():
    img = np.arange(6).reshape(2, 3, 1).astype(np.uint8)
    np.testing.assert_array_equal(TF.hflip(img)[..., 0], img[:, ::-1, 0])
    np.testing.assert_array_equal(TF.vflip(img)[..., 0], img[::-1, :, 0])


def test_normalize_chw():
    img = np.ones((3, 2, 2), np.float32)
    out = TF.normalize(img, mean=[1, 1, 1], std=[2, 2, 2])
    np.testing.assert_allclose(out, 0.0)


def test_pad_constant():
    img = np.ones((2, 2, 1), np.uint8)
    out = TF.pad(img, 1)
    assert out.shape == (4, 4, 1)
    assert out[0, 0, 0] == 0


def test_compose_pipeline():
    tf = T.Compose([
        T.Resize(8), T.CenterCrop(8), T.ToTensor(),
        T.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5]),
    ])
    out = tf(np.random.RandomState(0).randint(0, 255, (16, 16, 3), dtype=np.uint8).astype(np.uint8))
    assert out.shape == (3, 8, 8)


def test_random_crop_shape():
    img = np.zeros((10, 10, 3), np.uint8)
    out = T.RandomCrop(6)._apply_image(img)
    assert TF._to_numpy(out).shape[:2] == (6, 6)


def test_color_jitter_runs():
    img = np.random.RandomState(0).randint(0, 255, (8, 8, 3)).astype(np.uint8)
    out = T.ColorJitter(0.4, 0.4, 0.4, 0.1)._apply_image(img)
    assert TF._to_numpy(out).shape == (8, 8, 3)


def test_base_transform_keys_passthrough():
    tf = T.RandomHorizontalFlip(prob=1.0, keys=("image", None))
    img = np.arange(6).reshape(2, 3, 1).astype(np.uint8)
    out_img, label = tf((img, 7))
    assert label == 7
    np.testing.assert_array_equal(TF._to_numpy(out_img)[..., 0], img[:, ::-1, 0])


# --- datasets ---------------------------------------------------------------

def _write_idx(tmpdir, n=32):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 255, (n, 28, 28), dtype=np.uint8)
    labels = rng.randint(0, 10, (n,)).astype(np.uint8)
    img_path = os.path.join(tmpdir, "imgs.gz")
    lbl_path = os.path.join(tmpdir, "lbls.gz")
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path, images, labels


def test_mnist_reads_idx(tmp_path):
    img_path, lbl_path, images, labels = _write_idx(str(tmp_path))
    ds = D.MNIST(image_path=img_path, label_path=lbl_path, mode="train")
    assert len(ds) == 32
    img, label = ds[3]
    assert img.shape == (1, 28, 28)
    np.testing.assert_array_equal(img[0], images[3].astype(np.float32))
    assert int(label) == int(labels[3])


def test_mnist_missing_file_is_actionable(tmp_path):
    with pytest.raises(FileNotFoundError, match="no network egress"):
        D.MNIST(image_path=str(tmp_path / "nope.gz"),
                label_path=str(tmp_path / "nope2.gz"))


def test_cifar_reads_archive(tmp_path):
    import pickle
    import tarfile

    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, (10, 3072), dtype=np.uint8)
    labels = rng.randint(0, 10, (10,)).tolist()
    batch = {b"data": data, b"labels": labels}
    batch_file = tmp_path / "data_batch_1"
    with open(batch_file, "wb") as f:
        pickle.dump(batch, f)
    archive = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(archive, "w:gz") as tar:
        tar.add(batch_file, arcname="cifar-10-batches-py/data_batch_1")
    ds = D.Cifar10(data_file=str(archive), mode="train")
    assert len(ds) == 10
    img, label = ds[0]
    assert img.shape == (3, 32, 32)
    assert int(label) == labels[0]


def test_dataset_folder(tmp_path):
    from PIL import Image

    for cls in ("cat", "dog"):
        os.makedirs(tmp_path / cls)
        for i in range(3):
            Image.fromarray(
                np.random.RandomState(i).randint(0, 255, (8, 8, 3), dtype=np.uint8)
            ).save(tmp_path / cls / f"{i}.png")
    ds = D.DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    img, label = ds[0]
    assert img.shape == (8, 8, 3)
    assert int(label) == 0


def test_image_folder(tmp_path):
    from PIL import Image

    for i in range(4):
        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(tmp_path / f"{i}.jpg")
    ds = D.ImageFolder(str(tmp_path))
    assert len(ds) == 4
    (img,) = ds[0]
    assert img.shape == (8, 8, 3)


# --- convergence gate (book-test style) -------------------------------------

def test_resnet50_amp_dp_plan():
    """BASELINE configs 2+4: ResNet-50 trains AMP-O1 under an 8-device
    data-parallel fleet plan (batch sharded over the mesh, momentum with
    f32 master weights)."""
    from paddle_tpu import optimizer as popt
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.mesh import build_mesh, set_mesh

    fleet._initialized = False
    set_mesh(build_mesh())
    try:
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        paddle.seed(0)
        net = M.resnet50(num_classes=4)
        opt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.01, momentum=0.9,
                          multi_precision=True))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                      amp_configs="O1")
        assert model._plan is not None and model._plan.n_data_shards == 8

        rng = np.random.RandomState(0)
        labels = rng.randint(0, 4, (16,))
        x = rng.normal(0, 0.5, (16, 3, 64, 64)).astype(np.float32)
        for i, y in enumerate(labels):  # separable: class tints a channel
            x[i, int(y) % 3] += 1.0 + 0.5 * int(y)

        w_before = np.asarray(net.conv1.weight.value).copy()
        losses = [model.train_batch([x], [labels[:, None]])[0]
                  for _ in range(3)]
        assert all(np.isfinite(l) for l in losses), losses
        # the step actually trained (3 steps of a fresh BN net need not
        # decrease the loss yet — LeNet covers convergence)
        assert not np.allclose(w_before, np.asarray(net.conv1.weight.value))
        # AMP is engaged: conv compute runs in bf16 inside the traced step
        import jax
        import jax.numpy as jnp
        from paddle_tpu.amp import auto_cast

        params, _ = model._pull_state()

        def fwd(p):
            with auto_cast(level="O1"):
                return nn.functional_call(net, p, jnp.asarray(x),
                                          training=True)

        jaxpr = str(jax.make_jaxpr(fwd)(params))
        assert "bf16" in jaxpr, "O1 autocast left no bf16 compute in the step"
    finally:
        fleet._initialized = False
        fleet._strategy = None
        set_mesh(build_mesh())


def test_lenet_convergence_synthetic_digits():
    """Train LeNet on a synthetic separable 10-class image problem and
    assert the loss drops and accuracy rises — the BASELINE config-1 gate
    (ref: tests/book/test_recognize_digits.py asserts acc within a run)."""
    from paddle_tpu import optimizer as popt
    from paddle_tpu.metric import Accuracy

    paddle.seed(0)
    rng = np.random.RandomState(0)
    n, n_classes = 256, 10
    labels = rng.randint(0, n_classes, (n,))
    # each class lights up one distinct 7x7 quadrant cell + noise
    images = rng.normal(0, 0.3, (n, 1, 28, 28)).astype(np.float32)
    for i, y in enumerate(labels):
        r, c = divmod(int(y), 4)
        images[i, 0, r * 7:(r + 1) * 7, c * 7:(c + 1) * 7] += 2.0

    net = M.LeNet()
    model = paddle.Model(net)
    model.prepare(optimizer=popt.Adam(learning_rate=1e-3),
                  loss=nn.CrossEntropyLoss(), metrics=[Accuracy()])
    first = None
    for epoch in range(6):
        order = rng.permutation(n)
        for start in range(0, n, 64):
            idx = order[start:start + 64]
            loss, _ = model.train_batch([images[idx]], [labels[idx][:, None]])
            if first is None:
                first = loss
    acc = model._metrics[0].accumulate()
    assert loss < first * 0.5, (first, loss)
    assert acc > 0.7, acc


def test_resnet_stem_space_to_depth_exact():
    """stem_space_to_depth rewrites the 7x7/s2 stem as the equivalent
    4x4/s1 conv on 2x2 space-to-depth input (tools/resnet_mfu_analysis.md)
    — same parameters, same math, bit-level parity up to matmul reorder."""
    import jax

    from paddle_tpu.vision.models import resnet18

    paddle.seed(0)
    net1 = resnet18(data_format="NHWC")
    net2 = resnet18(data_format="NHWC", stem_space_to_depth=True)
    net2.set_state_dict(net1.state_dict())
    net1.eval()
    net2.eval()
    import jax.numpy as jnp

    x = jnp.asarray(np.random.RandomState(0).randn(2, 224, 224, 3)
                    .astype(np.float32))
    o1, o2 = np.asarray(net1(x)), np.asarray(net2(x))
    np.testing.assert_allclose(o1, o2, atol=1e-3)
    # grads flow through the re-gathered stem weights
    from paddle_tpu.nn.layer_base import functional_call

    params = {k: v.value for k, v in net2.named_parameters()}
    g = jax.grad(lambda p: functional_call(net2, p, x).sum())(params)
    gw = np.asarray(g["conv1.weight"])
    assert np.abs(gw).sum() > 0

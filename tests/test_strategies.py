"""Strategy-feature tests: recompute (remat), gradient merge, LAMB/LARS
toggles, and honest UnimplementedError for un-built strategies.

Mirrors the reference's meta-optimizer tests, which assert on the rewritten
program (fleet_meta_optimizer_base.py:23 — op/attr inspection); here the
"program" is the jaxpr, so remat is asserted by jaxpr inspection, and
gradient merge by trajectory parity with the equivalent big batch.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.framework.errors import UnimplementedError
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.optimizer.gradient_merge import GradientMergeOptimizer


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


class TestRecompute:
    def test_apply_recompute_wraps_repeated_blocks(self):
        net = GPTForCausalLM(gpt_tiny())
        n = nn.apply_recompute(net)
        assert n == 2  # gpt_tiny has 2 GPTBlocks
        assert all(getattr(b, "_recompute_wrapped", False)
                   for b in net.gpt.blocks)

    def test_jaxpr_contains_remat(self):
        paddle.seed(0)
        net = GPTForCausalLM(gpt_tiny())
        nn.apply_recompute(net)
        ids = jnp.zeros((2, 8), jnp.int32)
        params = net.param_pytree()

        def loss_fn(params):
            logits = nn.functional_call(net, params, ids, training=True)
            return net.loss(logits, ids)

        jaxpr = jax.make_jaxpr(jax.grad(loss_fn))(params)
        assert "remat" in str(jaxpr), "no remat/checkpoint in the grad jaxpr"

    def test_recompute_matches_baseline_numerics(self):
        paddle.seed(0)
        net_a = GPTForCausalLM(gpt_tiny())
        paddle.seed(0)
        net_b = GPTForCausalLM(gpt_tiny())
        nn.apply_recompute(net_b)
        ids = np.random.RandomState(0).randint(0, 128, (2, 8)).astype(np.int32)

        def train(net):
            opt = popt.Adam(learning_rate=1e-2)
            m = paddle.Model(net)
            m.prepare(optimizer=opt, loss=net.loss)
            losses = [m.train_batch([ids], [ids])[0] for _ in range(3)]
            return losses

        np.testing.assert_allclose(train(net_a), train(net_b),
                                   rtol=2e-5, atol=2e-6)

    def test_strategy_recompute_via_fleet(self):
        paddle.seed(0)
        strat = fleet.DistributedStrategy(recompute=True)
        fleet.init(is_collective=True, strategy=strat)
        net = GPTForCausalLM(gpt_tiny())
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=net.loss)
        assert all(getattr(b, "_recompute_wrapped", False)
                   for b in net.gpt.blocks)
        ids = np.random.RandomState(0).randint(0, 128, (8, 8)).astype(np.int32)
        loss, _ = model.train_batch([ids], [ids])
        assert np.isfinite(loss)


class TestGradientMerge:
    def _toy(self):
        paddle.seed(0)
        net = nn.Linear(4, 3)
        x = np.random.RandomState(0).normal(size=(8, 4)).astype(np.float32)
        y = np.random.RandomState(1).normal(size=(8, 3)).astype(np.float32)
        return net, x, y

    def test_merged_matches_big_batch_sgd(self):
        """k micro-steps with GM == one step on the concatenated batch."""
        net, x, y = self._toy()
        loss_fn = nn.MSELoss()

        def run(merge):
            paddle.seed(0)
            net = nn.Linear(4, 3)
            params = net.param_pytree()
            if merge:
                opt = GradientMergeOptimizer(popt.SGD(learning_rate=0.1), k_steps=2)
            else:
                opt = popt.SGD(learning_rate=0.1)
            state = opt.init(params)

            def grads_of(xb, yb, params):
                def f(p):
                    out = nn.functional_call(net, p, xb, training=True)
                    return loss_fn(out, yb)
                return jax.grad(f)(params)

            if merge:
                for xb, yb in ((x[:4], y[:4]), (x[4:], y[4:])):
                    g = grads_of(xb, yb, params)
                    params, state = opt.update(g, state, params, lr=0.1)
            else:
                g = grads_of(x, y, params)
                params, state = opt.update(g, state, params, lr=0.1)
            return params

        merged = run(True)
        big = run(False)
        for k in merged:
            np.testing.assert_allclose(np.asarray(merged[k]),
                                       np.asarray(big[k]), rtol=1e-5, atol=1e-6)

    def test_no_update_mid_cycle(self):
        net, x, y = self._toy()
        params = net.param_pytree()
        opt = GradientMergeOptimizer(popt.SGD(learning_rate=0.1), k_steps=3)
        state = opt.init(params)
        g = {k: jnp.ones_like(v) for k, v in params.items()}
        p1, state = opt.update(g, state, params, lr=0.1)
        for k in params:
            np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(params[k]))
        p2, state = opt.update(g, state, p1, lr=0.1)
        for k in params:
            np.testing.assert_array_equal(np.asarray(p2[k]), np.asarray(params[k]))
        p3, state = opt.update(g, state, p2, lr=0.1)
        for k in params:  # cycle complete: mean grad = 1 → p -= 0.1
            np.testing.assert_allclose(np.asarray(p3[k]),
                                       np.asarray(params[k]) - 0.1, rtol=1e-6)

    def test_inner_count_advances_per_cycle_not_per_micro(self):
        net, _, _ = self._toy()
        params = net.param_pytree()
        opt = GradientMergeOptimizer(popt.Adam(learning_rate=1e-3), k_steps=2)
        state = opt.init(params)
        g = {k: jnp.ones_like(v) for k, v in params.items()}
        _, state = opt.update(g, state, params)
        assert int(state["count"]) == 0  # mid-cycle: no Adam step yet
        _, state = opt.update(g, state, params)
        assert int(state["count"]) == 1  # one Adam step after k micro-steps

    def test_under_fleet_and_jit(self):
        paddle.seed(0)
        strat = fleet.DistributedStrategy(
            gradient_merge=True, gradient_merge_configs={"k_steps": 2})
        fleet.init(is_collective=True, strategy=strat)
        net = GPTForCausalLM(gpt_tiny())
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-2))
        assert isinstance(opt, GradientMergeOptimizer)
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=net.loss)
        ids = np.random.RandomState(0).randint(0, 128, (8, 8)).astype(np.int32)
        w0 = np.asarray(net.gpt.wte.weight.value).copy()
        model.train_batch([ids], [ids])
        w1 = np.asarray(net.gpt.wte.weight.value)
        np.testing.assert_array_equal(w0, w1)  # micro-step 1: accumulate only
        model.train_batch([ids], [ids])
        w2 = np.asarray(net.gpt.wte.weight.value)
        assert not np.array_equal(w1, w2)  # cycle end: params move


class TestOptimizerToggles:
    def test_lamb_toggle_replaces_optimizer(self):
        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy(lamb=True))
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
        assert isinstance(opt, popt.Lamb)

    def test_lars_toggle_replaces_momentum(self):
        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy(lars=True))
        opt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.1, momentum=0.9))
        assert isinstance(opt, popt.Lars)

    def test_lars_toggle_rejects_adam(self):
        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy(lars=True))
        with pytest.raises(Exception, match="Momentum"):
            fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))


class TestUnimplementedStrategies:
    @pytest.mark.parametrize("field", ["a_sync"])
    def test_raises_instead_of_silent_noop(self, field):
        strat = fleet.DistributedStrategy(**{field: True})
        fleet.init(is_collective=True, strategy=strat)
        with pytest.raises(UnimplementedError):
            fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))


class TestFp16AllReduce:
    """strategy.fp16_allreduce — comm-precision gradient reduction
    (ref: fleet/meta_optimizers/fp16_allreduce_optimizer.py:18)."""

    def _train(self, fp16=False, dtype=None, steps=4, seed=0):
        fleet._initialized = False
        cfg = {"dtype": dtype} if dtype else {}
        strategy = fleet.DistributedStrategy(
            fp16_allreduce=fp16, fp16_allreduce_configs=cfg)
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(seed)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.05))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=opt, loss=nn.MSELoss())
        rng = np.random.RandomState(1)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 1).astype(np.float32)
        losses = [float(model.train_batch([x], [y])[0]) for _ in range(steps)]
        return model, np.asarray(losses)

    def test_matches_plain_dp_within_fp16_tolerance(self):
        _, plain = self._train(fp16=False)
        _, comp = self._train(fp16=True)
        # fp16 mantissa on the reduction: close but not bitwise
        np.testing.assert_allclose(comp, plain, rtol=2e-3, atol=2e-3)
        assert comp[-1] < comp[0]

    def test_collective_operand_dtype_is_fp16(self):
        # jaxpr inspection: the cross-replica reduction must consume the
        # COMPRESSED dtype — that is the whole point of the knob
        from paddle_tpu.distributed.fleet.fp16_allreduce import (
            Fp16AllReducePlan)

        fleet._initialized = False
        strategy = fleet.DistributedStrategy(fp16_allreduce=True)
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 1))
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=opt, loss=nn.MSELoss())
        assert isinstance(model._plan, Fp16AllReducePlan)

        x = np.zeros((16, 8), np.float32)
        y = np.zeros((16, 1), np.float32)
        model.train_batch([x], [y])  # builds opt state + compiles
        params, buffers = model._pull_state()
        import jax

        # trace the full train step the model actually runs
        jaxpr = jax.make_jaxpr(_trace_plan, static_argnums=0)(
            model, params, model._opt_state, buffers,
            jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(y))

        sizes = []

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name in ("psum", "pmean", "psum2",
                                          "all_reduce"):
                    for var in eqn.invars:
                        aval = getattr(var, "aval", None)
                        if aval is not None and hasattr(aval, "dtype"):
                            sizes.append(str(aval.dtype))
                for sub in eqn.params.values():
                    if hasattr(sub, "eqns"):
                        walk(sub)
                    elif hasattr(sub, "jaxpr") and hasattr(sub.jaxpr, "eqns"):
                        walk(sub.jaxpr)

        walk(jaxpr.jaxpr)
        assert "float16" in sizes, sizes

    def test_bfloat16_option(self):
        _, losses = self._train(fp16=True, dtype="bfloat16")
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_bad_dtype_rejected(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            fp16_allreduce=True, fp16_allreduce_configs={"dtype": "int8"})
        fleet.init(is_collective=True, strategy=strategy)
        net = nn.Linear(4, 1)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(Exception, match="float16/bfloat16"):
            model.prepare(optimizer=opt, loss=nn.MSELoss())


def _trace_plan(model, p, s, b, k, xx, yy):
    """Re-run the model's actual (plan-wrapped) train step for tracing."""
    return model._train_step(p, s, b, k, 0.1, xx, yy)

"""Auto-checkpoint / preemption resume.

Reference capability: fluid/incubate/checkpoint/auto_checkpoint.py:265
(TrainEpochRange + CheckpointSaver).  Tests: exact-resume training
trajectory, per-N-steps async saves, keep_max pruning, and crash-safety
(meta-less directories are not resumed from).
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.incubate.checkpoint import AutoCheckpoint, train_epoch_range


def _model(seed=0):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    loss = nn.CrossEntropyLoss()
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2), loss=loss)
    return model


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(16, 4).astype(np.float32),
             rng.randint(0, 2, size=(16,)).astype(np.int32))
            for _ in range(n)]


class TestAutoCheckpoint:
    def test_exact_resume_trajectory(self, tmp_path):
        """train 6 steps straight == train 3, kill, resume, train 3 more."""
        data = _batches(6)

        straight = _model(seed=1)
        ref = [straight.train_batch([x], [y])[0] for x, y in data]

        m1 = _model(seed=1)
        acp1 = AutoCheckpoint(m1, os.path.join(tmp_path, "ck"), async_save=False)
        first = [m1.train_batch([x], [y])[0] for x, y in data[:3]]
        acp1.save(epoch=0)
        del m1  # "preempted"

        m2 = _model(seed=2)  # different init — must be overwritten by resume
        acp2 = AutoCheckpoint(m2, os.path.join(tmp_path, "ck"))
        meta = acp2.resume()
        assert meta is not None and meta["epoch"] == 0
        rest = [m2.train_batch([x], [y])[0] for x, y in data[3:]]

        np.testing.assert_allclose(first + rest, ref, rtol=1e-5, atol=1e-6)

    def test_save_steps_and_async(self, tmp_path):
        model = _model()
        d = os.path.join(tmp_path, "ck")
        acp = AutoCheckpoint(model, d, save_steps=2, keep_max=10)
        for x, y in _batches(5):
            model.train_batch([x], [y])
            acp.step(epoch=0)
        acp.close()  # drain async writes
        done = [n for n in os.listdir(d) if n.startswith("ckpt-")]
        assert len(done) == 2  # steps 2 and 4

    def test_keep_max_prunes(self, tmp_path):
        model = _model()
        d = os.path.join(tmp_path, "ck")
        acp = AutoCheckpoint(model, d, keep_max=2, async_save=False)
        for e in range(5):
            acp.epoch_end(e)
        names = sorted(n for n in os.listdir(d) if n.startswith("ckpt-"))
        assert len(names) == 2
        # newest survive
        meta = acp.resume()
        assert meta["epoch"] == 4

    def test_uncommitted_checkpoint_ignored(self, tmp_path):
        model = _model()
        d = os.path.join(tmp_path, "ck")
        acp = AutoCheckpoint(model, d, async_save=False)
        acp.epoch_end(0)
        # simulate a crash mid-write: newer dir without meta
        broken = os.path.join(d, "ckpt-9999999999")
        os.makedirs(broken)
        with open(os.path.join(broken, "m.pdparams"), "wb") as f:
            f.write(b"partial")
        meta = acp.resume()
        assert meta is not None and meta["epoch"] == 0

    def test_fresh_run_returns_none(self, tmp_path):
        model = _model()
        acp = AutoCheckpoint(model, os.path.join(tmp_path, "nope"))
        assert acp.resume() is None

    def test_train_epoch_range_resumes(self, tmp_path):
        d = os.path.join(tmp_path, "ck")
        data = _batches(2)

        m1 = _model(seed=1)
        seen = []
        for epoch, acp in train_epoch_range(4, m1, d):
            seen.append(epoch)
            for x, y in data:
                m1.train_batch([x], [y])
                acp.step(epoch)
            if epoch == 1:
                break  # "preempted" after epoch-1 yield, before its save
        assert seen == [0, 1]

        m2 = _model(seed=1)
        seen2 = []
        for epoch, acp in train_epoch_range(4, m2, d):
            seen2.append(epoch)
            for x, y in data:
                m2.train_batch([x], [y])
                acp.step(epoch)
        assert seen2 == [1, 2, 3]  # epoch 0 completed; 1 was cut short

    def test_mid_epoch_step_save_reenters_epoch(self, tmp_path):
        """A save_steps snapshot mid-epoch must NOT mark the epoch done —
        resume re-enters it (review finding: the rest of the epoch was
        silently skipped before)."""
        d = os.path.join(tmp_path, "ck")
        data = _batches(4)

        m1 = _model(seed=1)
        for epoch, acp in train_epoch_range(3, m1, d, save_steps=2):
            for i, (x, y) in enumerate(data):
                m1.train_batch([x], [y])
                acp.step(epoch)
                if epoch == 1 and i == 1:
                    break  # killed right after the step-2 save of epoch 1
            else:
                continue
            break

        m2 = _model(seed=1)
        seen = []
        for epoch, acp in train_epoch_range(3, m2, d, save_steps=2):
            seen.append(epoch)
            for x, y in data:
                m2.train_batch([x], [y])
                acp.step(epoch)
        assert seen == [1, 2]  # epoch 1 re-entered, not skipped

    def test_resume_rejects_model_mismatch(self, tmp_path):
        d = os.path.join(tmp_path, "ck")
        m1 = _model()
        AutoCheckpoint(m1, d, async_save=False).epoch_end(0)
        paddle.seed(0)
        bigger = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2),
                               nn.Linear(2, 2))
        m2 = paddle.Model(bigger, inputs=["x"], labels=["y"])
        m2.prepare(optimizer=popt.Adam(learning_rate=1e-2),
                   loss=nn.CrossEntropyLoss())
        with pytest.raises(Exception, match="lacks model state"):
            AutoCheckpoint(m2, d).resume()

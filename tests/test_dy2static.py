"""AST-lite dygraph-to-static transpiler (paddle_tpu/dy2static.py).

Parity model: the reference's dygraph_to_static test suite —
dygraph_to_static/test_ifelse.py + ifelse_simple_func.py (data-dependent
branches, one-sided variables, bool-op conditions, class attributes),
test_loop.py (tensor-cond while, tensor-bounded for, conflict vars,
class-var loops).  Each case asserts eager == to_static, the reference's
own acceptance criterion (test_ifelse.py TestDygraphIfElse.test_ast_to_func).

The functions here are freshly written to the same SHAPES as the
reference's cases (same control-flow structure, different bodies) — the
point is covering the transformer's case analysis, not copying tests.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.dy2static import Dy2StaticError, convert_to_static
from paddle_tpu.framework.errors import InvalidArgumentError


def both_ways(fn, *args, paddleisms=False):
    """Run fn eagerly and under jax.jit (the to_static compile path) and
    assert identical results — the reference's own acceptance criterion.
    ``paddleisms=True``: the source uses reference idioms raw jax arrays
    don't speak eagerly (``.numpy()``, ``range(shape-[1] tensor)``), so
    the eager side runs the CONVERTED function's concrete dispatch path
    (mirroring how the reference runs transpiled code in dygraph mode)."""
    conv = convert_to_static(fn)
    eager = (conv if paddleisms else fn)(*args)
    static = jax.jit(conv)(*args)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(static),
                               rtol=1e-6)
    return np.asarray(static)


# ---------------------------------------------------------------------------
# if / else (test_ifelse.py shapes)
# ---------------------------------------------------------------------------
def branch_on_mean(x):
    # shape of ifelse_simple_func.dyfunc_with_if_else
    if x.mean().numpy() > 5:
        x = x - 1
    else:
        x = x + 1
    return x


def branch_plus_concrete_if(x, label=None):
    if x.mean() > 5:
        x = x - 1
    else:
        x = x + 1
    if label is not None:  # plain Python if on a non-tensor
        return (x * label).sum()
    return x


def one_sided_vars(x):
    # shape of dyfunc_with_if_else3: q/z/m/n created inside branches,
    # q read after the if (placeholder semantics for the untaken side)
    y = x + 1
    if x.mean() < 5:
        x = x + 1
        z = x + 2
        q = x + 3
    else:
        y = y + 1
        z = x - 2
        m = x + 2
        n = x + 3
    q = q + 1
    n = q + 2
    x = n
    return x


def nested_branches(x):
    # shape of nested_if_else: three levels, mixed concrete/tensor conds
    feat = x.shape[-1]
    bias = jnp.ones((feat,), x.dtype)
    if x.shape[0] != 16:  # concrete
        bs = x.shape[0]
    if x.mean() < 0:  # tensor
        y = x + bias
        w = jnp.full((feat,), 10.0, x.dtype)
        if y.sum() < 10:  # tensor, nested
            y = jax.nn.relu(y * w)
            if y.mean() < 100:  # tensor, nested twice
                y = jnp.abs(y)
            else:
                y = y - 1
    else:
        y = x - bias
    return y


def if_with_and_or(x, label=None):
    # shape of if_with_and_or: None-checks short-circuit around tensor preds
    bs = x.shape
    if x is not None and (x.mean() > 0 or label is not None) \
            and bs[0] > 1 and True:
        x = x - 1
    else:
        x = x + 1
    if label is not None or bs[0] > 1:
        x = x * 2
    return x


def if_truthy_tensor(x):
    # shape of if_tensor_case: `if tensor:` + concrete for/break inside
    mean = x.mean()
    if mean:  # != 0
        for i in range(0, 10):
            if i > 5:
                x = x + 1
                break
            x = x + 1
    else:
        for i in range(0, 37):
            x = x + 1
            break

    if x.mean() + 1 and mean > -100 and x is not None or 2 > 1:
        x = x - 1

    if not (x.reshape(-1)[0] and (mean * x).reshape(-1)[0]):
        x = x + 1
    return x


def if_with_class_attr_dict(x):
    # shape of NetWithControlFlowIf's constant_vars dict writes
    class Box:
        pass

    box = Box()
    box.cache = {}
    box.cache["bias"] = jnp.ones((x.shape[-1],), x.dtype)
    if x.mean() < 0:
        y = x + box.cache["bias"]
        box.cache["w"] = jnp.full((x.shape[-1],), 10.0, x.dtype)
        y = y * box.cache["w"]
    else:
        y = x - box.cache["bias"]
    return y.sum()


class TestIfElse:
    def test_branch_on_mean_both_sides(self):
        lo = both_ways(branch_on_mean, jnp.ones((4, 2)), paddleisms=True)
        hi = both_ways(branch_on_mean, jnp.ones((4, 2)) * 10,
                       paddleisms=True)
        np.testing.assert_allclose(lo, 2.0)
        np.testing.assert_allclose(hi, 9.0)

    def test_concrete_if_with_return_stays_python(self):
        both_ways(branch_plus_concrete_if, jnp.ones((4, 2)))
        both_ways(branch_plus_concrete_if, jnp.ones((4, 2)),
                  jnp.ones((4, 2)))

    def test_one_sided_vars_taken_branch(self):
        # mean(1.0) < 5 → true branch assigns q; exact parity with eager
        both_ways(one_sided_vars, jnp.ones((3,)))

    def test_one_sided_vars_untaken_branch_placeholder(self):
        # mean(10) > 5 → q was never assigned; the reference feeds a
        # placeholder (data_layer_not_check) — zeros here
        conv = convert_to_static(one_sided_vars)
        out = jax.jit(conv)(jnp.ones((3,)) * 10)
        np.testing.assert_allclose(np.asarray(out), 3.0)  # q=0 → n=0+1+2

    def test_nested_branches(self):
        both_ways(nested_branches, jnp.ones((4, 3)) * -0.5)
        both_ways(nested_branches, jnp.ones((4, 3)) * 0.5)

    def test_bool_ops_short_circuit_none(self):
        both_ways(if_with_and_or, jnp.ones((4, 2)))
        both_ways(if_with_and_or, jnp.ones((4, 2)), 2.0)

    def test_truthy_tensor_and_not(self):
        both_ways(if_truthy_tensor, jnp.ones((2, 2)))
        both_ways(if_truthy_tensor, jnp.zeros((2, 2)))

    def test_class_attr_dict_carry(self):
        both_ways(if_with_class_attr_dict, jnp.ones((2, 3)) * -1)
        both_ways(if_with_class_attr_dict, jnp.ones((2, 3)))

    def test_multi_element_pred_raises(self):
        def f(x):
            if x > 0:  # shape (3,) pred
                x = x + 1
            else:
                x = x - 1
            return x

        with pytest.raises(Dy2StaticError, match="any"):
            jax.jit(convert_to_static(f))(jnp.ones((3,)))


# ---------------------------------------------------------------------------
# while (test_loop.py shapes)
# ---------------------------------------------------------------------------
def while_tensor_cond(x):
    # while_loop_dyfunc
    i = x * 1
    while x < 10:
        i = i + x
        x = x + 1
    return i


def while_no_tensor(x):
    # while_loop_dyfunc_without_tensor — plain Python while
    a = 1
    while not a > 4 and a > 0:
        x = x + 1
        a = a + 1
    return x


def while_conflict_var(x):
    # while_loop_dyfun_with_conflict_var: helper fn + shadowing lambda
    i = x * 1

    def double(y):
        return y * 2

    while x < 6:
        add_fn = lambda x, y: x + y  # noqa: E731
        i = add_fn(i, double(x) / 2)
        x = x + 1
    return i


def while_bool_op(x):
    # while_loop_bool_op2: tensor + Python values mixed in the condition
    i = x * 1
    a = 1
    while x < 10 and (a < 100 or a > 0) or a < -1 or not x > -1:
        i = i + x
        x = x + 1
        a = a + 1
    return i


def while_class_var(x):
    # while_loop_class_var: attribute state carried through the loop
    class Box:
        pass

    box = Box()
    box.a = 3
    box.b = 4
    box.c = 5
    i = x * 1
    while i < 10:
        box.b = jnp.zeros((1,), jnp.float32)
        box.c = box.b + box.a
        i += 1
    return box.c


class TestWhile:
    def test_tensor_cond(self):
        out = both_ways(while_tensor_cond, jnp.zeros((), jnp.int64))
        assert out == 45  # sum(0..9)

    def test_no_tensor_stays_python(self):
        both_ways(while_no_tensor, jnp.zeros(()))

    def test_conflict_var_lambda(self):
        both_ways(while_conflict_var, jnp.zeros((), jnp.float32))

    def test_bool_op_cond(self):
        both_ways(while_bool_op, jnp.zeros((), jnp.int64))

    def test_class_var_attr_carry(self):
        out = both_ways(while_class_var, jnp.zeros((), jnp.int64))
        np.testing.assert_allclose(out, 3.0)

    def test_break_in_tensor_while_not_transpiled(self):
        # break inside a data-dependent while: the pass declines (the
        # documented contract) and the trace hits the concretization error
        # — paddle.jit.to_static wraps it with the actionable message
        def f(x):
            while x < 10:
                x = x + 1
                if x.sum() > 5:
                    break
            return x

        with pytest.raises(jax.errors.TracerBoolConversionError):
            jax.jit(convert_to_static(f))(jnp.zeros(()))
        with pytest.raises(InvalidArgumentError, match="break"):
            paddle.jit.to_static(f)(jnp.zeros(()))


# ---------------------------------------------------------------------------
# for (test_loop.py shapes)
# ---------------------------------------------------------------------------
def for_concrete_range(n):
    # for_loop_dyfunc: ret created inside the loop
    for i in range(n):
        ret = jnp.zeros((1,), jnp.float32) + 2.0
    return ret


def for_use_before_create(n):
    # for_loop_dyfunc2
    for i in range(n):
        if i > 1:
            s = a
        a = 1
    return jnp.zeros((1,), jnp.int32) + s


def for_tensor_bound(mx):
    # for_loop_class_var: range over a tensor, attribute carries
    class Box:
        pass

    box = Box()
    box.a = 3
    box.b = 4
    box.c = 5
    for i in range(mx):
        box.b = jnp.zeros((1,), jnp.float32)
        box.c = box.b + box.a
    return box.c


def var_create_in_for(mx):
    # var_create_in_for_loop
    for i in range(mx):
        ret = jnp.zeros((3, 4), jnp.float64) + 1
    return ret


def nested_for(two, three):
    # nested_for_loop_dyfunc
    for j in range(two):
        for i in range(10):
            a = 2
    for i in range(three):
        b = jnp.zeros((1,), jnp.float32) + a
    return b


def for_accumulate(x, n):
    # the canonical accumulating loop over a tensor bound
    acc = jnp.zeros((), x.dtype)
    for i in range(n):
        acc = acc + x[i]
    return acc


class TestForRange:
    def test_concrete_range(self):
        both_ways(for_concrete_range, 5)

    def test_use_before_create(self):
        # the bound stays a static Python int (jitting it as an argument
        # would make `i` traced and `s = a` read an unassigned var — the
        # reference's placeholder garbage; with a static bound the branch
        # is concrete and semantics are exact)
        conv = convert_to_static(for_use_before_create)
        eager = for_use_before_create(4)
        static = jax.jit(lambda: conv(4))()
        np.testing.assert_allclose(np.asarray(eager), np.asarray(static))

    def test_tensor_bound_attr_carry(self):
        # shape-[1] bound, the reference's fill_constant idiom
        out = both_ways(for_tensor_bound, jnp.asarray([7], jnp.int32),
                        paddleisms=True)
        np.testing.assert_allclose(out, 3.0)

    def test_var_create_in_loop(self):
        both_ways(var_create_in_for, jnp.asarray(3, jnp.int32))

    def test_nested_loops(self):
        both_ways(nested_for, jnp.asarray(2, jnp.int32),
                  jnp.asarray(3, jnp.int32))

    def test_accumulating_tensor_bound(self):
        x = jnp.arange(8.0)
        out = both_ways(for_accumulate, x, jnp.asarray(5, jnp.int32))
        np.testing.assert_allclose(out, 10.0)

    def test_zero_trip_traced_range(self):
        def f(x, n):
            acc = x * 1
            for i in range(n):
                acc = acc + 1
            return acc

        out = jax.jit(convert_to_static(f))(jnp.zeros(()),
                                            jnp.asarray(0, jnp.int32))
        np.testing.assert_allclose(np.asarray(out), 0.0)


def _helper_with_branch(x):
    # an UNDECORATED helper with data-dependent control flow
    if x.mean() > 0:
        y = x - 1
    else:
        y = x + 1
    return y


def calls_helper(x):
    return _helper_with_branch(x).sum()


def _double(fn):
    import functools

    @functools.wraps(fn)
    def w(*a):
        return fn(*a) * 2

    return w


@_double
def decorated_fn(x):
    if x.mean() > 0:
        y = x + 1
    else:
        y = x - 1
    return y.sum() / 2


# ---------------------------------------------------------------------------
# integration with paddle.jit.to_static
# ---------------------------------------------------------------------------
class GatedNet(nn.Layer):
    """NetWithControlFlowIf shape: a Linear + tensor-cond branch over its
    output, nested once."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 3)

    def forward(self, x):
        h = self.fc(x)
        if h.mean() < 0:
            y = h + 1.0
            if y.sum() < 10:
                y = jax.nn.relu(y)
            else:
                y = y - 1.0
        else:
            y = h - 1.0
        return y.sum()


class CountNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(2, 2)

    @paddle.jit.to_static
    def forward(self, x):
        h = self.fc(x)
        steps = jnp.zeros((), jnp.float32)
        while steps < 3:
            h = h * 0.5
            steps = steps + 1
        return h.sum()


class TestToStaticIntegration:
    def test_layer_with_branch(self):
        paddle.seed(0)
        net = GatedNet()
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        eager = float(np.asarray(net(jnp.asarray(x))))
        static_fn = paddle.jit.to_static(net)
        static = float(np.asarray(static_fn(jnp.asarray(x))))
        assert abs(eager - static) < 1e-5

    def test_method_decorator_with_while(self):
        paddle.seed(0)
        net = CountNet()
        x = jnp.ones((2, 2))
        out = float(np.asarray(net(x)))
        # eager reference: disable the translator
        paddle.jit.ProgramTranslator().enable(False)
        try:
            ref = float(np.asarray(net(x)))
        finally:
            paddle.jit.ProgramTranslator().enable(True)
        assert abs(out - ref) < 1e-6

    def test_transformed_source_exposed(self):
        conv = convert_to_static(branch_on_mean)
        assert "run_if" in conv.__d2s_source__

    def test_unchanged_fn_returned_as_is(self):
        def plain(x):
            return x * 2 + 1

        assert convert_to_static(plain) is plain

    def test_return_in_tensor_branch_raises_actionable(self):
        def f(x):
            if x.mean() > 0:
                return x + 1
            return x - 1

        with pytest.raises(InvalidArgumentError, match="return"):
            paddle.jit.to_static(f)(jnp.ones((2,)))

    def test_branch_structure_mismatch_raises_actionable(self):
        def f(x):
            if x.mean() > 0:
                y = jnp.zeros((2, 2))
            else:
                y = jnp.zeros((3,))
            return y.sum()

        with pytest.raises(Dy2StaticError, match="mismatch"):
            jax.jit(convert_to_static(f))(jnp.ones((2,)))

    def test_branch_assigning_non_tensor_raises(self):
        # a str selected by a traced cond can't ride lax.cond — must be a
        # loud refusal, not a silent revert to the pre-branch value
        def f(x):
            mode = "relu"
            if x.mean() > 0:
                mode = "gelu"
                x = x + 1
            else:
                x = x - 1
            return x

        with pytest.raises(Dy2StaticError, match="mode"):
            jax.jit(convert_to_static(f))(jnp.ones((2,)))

    def test_forward_hooks_survive_transpilation(self):
        calls = []

        class HookedNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(2, 2)

            def forward(self, x):
                h = self.fc(x)
                if h.mean() > 0:
                    h = h + 1
                else:
                    h = h - 1
                return h

        paddle.seed(0)
        net = HookedNet()
        net.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1) or out * 2)
        x = jnp.ones((2, 2))
        eager = np.asarray(net(x))
        n_eager = len(calls)
        assert n_eager >= 1
        static = np.asarray(paddle.jit.to_static(net)(x))
        assert len(calls) > n_eager, "post hook did not run under to_static"
        np.testing.assert_allclose(static, eager, rtol=1e-6)

    def test_other_decorators_survive(self):
        conv = convert_to_static(decorated_fn)
        assert conv is not decorated_fn
        out = jax.jit(conv)(jnp.ones((2,)))
        # the @_double decorator must still apply on top of the transform
        np.testing.assert_allclose(np.asarray(out), 4.0)

    def test_undecorated_callee_transforms_via_conv_call(self):
        # program_translator's convert_call: helpers reached FROM the
        # decorated function transform lazily, no decoration needed
        out = jax.jit(convert_to_static(calls_helper))(jnp.ones((3,)) * 4)
        np.testing.assert_allclose(np.asarray(out), 3.0 * 3)  # 4-1 per elt
        out = jax.jit(convert_to_static(calls_helper))(jnp.ones((3,)) * -4)
        np.testing.assert_allclose(np.asarray(out), -3.0 * 3)

    def test_sublayer_with_control_flow_transforms(self):
        class Gate(nn.Layer):
            def forward(self, x):
                if x.mean() > 0:
                    return x * 2
                return x * -1

        class Outer(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 3)
                self.gate = Gate()

            def forward(self, x):
                return self.gate(self.fc(x)).sum()

        # note: Gate.forward has RETURN inside the tensor-if — declined by
        # the pass, actionable error expected
        paddle.seed(0)
        net = Outer()
        with pytest.raises(InvalidArgumentError, match="return"):
            paddle.jit.to_static(net)(jnp.ones((2, 3)))

        class Gate2(nn.Layer):
            def forward(self, x):
                if x.mean() > 0:
                    y = x * 2
                else:
                    y = x * -1
                return y

        class Outer2(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 3)
                self.gate = Gate2()

            def forward(self, x):
                return self.gate(self.fc(x)).sum()

        paddle.seed(0)
        net2 = Outer2()
        x = jnp.asarray(np.random.RandomState(0).randn(2, 3),
                        jnp.float32)
        eager = float(np.asarray(net2(x)))
        static = float(np.asarray(paddle.jit.to_static(net2)(x)))
        assert abs(eager - static) < 1e-5

    def test_closure_helpers_keep_live_cells(self):
        # conv_call must NOT convert closure helpers: a rebuilt function
        # would freeze the cell contents and detach it from later
        # nonlocal mutations (e.g. a schedule-updated lr)
        from paddle_tpu.dy2static import conv_call

        k = {"v": 1.0}
        scale = 1.0

        def make():
            nonlocal scale

            def helper(x):
                return x * scale

            return helper

        helper = make()

        def outer(x):
            return helper(x).sum()

        conv = convert_to_static(outer)
        assert conv_call(helper) is helper  # closure: runs natively
        got1 = float(np.asarray(conv(jnp.ones((2,)))))
        scale = 10.0
        got2 = float(np.asarray(conv(jnp.ones((2,)))))
        assert got1 == 2.0 and got2 == 20.0, (got1, got2)

    def test_set_code_level_prints(self, capsys):
        def g(x):
            if x.mean() > 0:
                x = x + 1
            else:
                x = x - 1
            return x

        paddle.jit.set_code_level(100)
        try:
            convert_to_static(g)
        finally:
            paddle.jit.set_code_level(0)
        assert "run_if" in capsys.readouterr().out

"""fleet.utils (LocalFS, KV server) + fleet.data_generator, including
the generator → native InMemoryDataset ingest integration."""
import io
import os
import urllib.request

import numpy as np
import pytest

from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.data_generator import (
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from paddle_tpu.framework.errors import (
    InvalidArgumentError, UnimplementedError,
)


class TestLocalFS:
    def test_roundtrip(self, tmp_path):
        fs = fleet.LocalFS()
        d = str(tmp_path / "a" / "b")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = os.path.join(d, "x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        dirs, files = fs.ls_dir(d)
        assert files == ["x.txt"] and dirs == []
        fs.mv(f, os.path.join(d, "y.txt"), overwrite=True)
        assert fs.is_file(os.path.join(d, "y.txt"))
        assert fs.list_dirs(str(tmp_path / "a")) == ["b"]
        fs.delete(d)
        assert not fs.is_exist(d)
        assert not fs.need_upload_download()

    def test_touch_exists(self, tmp_path):
        fs = fleet.LocalFS()
        f = str(tmp_path / "t")
        fs.touch(f)
        fs.touch(f, exist_ok=True)
        with pytest.raises(FileExistsError):
            fs.touch(f, exist_ok=False)

    def test_hdfs_raises_with_guidance(self):
        client = fleet.HDFSClient()
        with pytest.raises(UnimplementedError) as ei:
            client.ls_dir("/x")
        assert "hadoop" in str(ei.value)
        assert client.need_upload_download()


class TestKVServer:
    def test_put_get_delete(self):
        from paddle_tpu.distributed.fleet.utils import KVServer

        server = KVServer(0)  # ephemeral port
        port = server.http_server.server_address[1]
        server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            req = urllib.request.Request(f"{base}/rank/0", data=b"host:123",
                                         method="PUT")
            assert urllib.request.urlopen(req).status == 200
            got = urllib.request.urlopen(f"{base}/rank/0").read()
            assert got == b"host:123"
            with pytest.raises(Exception):
                urllib.request.urlopen(f"{base}/rank/9")
            req = urllib.request.Request(f"{base}/rank/0", method="DELETE")
            urllib.request.urlopen(req)
            assert server.http_server.get_deleted_size() == 1
        finally:
            server.stop()


class _WordsLabel(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def local_iter():
            fields = [int(x) for x in line.split()]
            yield [("words", fields[:-1]), ("label", [fields[-1]])]

        return local_iter


class TestDataGenerator:
    def test_multislot_format(self):
        gen = _WordsLabel()
        out = io.StringIO()
        gen.run_from_stdin(source=["1926 8 17 1\n", "3 4 5 0\n"], out=out)
        lines = out.getvalue().splitlines()
        assert lines[0] == "3 1926 8 17 1 1"
        assert lines[1] == "3 3 4 5 1 0"

    def test_string_generator(self):
        class G(MultiSlotStringDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("w", line.split())]

                return it

        out = io.StringIO()
        G().run_from_stdin(source=["a b\n".replace("a", "7").replace(
            "b", "9")], out=out)
        assert out.getvalue() == "2 7 9\n"

    def test_slot_order_enforced(self):
        class Bad(MultiSlotDataGenerator):
            def __init__(self):
                super().__init__()
                self.n = 0

            def generate_sample(self, line):
                def it():
                    self.n += 1
                    if self.n == 1:
                        yield [("a", [1]), ("b", [2])]
                    else:
                        yield [("b", [2]), ("a", [1])]

                return it

        gen = Bad()
        out = io.StringIO()
        with pytest.raises(InvalidArgumentError):
            gen.run_from_stdin(source=["x\n", "y\n"], out=out)

    def test_base_requires_generate_sample(self):
        with pytest.raises(NotImplementedError):
            DataGenerator().run_from_memory(out=io.StringIO())

    def test_feeds_in_memory_dataset(self, tmp_path):
        """End-to-end CTR preprocessing: generator emits fixed-width
        MultiSlot text that the native ingest engine loads and batches
        (data_generator → InMemoryDataset, the reference pipeline)."""
        from paddle_tpu.io import InMemoryDataset

        class Fixed(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    f = [int(v) for v in line.split()]
                    yield [("words", f[:3]), ("label", [f[3]])]

                return it

        part = tmp_path / "part-0.txt"
        out = io.StringIO()
        Fixed().run_from_stdin(
            source=[f"{i} {i+1} {i+2} {i%2}\n" for i in range(8)], out=out)
        # MultiSlot "<len> vals..." with fixed widths → strip the length
        # prefixes into the ingest engine's plain numeric columns
        rows = []
        for line in out.getvalue().splitlines():
            vals = line.split()
            assert vals[0] == "3" and vals[4] == "1"
            rows.append(" ".join(vals[1:4] + vals[5:]))
        part.write_text("\n".join(rows) + "\n")

        ds = InMemoryDataset(slots=[("words", 3, "int64"),
                                    ("label", 1, "int64")])
        ds.set_filelist([str(part)])
        assert ds.load_into_memory(thread_num=2) == 8
        words, label = next(ds.batch_iter(batch_size=8))
        assert words.shape == (8, 3) and label.shape == (8, 1)
        assert set(label[:, 0].tolist()) == {0, 1}

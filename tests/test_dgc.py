"""Deep Gradient Compression strategy.

Reference capability: DGCMomentumOptimizer (fluid/optimizer.py:1129) +
operators/dgc_op.cc.  Assertions are trajectory- and structure-level:
the dense warmup phase must equal plain DP Momentum, sparsity=0 must
reduce to SGD on averaged grads (all momentum mass is flushed every
step), the error-feedback accumulators must hold unsent gradient mass,
and the compiled sparse step must exchange k-sized all-gathers instead
of parameter-sized all-reduces.
"""
import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.optimizer import DGCMomentum


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _data(n=64, d=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    y = x @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
    return x, y


def _net(d=8):
    paddle.seed(0)
    return nn.Sequential(nn.Linear(d, 16), nn.ReLU(), nn.Linear(16, 1))


def _prepare(dgc_configs, lr=0.05, momentum=0.9):
    strat = fleet.DistributedStrategy(dgc=True, dgc_configs=dgc_configs)
    fleet.init(is_collective=True, strategy=strat)
    net = _net()
    opt = fleet.distributed_optimizer(
        popt.Momentum(learning_rate=lr, momentum=momentum))
    assert isinstance(opt, DGCMomentum)
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    return model, net


def _fit(model, x, y, steps):
    return [model.train_batch([x], [y])[0] for _ in range(steps)]


class TestDGCSchedule:
    def test_sparsity_at(self):
        opt = DGCMomentum(rampup_begin_step=2, rampup_step=4,
                          sparsity=[0.75, 0.9375])
        assert opt.sparsity_at(1) is None
        assert opt.sparsity_at(2) is None
        assert opt.sparsity_at(3) == 0.75
        assert opt.sparsity_at(4) == 0.75
        assert opt.sparsity_at(5) == 0.9375
        assert opt.sparsity_at(100) == 0.9375

    def test_validation(self):
        with pytest.raises(InvalidArgumentError):
            DGCMomentum(momentum=1.5)
        with pytest.raises(InvalidArgumentError):
            DGCMomentum(sparsity=[1.0])
        with pytest.raises(InvalidArgumentError, match="Model"):
            DGCMomentum(parameters=_net().parameters()).step({})


class TestDGCTrajectories:
    def test_warmup_matches_dense_momentum_dp(self):
        x, y = _data()

        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy())
        net_ref = _net()
        opt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.05, momentum=0.9))
        m_ref = paddle.Model(net_ref, inputs=["x"], labels=["y"])
        m_ref.prepare(optimizer=opt, loss=nn.MSELoss())
        ref = _fit(m_ref, x, y, 5)
        fleet._initialized = False

        # rampup_begin_step large → every tested step is dense warmup
        m, _ = _prepare({"rampup_begin_step": 100})
        got = _fit(m, x, y, 5)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_warmup_nesterov_and_clip_match_dense_dp(self):
        """grad_clip must apply to the AGGREGATED warmup gradient and
        nesterov must survive the Momentum→DGCMomentum conversion."""
        from paddle_tpu.optimizer import ClipGradByGlobalNorm

        x, y = _data()

        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy())
        net_ref = _net()
        opt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.05, momentum=0.9,
                          use_nesterov=True,
                          grad_clip=ClipGradByGlobalNorm(0.5)))
        m_ref = paddle.Model(net_ref, inputs=["x"], labels=["y"])
        m_ref.prepare(optimizer=opt, loss=nn.MSELoss())
        ref = _fit(m_ref, x, y, 5)
        fleet._initialized = False

        strat = fleet.DistributedStrategy(
            dgc=True, dgc_configs={"rampup_begin_step": 100})
        fleet.init(is_collective=True, strategy=strat)
        net = _net()
        dopt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.05, momentum=0.9,
                          use_nesterov=True,
                          grad_clip=ClipGradByGlobalNorm(0.5)))
        assert isinstance(dopt, DGCMomentum) and dopt._nesterov
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=dopt, loss=nn.MSELoss())
        got = _fit(m, x, y, 5)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_sparsity_zero_is_sgd_on_mean_grads(self):
        """k=n sends everything each step, so u is flushed every step and
        momentum never accumulates — DGC(s=0) == SGD on averaged grads."""
        x, y = _data()

        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy())
        net_ref = _net()
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.05))
        m_ref = paddle.Model(net_ref, inputs=["x"], labels=["y"])
        m_ref.prepare(optimizer=opt, loss=nn.MSELoss())
        ref = _fit(m_ref, x, y, 5)
        fleet._initialized = False

        m, _ = _prepare({"rampup_begin_step": 0, "sparsity": [0.0]})
        got = _fit(m, x, y, 5)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_error_feedback_holds_unsent_mass(self):
        x, y = _data()
        m, _ = _prepare({"rampup_begin_step": 0, "sparsity": [0.9]})
        m.train_batch([x], [y])
        v = m._opt_state["v"]
        # each replica's residual holds the ~90% unsent entries
        leaf = np.asarray(next(iter(v.values())))  # [8, ...]
        assert leaf.shape[0] == 8
        assert np.count_nonzero(leaf) > 0
        # replicas saw different shards → different residuals
        assert not np.allclose(leaf[0], leaf[1])

    def test_converges_at_high_sparsity(self):
        x, y = _data()
        m, _ = _prepare({"rampup_begin_step": 2, "rampup_step": 4,
                         "sparsity": [0.75, 0.9]}, lr=0.05)
        losses = _fit(m, x, y, 50)
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])

    def test_save_load_resets_schedule_mirror(self, tmp_path):
        import os

        x, y = _data()
        m, _ = _prepare({"rampup_begin_step": 0, "sparsity": [0.9]})
        for _ in range(3):
            m.train_batch([x], [y])
        ck = os.path.join(tmp_path, "ck")
        m.save(ck)
        m.train_batch([x], [y])
        m.load(ck)
        assert m._plan._t is None
        m.train_batch([x], [y])
        assert m._plan._t == 4
        assert int(np.asarray(m._opt_state["count"])) == 4


class TestDGCStructure:
    def test_sparse_step_has_no_param_sized_all_reduce(self):
        x, y = _data()
        m, net = _prepare({"rampup_begin_step": 0, "sparsity": [0.9]})
        m.train_batch([x], [y])
        cache = [c.cell_contents for c in m._train_step.__closure__
                 if isinstance(c.cell_contents, dict)][0]
        ((phase, nb), fn), = cache.items()
        assert phase == 0.9
        params, bufs = m._pull_state()
        hlo = fn.lower(params, m._opt_state, bufs, jax.random.PRNGKey(0),
                       jnp.float32(0.05), jnp.asarray(x),
                       jnp.asarray(y)).compile().as_text()
        ar_sizes = [int(np.prod([int(d) for d in s.split(",") if d])) if s
                    else 1
                    for s in re.findall(r"all-reduce[^\n]*f32\[([\d,]*)\]",
                                        hlo)]
        assert not [s for s in ar_sizes if s > 64], ar_sizes
        assert "all-gather" in hlo  # the k-sized sparse exchange

    def test_requires_momentum(self):
        strat = fleet.DistributedStrategy(dgc=True)
        fleet.init(is_collective=True, strategy=strat)
        with pytest.raises(InvalidArgumentError, match="Momentum"):
            fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))

    @pytest.mark.parametrize("other", ["localsgd", "lamb", "lars",
                                       "gradient_merge"])
    def test_rejects_meta_optimizer_combos(self, other):
        strat = fleet.DistributedStrategy(dgc=True, **{other: True})
        fleet.init(is_collective=True, strategy=strat)
        with pytest.raises(InvalidArgumentError, match="compose"):
            fleet.distributed_optimizer(
                popt.Momentum(learning_rate=0.05, momentum=0.9))

    def test_rejects_multi_precision(self):
        strat = fleet.DistributedStrategy(dgc=True)
        fleet.init(is_collective=True, strategy=strat)
        with pytest.raises(InvalidArgumentError, match="multi_precision"):
            fleet.distributed_optimizer(
                popt.Momentum(learning_rate=0.05, momentum=0.9,
                              multi_precision=True))

    def test_rejects_hybrid_mesh(self):
        strat = fleet.DistributedStrategy(dgc=True, mp_degree=2)
        fleet.init(is_collective=True, strategy=strat)
        net = _net()
        opt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.05, momentum=0.9))
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(InvalidArgumentError, match="dgc"):
            m.prepare(optimizer=opt, loss=nn.MSELoss())


class TestDGCRegularizer:
    def test_l1decay_survives_conversion(self):
        """A regularizer object on the source Momentum must reach the
        converted DGCMomentum (weight_decay floats and objects both)."""
        strat = fleet.DistributedStrategy(dgc=True)
        fleet.init(is_collective=True, strategy=strat)
        src = popt.Momentum(learning_rate=0.05, momentum=0.9,
                            weight_decay=paddle.regularizer.L1Decay(0.01))
        dopt = fleet.distributed_optimizer(src)
        assert isinstance(dopt, DGCMomentum)
        assert dopt._regularizer is src._regularizer
        assert dopt._regularizer is not None

"""Promoted 1.x long-tail ops vs transcribed kernel oracles.

References: add_position_encoding_op.h, bpr_loss_op.h, rank_loss_op.h,
margin_rank_loss_op.h, shuffle_channel_op.h, space_to_depth_op.h:41,
fsp_op.h, cvm_op.h, sampling_id_op.h, im2sequence_op.h.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


class TestAddPositionEncoding:
    def test_vs_oracle(self):
        rng = np.random.RandomState(0)
        N, S, E = 2, 5, 8
        x = rng.rand(N, S, E).astype(np.float32)
        alpha, beta = 0.7, 1.3
        out = np.asarray(F.add_position_encoding(x, alpha, beta))
        half = E // 2
        want = np.empty_like(x)
        for n in range(N):
            for j in range(S):
                for k in range(half):
                    val = j / (10000.0 ** (k / (half - 1))) if half > 1 \
                        else j / 10000.0
                    want[n, j, k] = x[n, j, k] * alpha + np.sin(val) * beta
                    want[n, j, half + k] = \
                        x[n, j, half + k] * alpha + np.cos(val) * beta
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


class TestRankingLosses:
    def test_bpr_vs_oracle(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 6).astype(np.float32)
        y = rng.randint(0, 6, (4, 1)).astype(np.int64)
        out = np.asarray(F.bpr_loss(x, y)).ravel()
        for i in range(4):
            s = 0.0
            for j in range(6):
                if j == y[i, 0]:
                    continue
                s += -np.log(1.0 + np.exp(x[i, j] - x[i, y[i, 0]]))
            np.testing.assert_allclose(out[i], -s / 5, rtol=1e-5)

    def test_rank_loss(self):
        lbl = np.array([1.0, 0.0], np.float32)
        l = np.array([0.5, -0.2], np.float32)
        r = np.array([0.1, 0.3], np.float32)
        out = np.asarray(F.rank_loss(lbl, l, r))
        want = np.log(1 + np.exp(l - r)) - lbl * (l - r)
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_margin_rank_loss(self):
        out = np.asarray(F.margin_rank_loss(
            np.array([1.0, -1.0], np.float32),
            np.array([0.5, 0.5], np.float32),
            np.array([0.1, 0.1], np.float32), margin=0.2))
        np.testing.assert_allclose(out, [0.0, 0.6], rtol=1e-6, atol=1e-7)


class TestChannelRearrange:
    def test_shuffle_channel(self):
        x = np.arange(1 * 6 * 2 * 2, dtype=np.float32).reshape(1, 6, 2, 2)
        out = np.asarray(F.shuffle_channel(x, 2))
        # (g=2, n=3) → (n=3, g=2): channels 0,3,1,4,2,5
        np.testing.assert_array_equal(out[0, :, 0, 0],
                                      x[0, [0, 3, 1, 4, 2, 5], 0, 0])

    def test_space_to_depth_vs_index_oracle(self):
        # transcribes space_to_depth_op.h:41 index math
        rng = np.random.RandomState(2)
        N, C, H, W, bs = 2, 3, 4, 6, 2
        x = rng.rand(N, C, H, W).astype(np.float32)
        out = np.asarray(F.space_to_depth(x, bs))
        assert out.shape == (N, C * bs * bs, H // bs, W // bs)
        oc, oh, ow = C * bs * bs, H // bs, W // bs
        for b in range(N):
            for k in range(oc):
                for j in range(oh):
                    for i in range(ow):
                        c2 = k % C
                        off = k // C
                        h2 = j * bs + off // bs
                        w2 = i * bs + off % bs
                        np.testing.assert_allclose(out[b, k, j, i],
                                                   x[b, c2, h2, w2])

    def test_space_to_depth_validates(self):
        with pytest.raises(Exception):
            F.space_to_depth(np.zeros((1, 1, 3, 4), np.float32), 2)


class TestFspCvm:
    def test_fsp_matrix(self):
        rng = np.random.RandomState(3)
        x = rng.rand(2, 3, 4, 5).astype(np.float32)
        y = rng.rand(2, 2, 4, 5).astype(np.float32)
        out = np.asarray(F.fsp_matrix(x, y))
        want = np.einsum("nihw,njhw->nij", x, y) / 20.0
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_cvm(self):
        x = np.array([[3.0, 1.0, 0.5, 0.6]], np.float32)
        out = np.asarray(F.continuous_value_model(x, None, use_cvm=True))
        np.testing.assert_allclose(
            out[0], [np.log(4.0), np.log(2.0) - np.log(4.0), 0.5, 0.6],
            rtol=1e-6)
        out2 = np.asarray(F.continuous_value_model(x, None, use_cvm=False))
        np.testing.assert_allclose(out2[0], [0.5, 0.6])


class TestSamplingAndFills:
    def test_sampling_id_degenerate_rows(self):
        # a one-hot probability row must always sample its hot index
        probs = np.eye(4, dtype=np.float32)
        out = np.asarray(F.sampling_id(probs, seed=7))
        np.testing.assert_array_equal(out, [0, 1, 2, 3])

    def test_fill_like(self):
        ref = np.zeros((5, 3), np.float32)
        out = F.fill_constant_batch_size_like(ref, [1, 4], "float32", 2.5)
        assert out.shape == (5, 4)
        assert float(jnp.max(jnp.abs(out - 2.5))) == 0
        u = F.uniform_random_batch_size_like(ref, [1, 2], seed=3)
        g = F.gaussian_random_batch_size_like(ref, [1, 2], seed=3)
        assert u.shape == (5, 2) and g.shape == (5, 2)


class TestAdaptiveAndMisc:
    def test_adaptive_pool2d(self):
        rng = np.random.RandomState(4)
        x = rng.rand(1, 2, 6, 6).astype(np.float32)
        a = np.asarray(F.adaptive_pool2d(x, 3, "avg"))
        b = np.asarray(F.adaptive_avg_pool2d(x, 3))
        np.testing.assert_allclose(a, b)
        m = np.asarray(F.adaptive_pool2d(x, 3, "max"))
        np.testing.assert_allclose(m, np.asarray(F.adaptive_max_pool2d(x, 3)))
        with pytest.raises(Exception):
            F.adaptive_pool2d(x, 3, "sum")

    def test_affine_channel(self):
        rng = np.random.RandomState(5)
        x = rng.rand(2, 3, 2, 2).astype(np.float32)
        s = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([0.1, 0.2, 0.3], np.float32)
        out = np.asarray(F.affine_channel(x, s, b))
        want = x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_lrn_matches_functional(self):
        rng = np.random.RandomState(6)
        x = rng.rand(1, 8, 4, 4).astype(np.float32)
        from paddle_tpu.nn.functional.norm import local_response_norm

        np.testing.assert_allclose(
            np.asarray(F.lrn(x, n=5, k=2.0, alpha=1e-3)),
            np.asarray(local_response_norm(x, size=5, alpha=1e-3, k=2.0)))

    def test_im2sequence_vs_slices(self):
        rng = np.random.RandomState(7)
        x = rng.rand(1, 2, 5, 5).astype(np.float32)
        out = np.asarray(F.im2sequence(x, filter_size=2, stride=1))
        assert out.shape == (1, 16, 8)
        # row (oh, ow) column order channel-major (c, fh, fw)
        for oh in range(4):
            for ow in range(4):
                patch = x[0, :, oh:oh + 2, ow:ow + 2].reshape(-1)
                np.testing.assert_allclose(out[0, oh * 4 + ow], patch,
                                           rtol=1e-6)


def test_fluid_resolution():
    from paddle_tpu.fluid import layers as fl

    for n in ("bpr_loss", "space_to_depth", "fsp_matrix", "im2sequence",
              "add_position_encoding", "sampling_id"):
        assert getattr(fl, n) is getattr(F, n)

"""Kernel autotuner (ops/autotune.py): keys, caches, counters, and
per-candidate numerical equivalence of every registered kernel.

All on CPU — measured searches are forced with FLAGS_kernel_autotune=
"force" (interpret-mode timing is meaningless as a measurement but
exercises the full search/cache machinery); the off-TPU default path
must resolve to the untimed heuristic.
"""
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.framework import trace_events
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops import autotune
from paddle_tpu.ops.flash_attention import (
    _fwd_tuned,
    _naive_reference,
    flash_attention,
)
from paddle_tpu.ops.fused_conv1x1_bn import _conv1x1_bn_stats
from paddle_tpu.ops.fused_layernorm import _ln_res_measured, layernorm_residual
from paddle_tpu.ops.fused_softmax_xent import (
    _sxent_measured,
    softmax_cross_entropy,
)


@pytest.fixture(autouse=True)
def _clean_tuner_state():
    """Each test starts cold (memory caches, counters, warm flag) and
    leaves the flags at their defaults."""
    autotune.clear_cache()
    autotune.reset_counters()
    autotune.reset_warm()
    yield
    set_flags({"kernel_autotune": "on", "kernel_tuning_cache": ""})
    autotune.clear_cache()
    autotune.reset_counters()
    autotune.reset_warm()


# one tiny registered kernel so cache/counter tests don't depend on the
# real kernels' spaces
_probe = autotune.autotune(
    "test_probe", params=("block",),
    space=lambda x: [{"block": 8}, {"block": 16}],
    heuristic=lambda x: {"block": 8},
)(lambda x, *, block: x * 2)


def _arr(*shape, dtype=np.float32, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


class TestSpaceHelpers:
    def test_tile_candidates_clamped_to_padded_length(self):
        # dim 48: every base clamps to round_up(48, 8) = 48
        assert autotune.tile_candidates(48, base=(128, 256, 512)) == [48]
        # dim 300 with lane multiple: caps at round_up(300, 128) = 384
        cands = autotune.tile_candidates(300, multiple=128,
                                         base=(128, 256, 512, 1024))
        assert cands == [128, 256, 384]
        assert all(c % 128 == 0 for c in cands)

    def test_tile_candidates_rejects_bad_dim(self):
        from paddle_tpu.framework.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            autotune.tile_candidates(0)

    def test_vmem_fits(self):
        assert autotune.vmem_fits(1024)
        assert not autotune.vmem_fits(autotune.VMEM_BYTES)


class TestCacheKey:
    def test_key_stable_and_shape_bucketed(self):
        a = _arr(100, 60)
        b = _arr(120, 64, seed=1)  # same pow2 buckets: (128, 64)
        assert _probe.cache_key(a) == _probe.cache_key(a)
        assert _probe.cache_key(a) == _probe.cache_key(b)
        c = _arr(200, 60)          # bucket (256, 64): distinct entry
        assert _probe.cache_key(a) != _probe.cache_key(c)

    def test_key_varies_with_dtype_and_kwargs(self):
        a32 = _arr(64, 64)
        a16 = _arr(64, 64).astype(jnp.bfloat16)
        assert _probe.cache_key(a32) != _probe.cache_key(a16)
        k1 = _fwd_tuned.cache_key(a32, a32, a32, causal=True, q_offset=0)
        k2 = _fwd_tuned.cache_key(a32, a32, a32, causal=False, q_offset=0)
        assert k1 != k2  # key_kwargs land in the key


class TestResolution:
    def test_off_tpu_defaults_to_heuristic_without_timing(self):
        assert jax.default_backend() != "tpu"
        cfg = _probe.config(_arr(32, 32))
        assert cfg == {"block": 8}
        c = autotune.get_counters("test_probe")
        assert c["heuristic"] == 1 and c["searches"] == 0
        assert c["configs_timed"] == 0
        # second resolution: heuristic-cache hit, still no timing
        _probe.config(_arr(32, 32))
        assert autotune.get_counters("test_probe")["hits"] == 1

    def test_force_mode_searches_and_memoizes(self):
        set_flags({"kernel_autotune": "force", "kernel_tuning_cache": "off"})
        x = _arr(32, 32)
        cfg = _probe.config(x)
        assert cfg in ({"block": 8}, {"block": 16})
        c = autotune.get_counters("test_probe")
        assert c["searches"] == 1 and c["configs_timed"] == 2
        _probe.config(x)
        assert autotune.get_counters("test_probe")["hits"] == 1

    def test_off_mode_never_searches(self):
        set_flags({"kernel_autotune": "off"})
        assert _probe.config(_arr(32, 32)) == {"block": 8}
        assert autotune.get_counters("test_probe")["searches"] == 0

    def test_explicit_override_bypasses_resolution(self):
        out = _probe(_arr(8, 8), block=16)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_arr(8, 8) * 2))
        assert autotune.get_counters("test_probe") == {
            k: 0 for k in autotune._COUNTER_KEYS}

    def test_disk_round_trip(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        set_flags({"kernel_autotune": "force", "kernel_tuning_cache": path})
        x = _arr(64, 16)
        won = _probe.config(x)
        assert autotune.get_counters("test_probe")["searches"] == 1
        data = json.load(open(path))
        assert len(data["entries"]) == 1
        (entry,) = data["entries"].values()
        assert entry["kernel"] == "test_probe" and entry["config"] == won
        # a "restarted process": memory gone, disk stays
        autotune.clear_cache(memory=True, disk=False)
        autotune.reset_counters()
        assert _probe.config(x) == won
        c = autotune.get_counters("test_probe")
        assert c["disk_hits"] == 1 and c["searches"] == 0

    def test_cache_path_flag_forms(self, tmp_path):
        set_flags({"kernel_tuning_cache": "off"})
        assert autotune.cache_path() is None
        set_flags({"kernel_tuning_cache": str(tmp_path / "t.json")})
        assert autotune.cache_path() == str(tmp_path / "t.json")
        set_flags({"kernel_tuning_cache": ""})
        assert autotune.cache_path().endswith(
            os.path.join(".cache", "paddle_tpu", "kernel_tuning.json"))
        from paddle_tpu import sysconfig
        assert sysconfig.kernel_tuning_cache_path() == autotune.cache_path()

    def test_events_published(self):
        seen = []
        cb = lambda site, info: seen.append((tuple(site), dict(info)))  # noqa: E731
        trace_events.register(cb)
        try:
            set_flags({"kernel_autotune": "force",
                       "kernel_tuning_cache": "off"})
            _probe.config(_arr(16, 16))
            _probe.config(_arr(16, 16))
        finally:
            trace_events.unregister(cb)
        kinds = [info["event"] for site, info in seen
                 if site == ("autotune", "test_probe")]
        assert kinds == ["search", "hit"]
        search_info = seen[0][1]
        assert search_info["n_timed"] == 2
        assert search_info["counters"]["searches"] == 1


class TestCandidateEquivalence:
    """Every candidate the space generates must compute the same values
    as the lax reference — a fast winner that changes numerics is a bug
    the tuner must never be able to pick."""

    def test_conv1x1_bn_stats_all_candidates(self):
        x, w = _arr(100, 24), _arr(24, 40, seed=1)
        ref_y = np.asarray(x) @ np.asarray(w)
        from paddle_tpu.ops.fused_conv1x1_bn import conv1x1_bn_stats
        cands = _conv1x1_bn_stats.candidates(x, w)
        assert len(cands) >= 2
        for cfg in cands:
            y, s, q = conv1x1_bn_stats(x, w, **cfg)
            np.testing.assert_allclose(np.asarray(y), ref_y,
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(np.asarray(s), ref_y.sum(0),
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(np.asarray(q), (ref_y ** 2).sum(0),
                                       rtol=1e-4, atol=1e-4)

    def test_layernorm_residual_all_candidates(self):
        from paddle_tpu.nn import functional as F
        x, r = _arr(52, 48), _arr(52, 48, seed=1)
        g = _arr(48, seed=2)
        b = _arr(48, seed=3)
        ref_s = np.asarray(x + r)
        ref_y = np.asarray(F.layer_norm(x + r, (48,), g, b, 1e-5))
        for cfg in _ln_res_measured.candidates(x, r, g, b, epsilon=1e-5):
            s, y = layernorm_residual(x, r, g, b, **cfg)
            np.testing.assert_allclose(np.asarray(s), ref_s,
                                       rtol=1e-6, atol=1e-6)
            np.testing.assert_allclose(np.asarray(y), ref_y,
                                       rtol=1e-5, atol=1e-5)

    def test_softmax_xent_all_candidates(self):
        logits = _arr(36, 200)
        labels = jnp.asarray(
            np.random.RandomState(1).randint(0, 200, 36), jnp.int32)
        ref = -np.take_along_axis(
            np.asarray(jax.nn.log_softmax(logits, -1)),
            np.asarray(labels)[:, None], 1)[:, 0]
        cands = _sxent_measured.candidates(logits, labels)
        assert len(cands) >= 2
        for cfg in cands:
            loss = softmax_cross_entropy(logits, labels, **cfg)
            np.testing.assert_allclose(np.asarray(loss), ref,
                                       rtol=1e-5, atol=1e-5)

    def test_softmax_xent_grad_matches_reference(self):
        logits = _arr(20, 130)
        labels = jnp.asarray(
            np.random.RandomState(1).randint(0, 130, 20), jnp.int32)

        def fused(lg):
            return softmax_cross_entropy(lg, labels, block_m=8,
                                         block_v=128).mean()

        def ref(lg):
            lp = jax.nn.log_softmax(lg, -1)
            return -jnp.take_along_axis(lp, labels[:, None], 1).mean()

        np.testing.assert_allclose(np.asarray(jax.grad(fused)(logits)),
                                   np.asarray(jax.grad(ref)(logits)),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_fwd_all_candidates(self, causal):
        B, H, S, D = 1, 2, 136, 16
        q, k, v = (_arr(B, H, S, D, seed=i) for i in range(3))
        scale = 1.0 / math.sqrt(D)
        ref = np.asarray(_naive_reference(q, k, v, causal, scale))
        cands = _fwd_tuned.candidates(q, k, v, causal=causal,
                                      sm_scale=scale, q_offset=0, kv_len=S)
        assert len(cands) >= 2
        for cfg in cands:
            out = flash_attention(q, k, v, causal=causal, **cfg)
            np.testing.assert_allclose(np.asarray(out), ref,
                                       rtol=2e-5, atol=2e-5)

    def test_flash_grad_with_candidate_blocks(self):
        B, H, S, D = 1, 2, 64, 16
        q, k, v = (_arr(B, H, S, D, seed=i) for i in range(3))
        scale = 1.0 / math.sqrt(D)

        def fused(q, k, v):
            return (flash_attention(q, k, v, causal=True, block_q=48,
                                    block_k=48) ** 2).sum()

        def ref(q, k, v):
            return (_naive_reference(q, k, v, True, scale) ** 2).sum()

        for gf, gr in zip(jax.grad(fused, (0, 1, 2))(q, k, v),
                          jax.grad(ref, (0, 1, 2))(q, k, v)):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=2e-4, atol=2e-4)

    def test_tuned_call_equals_explicit_default(self):
        """The no-argument (tuned) call path must be bit-identical to the
        explicit pre-autotuner defaults on CPU (heuristic == old behavior)."""
        B, H, S, D = 1, 2, 96, 16
        q, k, v = (_arr(B, H, S, D, seed=i) for i in range(3))
        tuned = flash_attention(q, k, v, causal=True)
        explicit = flash_attention(q, k, v, causal=True,
                                   block_q=512, block_k=512)
        assert (np.asarray(tuned) == np.asarray(explicit)).all()


class TestServingHotPath:
    def test_k701_after_warmup_search(self):
        from paddle_tpu.analysis import RetraceMonitor
        set_flags({"kernel_autotune": "force", "kernel_tuning_cache": "off"})
        with RetraceMonitor() as mon:
            autotune.mark_warm()
            _probe.config(_arr(16, 48))  # cold key -> hot-path search
        stats = mon.autotune_stats("test_probe")
        assert stats["counters"]["searches_after_warm"] == 1
        assert stats["warm"] is True
        diags = mon.diagnostics()
        k701 = [d for d in diags if d.rule == "K701"]
        assert len(k701) == 1
        assert "test_probe" in k701[0].message

    def test_no_k701_before_warmup(self):
        from paddle_tpu.analysis import RetraceMonitor
        set_flags({"kernel_autotune": "force", "kernel_tuning_cache": "off"})
        with RetraceMonitor() as mon:
            _probe.config(_arr(16, 48))
        assert not [d for d in mon.diagnostics() if d.rule == "K701"]


class TestProfilerSection:
    def test_summary_section_renders_and_resets(self):
        from paddle_tpu import profiler
        profiler.reset_profiler()
        _probe.config(_arr(24, 24))  # heuristic resolution on CPU
        s = profiler.summary()
        assert "Measured search" in s and "test_probe" in s
        profiler.reset_profiler()
        assert profiler.summary() == ""  # deltas cleared with the rest

"""Reference-Paddle checkpoint importer (framework/paddle_import.py).

Fixtures are generated in the REFERENCE's own formats:
* ProgramDesc bytes come from ``protoc --encode`` against the reference's
  ``framework.proto`` — an encoder completely independent of our wire
  parser;
* tensor streams follow tensor_util.cc TensorToStream /
  lod_tensor.cc:243 byte-for-byte (u32 version, LoD table, desc proto,
  raw data), written by a ~20-line struct packer in this file.

VERDICT r3 #9: a reference-saved LeNet state loads and matches logits.
"""
import os
import shutil
import struct
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.paddle_import import (
    adapt_state_dict, load_reference_state_dict,
    parse_program_persistables, read_lod_tensor_stream)

REF_PROTO = "/root/reference/paddle/fluid/framework/framework.proto"
_DT_CODE = {np.dtype(np.float32): 5, np.dtype(np.int64): 3,
            np.dtype(np.float64): 6, np.dtype(np.int32): 2}


def _desc_bytes(arr: np.ndarray) -> bytes:
    """VarType.TensorDesc wire bytes: field1 varint dtype, field2 repeated
    int64 dims (unpacked, as proto2 emits)."""
    out = bytes([0x08, _DT_CODE[arr.dtype]])
    for d in arr.shape:
        out += bytes([0x10]) + _varint(d)
    return out


def _varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _write_lod_tensor(f, arr: np.ndarray, lod=()):
    f.write(struct.pack("<I", 0))                    # LoDTensor version
    f.write(struct.pack("<Q", len(lod)))             # lod_level
    for level in lod:
        raw = np.asarray(level, np.uint64).tobytes()
        f.write(struct.pack("<Q", len(raw)))
        f.write(raw)
    f.write(struct.pack("<I", 0))                    # Tensor version
    desc = _desc_bytes(arr)
    f.write(struct.pack("<i", len(desc)))
    f.write(desc)
    f.write(np.ascontiguousarray(arr).tobytes())


def _protoc_program(var_entries) -> bytes:
    """Authoritative ProgramDesc bytes via protoc --encode."""
    vars_txt = ""
    for name, shape, persistable in var_entries:
        dims = " ".join(f"dims: {d}" for d in shape)
        vars_txt += f"""
  vars {{
    name: "{name}"
    type {{
      type: LOD_TENSOR
      lod_tensor {{ tensor {{ data_type: FP32 {dims} }} }}
    }}
    persistable: {"true" if persistable else "false"}
  }}"""
    txt = f"""blocks {{
  idx: 0
  parent_idx: -1{vars_txt}
}}"""
    proto_dir = os.path.dirname(REF_PROTO)
    r = subprocess.run(
        ["protoc", f"-I{proto_dir}",
         "--encode=paddle.framework.proto.ProgramDesc",
         os.path.basename(REF_PROTO)],
        input=txt.encode(), capture_output=True, cwd=proto_dir)
    assert r.returncode == 0, r.stderr.decode()
    return r.stdout


needs_protoc = pytest.mark.skipif(
    shutil.which("protoc") is None or not os.path.exists(REF_PROTO),
    reason="protoc / reference proto unavailable")


class TestWireFormats:
    def test_tensor_stream_roundtrip_with_lod(self, tmp_path):
        arr = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        p = tmp_path / "t"
        with open(p, "wb") as f:
            _write_lod_tensor(f, arr, lod=[[0, 2, 3]])
        with open(p, "rb") as f:
            got = read_lod_tensor_stream(f)
        np.testing.assert_array_equal(got, arr)

    def test_int64_and_scalarish_tensors(self, tmp_path):
        for arr in (np.arange(6, dtype=np.int64).reshape(2, 3),
                    np.asarray([7.0], np.float64)):
            p = tmp_path / "t"
            with open(p, "wb") as f:
                _write_lod_tensor(f, arr)
            with open(p, "rb") as f:
                got = read_lod_tensor_stream(f)
            np.testing.assert_array_equal(got, arr)
            assert got.dtype == arr.dtype

    @needs_protoc
    def test_program_parse_against_protoc_encoding(self):
        entries = [("fc_0.w_0", (13, 1), True),
                   ("fc_0.b_0", (1,), True),
                   ("feed", (1,), False)]
        blob = _protoc_program(entries)
        got = parse_program_persistables(blob)
        assert [(v["name"], v["shape"]) for v in got] == \
            [("fc_0.w_0", (13, 1)), ("fc_0.b_0", (1,))]
        assert all(v["dtype"] == np.float32 for v in got)


class TestEndToEnd:
    def _lenet(self):
        paddle.seed(0)
        return nn.Sequential(
            nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
            nn.Flatten(), nn.Linear(16 * 5 * 5, 120), nn.ReLU(),
            nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, 10))

    def test_per_file_checkpoint_loads_and_matches_logits(self, tmp_path):
        net = self._lenet()
        sd = {k: np.asarray(v) for k, v in net.state_dict().items()}
        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        for name, arr in sd.items():
            with open(ckpt / name, "wb") as f:
                _write_lod_tensor(f, arr.astype(arr.dtype))

        loaded = load_reference_state_dict(str(ckpt))
        assert set(loaded) == set(sd)
        net2 = self._lenet()
        # scramble, then restore from the imported dict
        for _, p in net2.named_parameters():
            p.value = p.value * 0.0 + 1.0
        net2.set_state_dict(adapt_state_dict(loaded, net2))
        x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
        net.eval(), net2.eval()
        np.testing.assert_allclose(np.asarray(net(x)), np.asarray(net2(x)),
                                   atol=1e-5)

    @needs_protoc
    def test_combined_params_with_model_proto(self, tmp_path):
        net = self._lenet()
        sd = {k: np.asarray(v) for k, v in net.state_dict().items()}
        # 1.x-style renamed variables, saved combined in sorted-name order
        renamed = {f"param_{i:02d}.w_0": v
                   for i, (k, v) in enumerate(sorted(sd.items()))}
        d = tmp_path / "model_dir"
        d.mkdir()
        with open(d / "__model__", "wb") as f:
            f.write(_protoc_program(
                [(n, v.shape, True) for n, v in renamed.items()]))
        with open(d / "params", "wb") as f:
            for n in sorted(renamed):
                _write_lod_tensor(f, renamed[n])

        loaded = load_reference_state_dict(str(d), params_filename="params")
        assert set(loaded) == set(renamed)
        # shapes in LeNet are all unique → shape-matching maps every param
        net2 = self._lenet()
        for _, p in net2.named_parameters():
            p.value = p.value * 0.0
        net2.set_state_dict(adapt_state_dict(loaded, net2))
        x = np.random.RandomState(1).randn(2, 1, 28, 28).astype(np.float32)
        net.eval(), net2.eval()
        np.testing.assert_allclose(np.asarray(net(x)), np.asarray(net2(x)),
                                   atol=1e-5)

    def test_pickled_2x_state_dict(self, tmp_path):
        import pickle

        sd = {"fc.weight": np.ones((3, 2), np.float32),
              "fc.bias": np.zeros((2,), np.float32)}
        p = tmp_path / "model.pdparams"
        with open(p, "wb") as f:
            pickle.dump(sd, f)
        loaded = load_reference_state_dict(str(p))
        np.testing.assert_array_equal(loaded["fc.weight"], sd["fc.weight"])

    @needs_protoc
    def test_trailing_bytes_rejected(self, tmp_path):
        d = tmp_path / "m"
        d.mkdir()
        with open(d / "__model__", "wb") as f:
            f.write(_protoc_program([("a", (2,), True)]))
        with open(d / "params", "wb") as f:
            _write_lod_tensor(f, np.zeros(2, np.float32))
            f.write(b"junk")
        with pytest.raises(Exception, match="trailing"):
            load_reference_state_dict(str(d), params_filename="params")
